"""Headline benchmark: Llama training-step throughput + MFU on real hardware.

Prints ONE JSON line:
  {"metric": "llama_train_mfu", "value": <mfu %>, "unit": "%MFU",
   "vs_baseline": <mfu / 40.0>, ...extras}

The reference publishes no Llama MFU numbers (BASELINE.md) — the north-star
target is >=40% MFU (reference: release/train_tests/benchmark/ defines only
the harness shape). vs_baseline is measured against that 40% target.

Model size auto-scales to the detected chip's HBM so the benchmark is a real
MXU workload on one chip (the driver runs this single-chip).
"""

import json
import sys
import time


# bf16 peak TFLOP/s per chip, by device_kind substring.
_PEAK_TFLOPS = [
    ("v6e", 918.0), ("v6", 918.0), ("v5p", 459.0), ("v5e", 197.0),
    ("v5", 197.0), ("v4", 275.0), ("v3", 123.0), ("v2", 45.0),
]


def _peak_tflops(device_kind: str) -> float:
    dk = device_kind.lower()
    for key, val in _PEAK_TFLOPS:
        if key in dk:
            return val
    return 100.0  # unknown accelerator: conservative placeholder


def _run_case(cfg, batch, seq, iters, warmup, dev):
    """One timed train-step config; returns (mfu, toks/s, tflops, loss)."""
    import jax
    import jax.numpy as jnp
    from ray_tpu.parallel import mesh as pmesh

    spec = pmesh.MeshSpec(data=1, fsdp=1, tensor=1, context=1)
    m = pmesh.make_mesh(spec, devices=[dev])
    init_fn, step_fn = pmesh.make_train_step(cfg, m)
    with m:
        state = init_fn(jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size,
            dtype=jnp.int32)
        bdict = {"tokens": tokens, "targets": tokens}
        for _ in range(warmup):
            state, metrics = step_fn(state, bdict)
        float(metrics["loss"])  # host fetch: hard sync on remote devices
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step_fn(state, bdict)
        final_loss = float(metrics["loss"])
        dt = time.perf_counter() - t0
    toks_per_s = batch * seq * iters / dt
    achieved_tflops = toks_per_s * cfg.flops_per_token(seq) / 1e12
    peak = _peak_tflops(getattr(dev, "device_kind", dev.platform))
    return (100.0 * achieved_tflops / peak, toks_per_s,
            achieved_tflops, final_loss)


def main():
    import jax
    import jax.numpy as jnp  # noqa: F401
    from ray_tpu.models import llama

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~1.3B params: fits one chip (params+opt state in f32 ~ 15GB is too
        # big for v5e 16G; use bf16 params + f32 adam -> ~13GB. Use 0.8B to
        # be safe across chip generations.)
        # Tuned on v5e (scripts/mfu_sweep.py): 1024^2 flash blocks cut the
        # pallas grid from 32k to 512 invocations (6.1 -> 14.6 TF/s on the
        # kernel); full per-layer remat beats saving attention residuals
        # (residual HBM traffic costs more than the recompute); batch 16 and
        # 2048 blocks OOM. Round-3 sweep: bf16 logits (+0.3pt) and
        # batch 4 x seq 4096 (+1.2pt over 8x2048; b12, b8s4096 regress).
        # 28.9% -> 53.7% -> ~54.8% MFU overall.
        cfg = llama.LlamaConfig(
            vocab_size=32000, dim=2048, n_layers=16, n_heads=16,
            n_kv_heads=16, ffn_dim=5504, max_seq_len=4096,
            attn_impl="flash", attn_block_q=1024, attn_block_k=1024,
            logits_dtype="bfloat16")
        batch, seq, iters, warmup = 4, 4096, 20, 3
    else:
        cfg = llama.tiny(attn_impl="reference")
        batch, seq, iters, warmup = 4, 256, 5, 1

    mfu, toks_per_s, achieved_tflops, final_loss = _run_case(
        cfg, batch, seq, iters, warmup, dev)
    peak = _peak_tflops(getattr(dev, "device_kind", dev.platform))

    out = {
        "metric": "llama_train_mfu",
        "value": round(mfu, 2),
        "unit": "%MFU",
        "vs_baseline": round(mfu / 40.0, 3),
        "tokens_per_s": round(toks_per_s, 1),
        "achieved_tflops": round(achieved_tflops, 2),
        "peak_tflops": peak,
        "device": str(getattr(dev, "device_kind", dev.platform)),
        "model_params_m": round(cfg.num_params() / 1e6, 1),
        "batch": batch, "seq": seq, "final_loss": round(final_loss, 4),
        "timed_iters": iters,
    }

    if on_tpu:
        # TRUE Llama-2-7B layer shapes (dim 4096 / ffn 11008 / 32 heads
        # / 32000 vocab) — the north star names 7B, and small-model MFU
        # can flatter. The full 7B train state (f32 adam moments) can't
        # fit one 16GB chip, so this runs 4 full-width layers: exactly
        # the per-host shard a 7B fsdp-8 run places per chip, same MXU
        # tile shapes, honest per-config FLOPs accounting.
        cfg7 = llama.llama2_7b(
            n_layers=4, attn_impl="flash",
            attn_block_q=1024, attn_block_k=1024,
            logits_dtype="bfloat16")
        try:
            mfu7, tps7, tf7, _ = _run_case(cfg7, 4, 4096, 20, 3, dev)
            out["mfu_7b_shapes"] = round(mfu7, 2)
            out["tokens_per_s_7b_shapes"] = round(tps7, 1)
            out["achieved_tflops_7b_shapes"] = round(tf7, 2)
            out["config_7b_shapes"] = ("dim4096/ffn11008/h32/vocab32k/"
                                       "4 full-width layers, b4 s4096")
        except Exception as e:  # noqa: BLE001 — headline still reports
            out["mfu_7b_shapes_error"] = f"{type(e).__name__}: {e}"[:200]

    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # never leave the driver without a JSON line
        print(json.dumps({"metric": "llama_train_mfu", "value": 0.0,
                          "unit": "%MFU", "vs_baseline": 0.0,
                          "error": f"{type(e).__name__}: {e}"[:300]}))
        sys.exit(0)
