"""ray_tpu: a TPU-native distributed AI framework.

Capability parity with the reference (Ray) — tasks/actors/objects/placement
groups under a Python API, plus Train/Data/Tune/Serve libraries — re-designed
for TPU pods: gang-scheduled slices, SPMD meshes, XLA collectives over ICI,
Pallas kernels for the hot ops.
"""

from ray_tpu.version import __version__

# Heavy submodules (runtime, jax) are imported lazily so `import ray_tpu`
# stays cheap for CLI tools.
_API = (
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "timeline", "ObjectRef", "ObjectRefGenerator",
    "ActorHandle",
    "free", "get_async", "placement_group", "remove_placement_group",
    "PlacementGroup",
    # exceptions (the reference exports these at top level too)
    "RayTpuError", "TaskError", "ActorError", "ActorDiedError",
    "WorkerCrashedError", "ObjectLostError", "GetTimeoutError",
)


def __getattr__(name):
    if name in _API:
        try:
            from ray_tpu import api
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"ray_tpu.{name} is unavailable: {e}") from e
        return getattr(api, name)
    if name in ("util", "train", "data", "serve", "tune", "models", "ops",
                "parallel", "api", "runtime", "dag", "llm",
                "job_submission", "rllib"):
        import importlib
        try:
            return importlib.import_module(f"ray_tpu.{name}")
        except ModuleNotFoundError as e:
            raise AttributeError(
                f"ray_tpu.{name} is unavailable: {e}") from e
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
