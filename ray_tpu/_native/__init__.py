"""Native (C++) runtime components, built on demand with the system
toolchain and loaded via ctypes — no pybind11/pip dependency. Every
native path has a pure-Python fallback; set RAY_TPU_NATIVE=0 to force
the fallbacks.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_dir() -> str:
    d = os.environ.get("RAY_TPU_NATIVE_CACHE") or os.path.join(
        tempfile.gettempdir(),
        f"ray_tpu_native-py{sys.version_info[0]}{sys.version_info[1]}")
    os.makedirs(d, exist_ok=True)
    return d


def _ensure_built() -> Optional[str]:
    src = os.path.join(_HERE, "ringbuf.cc")
    out = os.path.join(_build_dir(), "libray_tpu_ringbuf.so")
    try:
        if os.path.exists(out) and \
                os.path.getmtime(out) >= os.path.getmtime(src):
            return out
        tmp = out + f".tmp{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
             "-o", tmp, src],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except (OSError, subprocess.SubprocessError):
        return None


def load_ringbuf() -> Optional[ctypes.CDLL]:
    """The compiled ring library, or None (caller falls back to
    Python). Compilation happens once per machine/python; concurrent
    builders race benignly via the atomic rename."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("RAY_TPU_NATIVE", "1") in ("0", "false", "off"):
        return None
    path = _ensure_built()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    u64, u8p = ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8)
    lib.rb_write.argtypes = [u8p, u64, u64, ctypes.c_char_p, u64,
                             ctypes.c_uint8, ctypes.c_double]
    lib.rb_write.restype = ctypes.c_int
    lib.rb_read.argtypes = [u8p, u64, u64, u8p, u64,
                            ctypes.POINTER(ctypes.c_uint8),
                            ctypes.POINTER(u64), ctypes.c_double]
    lib.rb_read.restype = ctypes.c_int64
    lib.rb_wait_readable.argtypes = [u8p, u64, u64, ctypes.c_double]
    lib.rb_wait_readable.restype = ctypes.c_int64
    lib.rb_release.argtypes = [u8p]
    lib.rb_release.restype = None
    lib.rb_has_space.argtypes = [u8p, u64]
    lib.rb_has_space.restype = ctypes.c_int
    lib.rb_wait_space.argtypes = [u8p, u64, ctypes.c_double]
    lib.rb_wait_space.restype = ctypes.c_int
    lib.rb_publish_write.argtypes = [u8p]
    lib.rb_publish_write.restype = None
    lib.rb_wake_readers.argtypes = [u8p]
    lib.rb_wake_readers.restype = None
    lib.rb_wake_writers.argtypes = [u8p]
    lib.rb_wake_writers.restype = None
    _LIB = lib
    return lib
