// SPSC shared-memory ring operations for ray_tpu.dag.channel.
//
// Native counterpart of the Python ShmRingChannel (same segment layout:
// 128-byte header with write_seq at offset 0 and read_seq at offset 64,
// then nslots * (8-byte slot header [u32 len | u8 kind | 3B pad] +
// slot_bytes payload)). The reference implements its channel/plasma hot
// paths in C++ for the same reasons this exists
// (src/ray/object_manager/plasma/*, experimental channels):
//   - real atomics with acquire/release ordering (the Python impl
//     documents an x86-TSO assumption; this is portable),
//   - FUTEX-backed blocking waits: consumers/producers sleep in the
//     kernel and are woken by the peer's store — no polling loop at
//     all, which beats sleep-poll at every core count (critically on
//     small hosts where a spinner starves the peer off the CPU),
//   - memcpy at C speed for the copy path.
//
// Exposed as a plain C ABI for ctypes — no pybind11 dependency. ctypes
// releases the GIL around calls, so blocked waiters don't stall their
// process's other Python threads.

#include <atomic>
#include <chrono>
#include <climits>
#include <cstdint>
#include <cstring>
#include <thread>

#if defined(__linux__)
#include <linux/futex.h>
#include <sys/syscall.h>
#include <sys/time.h>
#include <unistd.h>
#endif

namespace {

constexpr uint64_t HDR = 128;
constexpr uint64_t SLOT_HDR = 8;

inline std::atomic<uint64_t>* wseq(uint8_t* base) {
    return reinterpret_cast<std::atomic<uint64_t>*>(base);
}
inline std::atomic<uint64_t>* rseq(uint8_t* base) {
    return reinterpret_cast<std::atomic<uint64_t>*>(base + 64);
}

inline uint8_t* slot_ptr(uint8_t* base, uint64_t seq, uint64_t nslots,
                         uint64_t slot_bytes) {
    return base + HDR + (seq % nslots) * (SLOT_HDR + slot_bytes);
}

#if defined(__linux__)
// Wait until *word != seen (32-bit view of the peer's sequence counter;
// increments always change the low word except at the 2^32 wrap, which
// the re-check loop survives as a spurious wake).
inline void futex_wait_u32(void* word, uint32_t seen, double timeout_s) {
    timespec ts;
    timespec* tsp = nullptr;
    if (timeout_s >= 0) {
        ts.tv_sec = static_cast<time_t>(timeout_s);
        ts.tv_nsec = static_cast<long>((timeout_s - ts.tv_sec) * 1e9);
        tsp = &ts;
    }
    syscall(SYS_futex, word, FUTEX_WAIT, seen, tsp, nullptr, 0);
}

inline void futex_wake_all(void* word) {
    syscall(SYS_futex, word, FUTEX_WAKE, INT_MAX, nullptr, nullptr, 0);
}
#endif

// Wait for cond(); `watch` is the atomic whose change signals progress.
template <typename Cond>
bool wait_on(std::atomic<uint64_t>* watch, Cond cond, double timeout_s) {
    // Short PAUSE-spin first: the no-contention fast path never enters
    // the kernel.
    for (int i = 0; i < 128; i++) {
        if (cond()) return true;
#if defined(__x86_64__) || defined(__i386__)
        __builtin_ia32_pause();
#endif
    }
    using clock = std::chrono::steady_clock;
    auto deadline = clock::now();
    if (timeout_s >= 0)
        deadline += std::chrono::duration_cast<clock::duration>(
            std::chrono::duration<double>(timeout_s));
    for (;;) {
        if (cond()) return true;
        double remaining = -1.0;
        if (timeout_s >= 0) {
            auto left = std::chrono::duration<double>(
                deadline - clock::now()).count();
            if (left <= 0) return false;
            remaining = left;
        }
#if defined(__linux__)
        uint32_t seen = static_cast<uint32_t>(
            watch->load(std::memory_order_acquire));
        if (cond()) return true;
        // Cap each kernel wait: a NON-native peer (pure-Python fallback
        // in the other process) publishes without a futex wake, so we
        // must re-check periodically — 50ms of kernel sleep costs ~0 CPU.
        futex_wait_u32(watch, seen,
                       remaining < 0 ? 0.05
                                     : (remaining < 0.05 ? remaining
                                                         : 0.05));
#else
        std::this_thread::sleep_for(std::chrono::microseconds(50));
#endif
    }
}

inline void publish(std::atomic<uint64_t>* seq_word, uint64_t next) {
    seq_word->store(next, std::memory_order_release);
#if defined(__linux__)
    futex_wake_all(seq_word);
#endif
}

inline void futex_wake_hint(std::atomic<uint64_t>* seq_word) {
#if defined(__linux__)
    futex_wake_all(seq_word);
#else
    (void)seq_word;
#endif
}

}  // namespace

extern "C" {

// returns 0 ok, -1 timeout, -2 payload too large
int rb_write(uint8_t* base, uint64_t nslots, uint64_t slot_bytes,
             const uint8_t* payload, uint64_t n, uint8_t kind,
             double timeout_s) {
    if (n > slot_bytes) return -2;
    uint64_t seq = wseq(base)->load(std::memory_order_relaxed);
    if (!wait_on(
            rseq(base),
            [&] {
                return seq - rseq(base)->load(std::memory_order_acquire)
                    < nslots;
            },
            timeout_s))
        return -1;
    uint8_t* s = slot_ptr(base, seq, nslots, slot_bytes);
    uint32_t len = static_cast<uint32_t>(n);
    std::memcpy(s, &len, 4);
    s[4] = kind;
    if (n) std::memcpy(s + SLOT_HDR, payload, n);
    publish(wseq(base), seq + 1);
    return 0;
}

// returns payload length >= 0 on success (kind in *kind_out),
// -1 timeout, -3 output buffer too small (*n_needed holds the required
// size; the frame is NOT consumed).
int64_t rb_read(uint8_t* base, uint64_t nslots, uint64_t slot_bytes,
                uint8_t* out, uint64_t out_cap, uint8_t* kind_out,
                uint64_t* n_needed, double timeout_s) {
    uint64_t seq = rseq(base)->load(std::memory_order_relaxed);
    if (!wait_on(
            wseq(base),
            [&] {
                return wseq(base)->load(std::memory_order_acquire) > seq;
            },
            timeout_s))
        return -1;
    uint8_t* s = slot_ptr(base, seq, nslots, slot_bytes);
    uint32_t len;
    std::memcpy(&len, s, 4);
    if (len > out_cap) {
        *n_needed = len;
        return -3;
    }
    *kind_out = s[4];
    if (len) std::memcpy(out, s + SLOT_HDR, len);
    publish(rseq(base), seq + 1);
    return static_cast<int64_t>(len);
}

// Wait until data is available WITHOUT consuming it; returns the byte
// offset of the slot header within the segment, or -1 on timeout. The
// caller reads the frame in place and then calls rb_release. (Backs the
// zero-copy path: the wait happens GIL-free in native code, the view
// stays in Python.)
int64_t rb_wait_readable(uint8_t* base, uint64_t nslots,
                         uint64_t slot_bytes, double timeout_s) {
    uint64_t seq = rseq(base)->load(std::memory_order_relaxed);
    if (!wait_on(
            wseq(base),
            [&] {
                return wseq(base)->load(std::memory_order_acquire) > seq;
            },
            timeout_s))
        return -1;
    return static_cast<int64_t>(
        HDR + (seq % nslots) * (SLOT_HDR + slot_bytes));
}

void rb_release(uint8_t* base) {
    uint64_t seq = rseq(base)->load(std::memory_order_relaxed);
    publish(rseq(base), seq + 1);
}

int rb_has_space(uint8_t* base, uint64_t nslots) {
    return wseq(base)->load(std::memory_order_relaxed) -
               rseq(base)->load(std::memory_order_acquire) < nslots
           ? 1 : 0;
}

// Blocking wait for a free slot WITHOUT writing (the zero-copy producer
// serializes straight into the slot from Python, then calls
// rb_publish_write). 0 ok, -1 timeout.
int rb_wait_space(uint8_t* base, uint64_t nslots, double timeout_s) {
    uint64_t seq = wseq(base)->load(std::memory_order_relaxed);
    return wait_on(
               rseq(base),
               [&] {
                   return seq - rseq(base)->load(
                              std::memory_order_acquire) < nslots;
               },
               timeout_s)
           ? 0 : -1;
}

// Publish + futex-wake after a Python-side slot fill. Mixed-path rings
// (native reader, Python zero-copy writer) need these so sleeping
// native waiters wake immediately instead of at the futex re-check cap.
void rb_publish_write(uint8_t* base) {
    uint64_t seq = wseq(base)->load(std::memory_order_relaxed);
    publish(wseq(base), seq + 1);
}

void rb_wake_readers(uint8_t* base) { futex_wake_hint(wseq(base)); }
void rb_wake_writers(uint8_t* base) { futex_wake_hint(rseq(base)); }

}  // extern "C"
