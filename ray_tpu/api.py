"""Public API: init/remote/get/put/wait, actors, placement groups.

The analog of the reference's Python core API (reference:
python/ray/_private/worker.py:1406 init, :3494 remote, :2835 get,
:3018 put, :3089 wait; actor.py:1445 ActorClass; util/placement_group.py).
A driver `init()` starts an in-process head (control service) + node agent
on a dedicated IO thread and spawns worker subprocesses; `init(address=)`
joins an existing cluster. Worker processes attach through
`_attach_existing` so tasks can submit subtasks and use objects.
"""

from __future__ import annotations

import asyncio
import atexit
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ray_tpu.config import Config, set_config
from ray_tpu.runtime import rpc
from ray_tpu.runtime.core import (ActorDiedError, ActorError, CoreContext,
                                  GetTimeoutError, ObjectLostError,
                                  ObjectRef, RayTpuError, TaskError,
                                  WorkerCrashedError)
from ray_tpu.runtime.ids import ActorID, JobID, NodeID, PlacementGroupID

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "get", "put", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "timeline", "ObjectRef", "ObjectRefGenerator",
    "ActorHandle",
    "placement_group", "remove_placement_group", "PlacementGroup",
    "get_async", "free", "RayTpuError", "TaskError", "ActorError",
    "ActorDiedError", "WorkerCrashedError", "ObjectLostError",
    "GetTimeoutError",
]


def _driver_pythonpath() -> str:
    """Workers can import what the driver can (the reference propagates
    driver code paths through the job config / runtime envs)."""
    import sys
    entries = [p if p else os.getcwd() for p in sys.path]
    return ":".join(dict.fromkeys(entries))


class _Global:
    def __init__(self):
        self.ctx: Optional[CoreContext] = None
        self.elt: Optional[rpc.EventLoopThread] = None
        self.head = None            # in-process ControlService (head driver)
        self.agent = None           # in-process NodeAgent
        self.owns_loop = False      # driver owns elt; workers reuse theirs
        self.job_id: Optional[JobID] = None
        self.namespace = "default"
        self.job_runtime_env = None  # init(runtime_env=...) job default
        self.ctx_loop = None        # worker mode: the process's asyncio loop

    @property
    def initialized(self):
        return self.ctx is not None


_g = _Global()


def _run(coro, timeout=None):
    """Bridge sync API -> runtime event loop."""
    if _g.elt is not None:
        return _g.elt.run(coro, timeout)
    # Worker process: the runtime loop is the process's asyncio loop.
    loop = _g.ctx_loop
    cur = None
    try:
        cur = asyncio.get_running_loop()
    except RuntimeError:
        pass
    if cur is loop:
        raise RuntimeError(
            "blocking ray_tpu API called from the event loop; use the "
            "async variants (await ref / ray_tpu.get_async) in async actors")
    return asyncio.run_coroutine_threadsafe(coro, loop).result(timeout)


def is_initialized() -> bool:
    return _g.initialized


def _norm_addr(address: str) -> tuple:
    """(resolved-ip, port) so 'localhost:6379' == '127.0.0.1:6379'."""
    import socket
    host, port = address.rsplit(":", 1)
    try:
        host = socket.gethostbyname(host)
    except OSError:
        pass
    return (host, port)


def _local_cli_node(address: str) -> Optional[dict]:
    """Info for a `ray-tpu start`ed node on this host joined to the
    cluster at `address`, or None. The session dir is this host's record
    of its own node processes, so a hit proves same-machine shm access."""
    import json

    from ray_tpu.scripts import _node_files
    target = _norm_addr(address)
    for f in reversed(_node_files()):
        try:
            with open(f) as fh:
                info = json.load(fh)
            if (_norm_addr(info.get("address", "")) == target
                    and "agent_addr" in info):
                os.kill(info["pid"], 0)  # still running?
                return info
        except (OSError, ValueError, KeyError):
            continue
    return None


def init(address: Optional[str] = None, *,
         num_cpus: Optional[float] = None,
         resources: Optional[Dict[str, float]] = None,
         labels: Optional[Dict[str, str]] = None,
         runtime_env: Optional[dict] = None,
         namespace: str = "default",
         config: Optional[Config] = None,
         system_config: Optional[dict] = None,
         ignore_reinit_error: bool = False) -> dict:
    """Start a local cluster (head + one agent + workers) or join an
    existing one via ``address="host:port"``."""
    if _g.initialized:
        if ignore_reinit_error:
            return {"address": f"{_g.ctx.head_addr[0]}:{_g.ctx.head_addr[1]}"}
        raise RuntimeError("ray_tpu.init() called twice")
    cfg = config or Config.from_env()
    cfg.update(system_config)
    set_config(cfg)
    _g.namespace = namespace
    from ray_tpu.runtime import runtime_env as _rt
    _g.job_runtime_env = _rt.validate(runtime_env)
    _g.elt = rpc.EventLoopThread()
    _g.owns_loop = True
    session_id = uuid.uuid4().hex[:16]

    async def _boot():
        from ray_tpu.runtime.agent import NodeAgent
        from ray_tpu.runtime.control import ControlService
        if address is None:
            head = ControlService(cfg)
            head_addr = await head.start(cfg.head_host, cfg.head_port)
            _g.head = head
        else:
            host, port = address.rsplit(":", 1)
            head_addr = (host, int(port))
            # verify reachable
            pool = rpc.ConnectionPool()
            await pool.call(head_addr, "ping", timeout=cfg.rpc_connect_timeout_s)
            await pool.close()
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if "CPU" not in res:
            res["CPU"] = float(os.cpu_count() or 1)
        sid = session_id
        if address is not None:
            # join: session id is cluster-wide (head KV)
            pool = rpc.ConnectionPool()
            existing = await pool.call(head_addr, "kv_get",
                                       key="__session_id")
            if existing:
                sid = existing.decode()
            await pool.close()
            # A `ray-tpu start`ed node on THIS host serving this cluster?
            # Attach to its agent (the reference driver attaches to the
            # local raylet) instead of booting a second agent that would
            # double-count the machine's resources. Only when the caller
            # didn't ask for specific node resources — those need an
            # agent of our own to advertise them.
            local = None
            if num_cpus is None and not resources and not labels:
                local = _local_cli_node(address)
            if local is not None:
                try:
                    pool = rpc.ConnectionPool()
                    try:
                        host, port = local["agent_addr"].rsplit(":", 1)
                        agent_addr = (host, int(port))
                        await pool.call(agent_addr, "ping",
                                        timeout=cfg.rpc_connect_timeout_s)
                    finally:
                        await pool.close()
                    from ray_tpu.runtime.ids import NodeID
                    ctx = CoreContext(
                        head_addr, agent_addr,
                        NodeID(bytes.fromhex(local["node_id"])),
                        sid, config=cfg, is_driver=True)
                    await ctx.start()
                    job_id = JobID.generate()
                    await ctx.pool.call(head_addr, "register_job",
                                        job_id=job_id,
                                        metadata={"driver_pid": os.getpid()})
                    _g.job_id = job_id
                    return ctx
                except Exception:
                    # Stale session record (killed node, recycled pid):
                    # fall through to booting our own agent, the
                    # pre-attach behavior.
                    pass
        agent = NodeAgent(head_addr, resources=res, labels=labels,
                          config=cfg, session_id=sid,
                          env_extra={"PYTHONPATH": _driver_pythonpath()})
        agent_addr = await agent.start()
        _g.agent = agent
        if address is None:
            await agent.pool.call(head_addr, "kv_put", key="__session_id",
                                  value=sid.encode())
        ctx = CoreContext(head_addr, agent_addr, agent.node_id, sid,
                          config=cfg, is_driver=True)
        await ctx.start()
        job_id = JobID.generate()
        await ctx.pool.call(head_addr, "register_job", job_id=job_id,
                            metadata={"driver_pid": os.getpid()})
        _g.job_id = job_id
        return ctx

    _g.ctx = _g.elt.run(_boot(), timeout=120)
    atexit.register(shutdown)
    return {"address": f"{_g.ctx.head_addr[0]}:{_g.ctx.head_addr[1]}",
            "session_id": _g.ctx.session_id, "node_id": _g.ctx.node_id}


def _attach_existing(ctx: CoreContext) -> None:
    """Called inside worker processes: adopt the worker's CoreContext and
    its running loop as this process's API backend."""
    _g.ctx = ctx
    _g.elt = None
    _g.ctx_loop = asyncio.get_running_loop()


def shutdown() -> None:
    if not _g.initialized:
        return
    try:
        atexit.unregister(shutdown)
    except Exception:
        pass
    ctx, elt = _g.ctx, _g.elt
    _g.ctx = None
    if elt is None:
        return
    try:
        if _g.job_id is not None:
            elt.run(ctx.pool.call(ctx.head_addr, "finish_job",
                                  job_id=_g.job_id), timeout=5)
    except Exception:
        pass
    try:
        elt.run(ctx.stop(), timeout=10)
    except Exception:
        pass
    for svc in (_g.agent, _g.head):
        if svc is not None:
            try:
                elt.run(svc.stop(), timeout=10)
            except Exception:
                pass
    _g.agent = _g.head = None
    elt.stop()
    _g.elt = None


def _require_init():
    if not _g.initialized:
        init()
    return _g.ctx


# --- objects ----------------------------------------------------------------

def put(value: Any) -> ObjectRef:
    ctx = _require_init()
    from ray_tpu.runtime.ids import ObjectID
    from ray_tpu.runtime.serialization import serialize
    ser = serialize(value)
    if ser.total_bytes <= ctx.config.inline_object_max_bytes:
        # Inline object: resolve in the caller's thread; nobody can be
        # awaiting a ref that hasn't been returned yet, so no loop hop.
        oid = ObjectID.generate()
        ctx.store.resolve(oid, frame=ser.to_bytes())
        return ObjectRef(oid, ctx.addr, ser.total_bytes)
    return _run(ctx.put_serialized(ser))


def get(refs, timeout: Optional[float] = None):
    ctx = _require_init()
    single = isinstance(refs, ObjectRef)
    # Materialize once: generator inputs must not be consumed twice.
    ref_list = [refs] if single else list(refs)
    if not ref_list:
        return []
    # Fast path: every ref already resolved inline in this process — load
    # on the caller's thread, no event-loop round trip.
    values = []
    for r in ref_list:
        hit, v = ctx.try_get_local(r)
        if not hit:
            break
        values.append(v)
    else:
        return values[0] if single else values
    wait_budget = None if timeout is None else timeout + 10
    # Capture task-context HERE: run_coroutine_threadsafe runs the
    # coroutine in a fresh context on the loop, so the executing-task
    # contextvar (tracing.current_span) is only visible on this thread.
    from ray_tpu.util import tracing
    in_task = not ctx.is_driver and bool(tracing.current_span.get())
    return _run(ctx.get(refs if single else ref_list, timeout,
                        in_task=in_task),
                timeout=wait_budget)


async def get_async(refs, timeout: Optional[float] = None):
    return await _g.ctx.get(refs, timeout)


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None):
    ctx = _require_init()
    if num_returns > len(refs):
        raise ValueError("num_returns > len(refs)")
    from ray_tpu.util import tracing
    in_task = not ctx.is_driver and bool(tracing.current_span.get())
    return _run(ctx.wait(refs, num_returns, timeout, in_task=in_task))


def free(refs: Sequence[ObjectRef]) -> None:
    ctx = _require_init()
    _run(ctx.free(list(refs)))


class ObjectRefGenerator:
    """Consumer side of a ``num_returns="streaming"`` call (reference:
    python/ray/_private/object_ref_generator.py:32 ObjectRefGenerator).

    Iterating (sync ``for`` or ``async for``) yields ObjectRefs in the
    order the producer yielded values, as they are produced — each ref
    is already resolved in this process, so ``ray_tpu.get(ref)`` on it
    is a local memory-store hit. A producer error terminates the stream
    by raising AFTER all previously-yielded items are delivered.

    Consumption is owner-process-only: the generator is not picklable
    (pass the deployment/actor handle and stream there instead)."""

    def __init__(self, stream_id):
        self._stream_id = stream_id
        self._done = False

    def __iter__(self):
        return self

    def __next__(self) -> ObjectRef:
        # Only genuine termination marks the generator done: a transient
        # failure (timeout, wrong-thread RuntimeError) must leave close()
        # able to release the stream. Producer errors delete the owner
        # state themselves, so close() after them is already a no-op.
        try:
            return _run(_g.ctx.stream_next(self._stream_id))
        except StopAsyncIteration:
            self._done = True
            raise StopIteration

    def __aiter__(self):
        return self

    async def __anext__(self) -> ObjectRef:
        try:
            return await _g.ctx.stream_next(self._stream_id)
        except StopAsyncIteration:
            self._done = True
            raise

    def next_ready(self, timeout: float) -> ObjectRef:
        """__next__ with a timeout (raises GetTimeoutError)."""
        try:
            return _run(_g.ctx.stream_next(self._stream_id, timeout))
        except StopAsyncIteration:
            self._done = True
            raise StopIteration

    def close(self):
        """Abandon the stream: the producer observes the closure on its
        next push and stops the generator."""
        if self._done:
            return
        self._done = True
        ctx, loop = _g.ctx, (_g.elt.loop if _g.elt else _g.ctx_loop)
        if ctx is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(ctx.close_stream,
                                          self._stream_id)
            except RuntimeError:
                pass  # loop already gone (shutdown)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator is not picklable: streams are consumed "
            "in the owner process")


# --- tasks ------------------------------------------------------------------

def _resolve_runtime_env(opts: dict):
    """Task/actor env over the inherited default (the reference layers
    job -> parent -> child the same way). Validation does filesystem
    checks and working_dir/py_modules paths are PACKAGED into the
    cluster KV here (content-addressed pkg:// uris — worker nodes don't
    share the driver's filesystem), so callers cache the result per
    RemoteFunction/ActorClass instead of re-resolving on the hot
    path."""
    from ray_tpu.runtime import runtime_env as rt
    override = rt.validate(opts.get("runtime_env"))
    env = rt.merge(_inherited_runtime_env(), override)
    if env and (env.get("working_dir") or env.get("py_modules")):
        ctx = _require_init()

        def kv_put(key, value):
            _run(ctx.pool.call(ctx.head_addr, "kv_put", key=key,
                               value=value, overwrite=False))

        def kv_has(key):
            return bool(_run(ctx.pool.call(ctx.head_addr, "kv_keys",
                                           prefix=key)))

        env = rt.publish_packages(env, kv_put, kv_has)
    return env


def _inherited_runtime_env():
    """Driver: init(runtime_env=...). Worker: the env it was spawned
    with (RAY_TPU_RT_ENV), so nested tasks inherit the parent's env."""
    if _g.job_runtime_env is not None:
        return _g.job_runtime_env
    blob = os.environ.get("RAY_TPU_RT_ENV")
    if blob:
        import json
        _g.job_runtime_env = json.loads(blob)
        return _g.job_runtime_env
    return None


def _norm_resources(opts: dict) -> dict:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    if opts.get("num_tpus") is not None:
        res["TPU"] = float(opts["num_tpus"])
    if opts.get("num_gpus") is not None:
        res["GPU"] = float(opts["num_gpus"])
    if "CPU" not in res:
        res["CPU"] = 1.0
    return res


def _pg_tuple(opts: dict) -> Optional[tuple]:
    pg = opts.get("placement_group")
    if pg is None:
        return None
    idx = opts.get("placement_group_bundle_index", 0)
    pg_id = pg.id if isinstance(pg, PlacementGroup) else pg
    return (pg_id, idx)


class RemoteFunction:
    def __init__(self, fn: Callable, **default_opts):
        self._fn = fn
        self._opts = default_opts
        self._rt_env, self._rt_resolved = None, False
        self.__name__ = getattr(fn, "__name__", "remote_fn")

    def options(self, **opts) -> "RemoteFunction":
        merged = dict(self._opts)
        merged.update(opts)
        return RemoteFunction(self._fn, **merged)

    def remote(self, *args, **kwargs):
        ctx = _require_init()
        opts = self._opts
        num_returns = opts.get("num_returns", 1)
        refs = ctx.submit_task_sync(
            self._fn, args, kwargs,
            num_returns=num_returns,
            resources=_norm_resources(opts),
            max_retries=opts.get("max_retries"),
            pg=_pg_tuple(opts),
            policy=opts.get("scheduling_strategy", "default"),
            runtime_env=self._cached_runtime_env())
        if num_returns == "streaming":
            return ObjectRefGenerator(refs)  # refs IS the stream id
        return refs[0] if num_returns == 1 else refs

    def _cached_runtime_env(self):
        # validate() hits the filesystem; resolve once per instance,
        # not per .remote() (hot path). A plain flag, not an identity
        # sentinel — these objects cross pickling into workers.
        if not self._rt_resolved:
            self._rt_env = _resolve_runtime_env(self._opts)
            self._rt_resolved = True
        return self._rt_env

    def __call__(self, *a, **kw):
        raise TypeError(
            f"remote function {self.__name__} must be invoked with "
            f"`.remote()` (direct call would run locally)")


# --- actors -----------------------------------------------------------------

class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, **opts):
        self._handle = handle
        self._name = name
        self._opts = opts

    def options(self, **opts):
        merged = dict(self._opts)
        merged.update(opts)
        return ActorMethod(self._handle, self._name, **merged)

    def remote(self, *args, **kwargs):
        ctx = _require_init()
        num_returns = self._opts.get("num_returns", 1)
        refs = ctx.submit_actor_call_sync(
            self._handle._actor_id, self._name, args, kwargs,
            num_returns=num_returns,
            max_task_retries=self._opts.get(
                "max_task_retries", self._handle._max_task_retries),
            concurrency_group=self._opts.get("concurrency_group"))
        if num_returns == "streaming":
            return ObjectRefGenerator(refs)  # refs IS the stream id
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args):
        """Add this actor method as a node in a (to-be-compiled) DAG;
        see ray_tpu.dag (reference: dag/class_node.py bind API)."""
        from ray_tpu.dag import MethodNode
        return MethodNode(self._handle, self._name, args)


class ActorHandle:
    def __init__(self, actor_id: ActorID, max_task_retries: int = 0):
        self._actor_id = actor_id
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name)

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._max_task_retries))

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._opts = default_opts
        self._rt_env, self._rt_resolved = None, False
        self.__name__ = getattr(cls, "__name__", "Actor")

    def options(self, **opts) -> "ActorClass":
        merged = dict(self._opts)
        merged.update(opts)
        return ActorClass(self._cls, **merged)

    def _cached_runtime_env(self):
        if not self._rt_resolved:
            self._rt_env = _resolve_runtime_env(self._opts)
            self._rt_resolved = True
        return self._rt_env

    def remote(self, *args, **kwargs) -> ActorHandle:
        ctx = _require_init()
        opts = self._opts
        retries = opts.get("max_task_retries", 0)
        if opts.get("get_if_exists") and opts.get("name"):
            try:
                h = get_actor(opts["name"], opts.get("namespace"))
                h._max_task_retries = retries
                return h
            except ValueError:
                pass  # not there yet — create it below
        resources = dict(opts.get("resources") or {})
        if opts.get("num_cpus") is not None:
            resources["CPU"] = float(opts["num_cpus"])
        if opts.get("num_tpus") is not None:
            resources["TPU"] = float(opts["num_tpus"])
        if "CPU" not in resources and "TPU" not in resources:
            resources["CPU"] = 1.0
        scheduling = {}
        if opts.get("labels"):
            scheduling["labels"] = opts["labels"]
        groups = opts.get("concurrency_groups")
        mc = opts.get("max_concurrency", 1)
        if groups and "max_concurrency" not in opts:
            # declaring groups implies a concurrent actor: the caller
            # pipeline must admit at least as many in-flight calls as
            # the groups can execute (reference: concurrency_groups
            # actors are concurrent by construction)
            mc = max(1, sum(int(v) for v in groups.values()))
        try:
            actor_id = _run(ctx.create_actor(
                self._cls, args, kwargs,
                name=opts.get("name"),
                namespace=opts.get("namespace", _g.namespace),
                resources=resources,
                max_restarts=opts.get("max_restarts", 0),
                max_concurrency=mc,
                concurrency_groups=groups,
                pg=_pg_tuple(opts),
                scheduling=scheduling or None,
                lifetime=opts.get("lifetime"),
                runtime_env=self._cached_runtime_env()))
        except Exception as e:
            # get_if_exists race: another creator won between our lookup
            # miss and this create — adopt theirs.
            if (opts.get("get_if_exists") and opts.get("name")
                    and "taken" in str(e)):
                h = get_actor(opts["name"], opts.get("namespace"))
                h._max_task_retries = retries
                return h
            raise
        return ActorHandle(actor_id, max_task_retries=retries)

    def __call__(self, *a, **kw):
        raise TypeError(
            f"actor class {self.__name__} must be instantiated with "
            f"`.remote()`")


def remote(*args, **options):
    """``@remote`` / ``@remote(num_cpus=2, ...)`` for functions and
    classes (reference: worker.py:3494)."""
    def wrap(obj):
        if isinstance(obj, type):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)
    if len(args) == 1 and not options and callable(args[0]):
        return wrap(args[0])
    assert not args, "use @remote or @remote(**options)"
    return wrap


def method(**opts):
    """Decorator kept for API parity; options are applied at call sites via
    ``handle.method.options(...)`` (reference: actor.py method decorator)."""
    def wrap(fn):
        fn._method_opts = opts
        return fn
    return wrap


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    ctx = _require_init()
    info = _run(ctx.pool.call(ctx.head_addr, "get_named_actor",
                              name=name,
                              namespace=namespace or _g.namespace))
    if info is None or info.get("state") == "DEAD":
        raise ValueError(f"no live actor named {name!r}")
    return ActorHandle(info["actor_id"])


def kill(target, *, no_restart: bool = True) -> None:
    ctx = _require_init()
    if isinstance(target, ActorHandle):
        _run(ctx.kill_actor(target._actor_id, no_restart=no_restart))
    else:
        raise TypeError("kill() takes an ActorHandle")


def cancel(ref: ObjectRef, *, force: bool = False) -> None:
    """Best-effort cancel of a pending task (running tasks are not
    interrupted — cooperative only)."""
    # v1: cancellation marks are worker-side; a task not yet started on a
    # worker will fail with 'task cancelled'.
    ctx = _require_init()
    e = ctx.store.get_entry(ref.oid)
    if e is not None and e.status == "pending":
        from ray_tpu.runtime.serialization import dumps_oob
        ctx.store.resolve(
            ref.oid, error_frame=dumps_oob(TaskError("task cancelled")))


# --- cluster info -----------------------------------------------------------

def nodes() -> List[dict]:
    ctx = _require_init()
    return _run(ctx.pool.call(ctx.head_addr, "get_nodes"))


def cluster_resources() -> Dict[str, float]:
    out: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in n["resources_total"].items():
            out[k] = out.get(k, 0) + v
    return out


def available_resources() -> Dict[str, float]:
    out: Dict[str, float] = {}
    for n in nodes():
        if not n["alive"]:
            continue
        for k, v in n["resources_available"].items():
            out[k] = out.get(k, 0) + v
    return out


def timeline(all_nodes: bool = False,
             chrome_path: Optional[str] = None,
             trace_id: Optional[str] = None) -> List[dict]:
    """Task/actor event timeline (reference: _private/state.py:1010).

    ``all_nodes=True`` collects every node's worker span buffers through
    the control service (submit edges + exec spans from
    util/tracing.py, plus collective ring spans from dag/ring.py and
    request spans from the serve path) and the head's per-node
    clock-offset estimates; ``chrome_path=`` additionally writes a
    chrome://tracing / Perfetto JSON file — with cross-node timestamps
    corrected by the offsets — and the returned records are the
    chrome-trace events. ``trace_id=`` narrows either form to ONE
    request trace (its spans, exec spans of its nested tasks, batch
    spans linked to it, and — for train-step traces — its steps'
    collective rounds)."""
    from ray_tpu.util import events
    offsets = None
    if all_nodes:
        ctx = _require_init()
        r = _run(ctx.pool.call(ctx.head_addr, "collect_timeline",
                               timeout=45.0))
        evs = list(r.get("events", []))
        offsets = r.get("clock_offsets")
        if _g.agent is None:
            # driver attached to an externally-started node: its local
            # buffer isn't behind any agent — append it. (With an
            # in-process agent the buffer is process-global and
            # node_timeline already returned it, tagged.)
            evs += events.dump()
    else:
        evs = events.dump()
    from ray_tpu.util import tracing
    if chrome_path is not None:
        return tracing.to_chrome(evs, chrome_path,
                                 clock_offsets=offsets,
                                 trace_id=trace_id)
    if trace_id is not None:
        evs = tracing.filter_trace(evs, trace_id)
    return evs


# --- placement groups --------------------------------------------------------

@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str

    def ready(self, timeout: float = 60.0) -> bool:
        ctx = _require_init()
        import time
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            info = _run(ctx.pool.call(ctx.head_addr, "get_pg",
                                      pg_id=self.id))
            if info and info["state"] == "CREATED":
                return True
            if info and info["state"] in ("INFEASIBLE", "REMOVED"):
                return False
            import time as _t
            _t.sleep(0.05)
        return False

    def bundle_specs(self):
        return self.bundles


def placement_group(bundles: List[Dict[str, float]],
                    strategy: str = "PACK",
                    name: Optional[str] = None) -> PlacementGroup:
    """Gang-reserve resource bundles (reference: util/placement_group.py:22;
    2-phase protocol in control.py create_pg)."""
    ctx = _require_init()
    pg_id = PlacementGroupID.generate()
    r = _run(ctx.pool.call(ctx.head_addr, "create_pg", pg_id=pg_id,
                           bundles=bundles, strategy=strategy, name=name,
                           timeout=120.0))
    if not r.get("ok"):
        raise RayTpuError(r.get("error", "placement group failed"))
    return PlacementGroup(pg_id, bundles, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    ctx = _require_init()
    _run(ctx.pool.call(ctx.head_addr, "remove_pg", pg_id=pg.id))
