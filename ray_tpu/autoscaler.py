"""Autoscaler: reconcile cluster size against resource demand.

The v2-reconciler analog (reference:
python/ray/autoscaler/v2/instance_manager/reconciler.py:56 Reconciler,
autoscaler/v2/sdk.py request_resources): a loop reads the head's view —
per-node pending lease demand (piggybacked on heartbeats), PENDING
placement groups, and explicit `request_resources` asks from the KV —
decides how many nodes to add or drain, and drives a pluggable
NodeProvider. `LocalNodeProvider` launches real `ray_tpu.node` OS
processes, which is both the dev story and the test story; cloud
providers implement the same three methods against their instance APIs.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.runtime import rpc

REQUEST_KV_KEY = "__autoscaler_request"


@dataclass
class AutoscalerConfig:
    min_nodes: int = 0
    max_nodes: int = 8
    node_resources: Dict[str, float] = field(
        default_factory=lambda: {"CPU": 1.0})
    idle_timeout_s: float = 30.0
    reconcile_interval_s: float = 2.0
    # nodes the autoscaler must never touch (e.g. the head's)
    protected_node_ids: tuple = ()


class NodeProvider:
    """Implement these three against your instance API."""

    async def launch(self, resources: Dict[str, float],
                     labels: Dict[str, str]) -> str:
        raise NotImplementedError

    async def terminate(self, handle: str) -> None:
        raise NotImplementedError

    async def alive_handles(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Nodes as local `ray_tpu.node` subprocesses."""

    def __init__(self, head_address: str):
        self.head_address = head_address
        self._procs: Dict[str, object] = {}
        self._n = 0

    async def launch(self, resources, labels) -> str:
        import sys
        self._n += 1
        handle = f"local-{self._n}"
        cmd = [sys.executable, "-m", "ray_tpu.node",
               "--address", self.head_address,
               "--num-cpus", str(resources.get("CPU", 1.0)),
               "--labels", json.dumps(
                   {**labels, "autoscaler_handle": handle})]
        extra = {k: v for k, v in resources.items() if k != "CPU"}
        if extra:
            cmd += ["--resources", json.dumps(extra)]
        proc = await asyncio.create_subprocess_exec(
            *cmd, start_new_session=True,
            stdout=asyncio.subprocess.DEVNULL,
            stderr=asyncio.subprocess.DEVNULL)
        self._procs[handle] = proc
        return handle

    async def terminate(self, handle: str) -> None:
        proc = self._procs.pop(handle, None)
        if proc is None:
            return
        try:
            proc.terminate()
            await asyncio.wait_for(proc.wait(), 15)
        except (ProcessLookupError, asyncio.TimeoutError):
            try:
                proc.kill()
            except ProcessLookupError:
                pass

    async def alive_handles(self) -> List[str]:
        return [h for h, p in self._procs.items()
                if p.returncode is None]


def request_resources(bundles: List[Dict[str, float]],
                      address: Optional[str] = None) -> None:
    """Explicit scale ask (reference: autoscaler/v2/sdk.py
    request_resources): the autoscaler keeps the cluster able to fit
    these bundles regardless of current load."""
    from ray_tpu import api
    ctx = api._require_init()
    api._run(ctx.pool.call(ctx.head_addr, "kv_put", key=REQUEST_KV_KEY,
                           value=json.dumps(bundles).encode()))


class Autoscaler:
    def __init__(self, head_address: str, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None):
        host, port = head_address.rsplit(":", 1)
        self.head_addr = (host, int(port))
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self.pool = rpc.ConnectionPool()
        self._task: Optional[asyncio.Task] = None
        self._stopped = False
        # handle -> node_id hex once matched; node_id -> idle_since
        self._handle_nodes: Dict[str, str] = {}
        self._idle_since: Dict[str, float] = {}

    async def start(self):
        self._task = asyncio.ensure_future(self._loop())

    async def stop(self):
        self._stopped = True
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        await self.pool.close()

    async def _loop(self):
        while not self._stopped:
            try:
                await self.reconcile_once()
            except Exception:
                pass
            await asyncio.sleep(self.config.reconcile_interval_s)

    # --- one reconcile pass --------------------------------------------

    async def reconcile_once(self) -> dict:
        nodes = await self.pool.call(self.head_addr, "get_nodes",
                                     timeout=10.0)
        alive = [n for n in nodes if n["alive"]]
        self._match_handles(alive)
        demand = await self._collect_demand(alive)
        actions = {"launched": 0, "terminated": 0,
                   "nodes": len(alive), "demand": len(demand)}

        handles = set(await self.provider.alive_handles())
        alive_ids = {_nid(n) for n in alive}
        booting = sum(1 for h in handles
                      if self._handle_nodes.get(h) not in alive_ids)

        # Scale up: first-fit demand into current availability PLUS
        # capacity already booting (launched but not yet registered) —
        # without the offset every reconcile pass would re-launch for
        # the same pending task until it lands. Nodes that standing
        # demand fits into are RESERVED: scale-down must not terminate
        # the capacity a request_resources ask is being held by.
        unfit, reserved = self._unfit_demand(demand, alive, booting)
        want = 0
        if unfit:
            per_node = self.config.node_resources
            pool: List[Dict[str, float]] = []
            for shape in unfit:
                for avail in pool:
                    if _fits(shape, avail):
                        _take(shape, avail)
                        break
                else:
                    fresh = dict(per_node)
                    if not _fits(shape, fresh):
                        continue  # a single node can never fit it
                    _take(shape, fresh)
                    pool.append(fresh)
                    want += 1
        managed = len(handles)
        if managed + want > self.config.max_nodes:
            want = max(0, self.config.max_nodes - managed)
        for _ in range(want):
            await self.provider.launch(self.config.node_resources, {})
            actions["launched"] += 1

        # scale down: managed nodes idle past the timeout, above min
        if not unfit:
            await self._scale_down(alive, actions, reserved)
        return actions

    def _match_handles(self, alive):
        for n in alive:
            h = (n.get("labels") or {}).get("autoscaler_handle")
            if h:
                self._handle_nodes[h] = n["node_id"].hex() \
                    if hasattr(n["node_id"], "hex") else str(n["node_id"])

    async def _collect_demand(self, alive) -> List[Dict[str, float]]:
        demand: List[Dict[str, float]] = []
        for n in alive:
            demand.extend(n.get("pending_demand") or [])
        # PENDING placement groups
        pgs = await self.pool.call(self.head_addr, "list_pgs",
                                   timeout=10.0)
        for pg in pgs:
            if pg.get("state") == "PENDING":
                demand.extend(pg.get("bundles") or [])
        # explicit request_resources bundles
        blob = await self.pool.call(self.head_addr, "kv_get",
                                    key=REQUEST_KV_KEY, timeout=10.0)
        if blob:
            demand.extend(json.loads(blob.decode()))
        return demand

    def _unfit_demand(self, demand, alive, booting: int = 0):
        """First-fit the demand into current availability (+ booting
        capacity); returns (unfit shapes, node ids holding demand)."""
        avails = [(_nid(n), dict(n["resources_available"]))
                  for n in alive]
        avails += [(None, dict(self.config.node_resources))
                   for _ in range(booting)]
        unfit, reserved = [], set()
        for shape in demand:
            shape = {k: float(v) for k, v in shape.items()
                     if not str(k).startswith("_")}
            for nid, avail in avails:
                if _fits(shape, avail):
                    _take(shape, avail)
                    if nid is not None:
                        reserved.add(nid)
                    break
            else:
                unfit.append(shape)
        return unfit, reserved

    async def _scale_down(self, alive, actions, reserved=()):
        handles = set(await self.provider.alive_handles())
        now = time.monotonic()
        by_node = {v: k for k, v in self._handle_nodes.items()}
        n_managed_alive = sum(
            1 for n in alive
            if _nid(n) in by_node and by_node[_nid(n)] in handles)
        actor_nodes = await self._nodes_hosting_actors()
        if actor_nodes is None:
            return  # can't see actors: don't terminate anything
        for n in alive:
            nid = _nid(n)
            handle = by_node.get(nid)
            if handle is None or handle not in handles:
                continue
            if nid in self.config.protected_node_ids:
                continue
            busy = any(n["resources_available"].get(k, 0) != v
                       for k, v in n["resources_total"].items()) \
                or (n.get("pending_demand") or []) \
                or nid in reserved \
                or nid in actor_nodes
            if busy:
                self._idle_since.pop(nid, None)
                continue
            since = self._idle_since.setdefault(nid, now)
            if now - since < self.config.idle_timeout_s:
                continue
            if n_managed_alive <= self.config.min_nodes:
                break
            await self.pool.call(self.head_addr, "drain_node",
                                 node_id=n["node_id"], timeout=10.0)
            await self.provider.terminate(handle)
            self._idle_since.pop(nid, None)
            n_managed_alive -= 1
            actions["terminated"] += 1


    async def _nodes_hosting_actors(self):
        """Nodes with live actors must not be drained — zero-resource
        actors are invisible to the availability check. Returns None
        when the view is unavailable (caller skips scale-down)."""
        try:
            actors = await self.pool.call(self.head_addr, "list_actors",
                                          timeout=10.0)
        except Exception:  # noqa: BLE001
            return None
        out = set()
        for a in actors:
            if a.get("state") in ("PENDING", "ALIVE", "RESTARTING") \
                    and a.get("node_id") is not None:
                v = a["node_id"]
                out.add(v.hex() if hasattr(v, "hex") else str(v))
        return out


def _nid(n) -> str:
    v = n["node_id"]
    return v.hex() if hasattr(v, "hex") else str(v)


# Shared fit predicate (same float tolerance as the scheduler's).
from ray_tpu.runtime.agent import _fits  # noqa: E402


def _take(shape: Dict[str, float], avail: Dict[str, float]) -> None:
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v
