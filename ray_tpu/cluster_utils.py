"""Multi-node-without-a-cluster: several node agents in one process.

The reference's primary distributed-test mechanism (reference:
python/ray/cluster_utils.py:137 Cluster.add_node) — real control service,
real agents, real RPC and worker subprocesses, fake machine boundary. Each
`add_node` starts another NodeAgent with its own resources/labels on the
shared event-loop thread.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ray_tpu.config import Config
from ray_tpu.runtime import rpc


class Cluster:
    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config.from_env()
        self.elt = rpc.EventLoopThread("ray_tpu_cluster")
        from ray_tpu.runtime.control import ControlService
        self.head = ControlService(self.config)
        self.head_addr = self.elt.run(self.head.start(
            self.config.head_host, self.config.head_port))
        import uuid
        self.session_id = uuid.uuid4().hex[:16]
        self.elt.run(self._put_session())
        self.agents: List = []

    async def _put_session(self):
        await self.head.pool.call(self.head_addr, "kv_put",
                                  key="__session_id",
                                  value=self.session_id.encode())

    @property
    def address(self) -> str:
        return f"{self.head_addr[0]}:{self.head_addr[1]}"

    def add_node(self, num_cpus: float = 1,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None):
        from ray_tpu.api import _driver_pythonpath
        from ray_tpu.runtime.agent import NodeAgent
        res = dict(resources or {})
        res.setdefault("CPU", float(num_cpus))
        agent = NodeAgent(self.head_addr, resources=res, labels=labels,
                          config=self.config, session_id=self.session_id,
                          env_extra={"PYTHONPATH": _driver_pythonpath()})
        self.elt.run(agent.start())
        self.agents.append(agent)
        return agent

    def restart_head(self):
        """Crash-restart the control service on the same address. With
        ``config.control_persist_dir`` set, the new instance replays the
        persisted tables and agents rejoin on their next heartbeat
        (reference: GCS restart + NotifyGCSRestart,
        gcs/store_client/redis_store_client.h:126)."""
        from ray_tpu.runtime.control import ControlService
        host, port = self.head_addr
        self.elt.run(self.head.stop(), timeout=15)
        self.head = ControlService(self.config)
        self.head_addr = self.elt.run(self.head.start(host, port))
        return self.head

    def remove_node(self, agent) -> None:
        self.agents.remove(agent)
        self.elt.run(agent.stop(), timeout=15)
        self.elt.run(self.head.pool.call(
            self.head_addr, "drain_node", node_id=agent.node_id))

    def kill_node(self, agent) -> None:
        """Simulate node death: stop the agent WITHOUT telling the head —
        the health checker must notice."""
        self.agents.remove(agent)
        self.elt.run(agent.stop(), timeout=15)

    def shutdown(self) -> None:
        for agent in list(self.agents):
            try:
                self.elt.run(agent.stop(), timeout=15)
            except Exception:
                pass
        self.agents.clear()
        try:
            self.elt.run(self.head.stop(), timeout=10)
        except Exception:
            pass
        self.elt.stop()
