"""Central config table for the runtime.

Equivalent in spirit to the reference's ``RAY_CONFIG`` X-macro table
(reference: src/ray/common/ray_config_def.h) — every tunable has a typed
default and is overridable from the environment as ``RAY_TPU_<NAME>`` or from
the ``system_config`` dict handed to :func:`ray_tpu.init`.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any


def _env(name: str, default: Any, typ: type) -> Any:
    raw = os.environ.get(f"RAY_TPU_{name.upper()}")
    if raw is None:
        return default
    if typ is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    if typ in (dict, list):
        return json.loads(raw)
    return typ(raw)


@dataclass
class Config:
    # --- control service (head) ---
    head_host: str = "127.0.0.1"
    head_port: int = 0                      # 0 = pick a free port
    health_check_period_s: float = 1.0      # head -> agent liveness probes
    health_check_failure_threshold: int = 5
    # Cluster-view snapshot staleness bound: heartbeat replies ship a
    # cached pickled view rebuilt at most this often (O(nodes) to build,
    # so per-beat rebuilds are O(nodes^2)/s cluster-wide — see
    # SCALE_BENCH_STRETCH.json for the measured collapse at 1k nodes).
    view_snapshot_interval_s: float = 0.5
    kv_max_value_bytes: int = 64 * 1024 * 1024

    # --- node agent / workers ---
    num_workers_prestart: int = 2           # warm pool per node
    worker_start_timeout_s: float = 60.0
    worker_idle_reap_s: float = 600.0
    max_workers_per_node: int = 64

    # --- scheduling ---
    scheduler_policy: str = "hybrid"        # hybrid | spread | random
    hybrid_local_threshold: float = 0.5     # pack locally until this utilization
    lease_timeout_s: float = 30.0
    infeasible_wait_window_s: float = 10.0  # grace for joining/scaled nodes

    # --- object plane ---
    inline_object_max_bytes: int = 100 * 1024   # small objects ride RPC replies
    shm_store_bytes: int = 2 * 1024 * 1024 * 1024
    shm_fallback_dir: str = "/tmp"
    object_transfer_chunk_bytes: int = 4 * 1024 * 1024
    object_spill_dir: str = ""              # "" = <session>/spill
    stream_backpressure_window: int = 64    # unconsumed items per stream
    stream_producer_inflight: int = 8       # unacked pushes per producer
    # Collective plane: dag allreduce(impl="auto") picks the star reduce
    # for payloads at or below this and the chunked ring above it — the
    # measured crossover on shm channels (ALLREDUCE_BENCH: the star wins
    # under ~4 MB because a ring round is 3(N-1) sequential hops and hop
    # latency dominates small frames; above it the root's O(N*S)
    # ingress/egress collapses).
    allreduce_star_max_bytes: int = 4 * 1024 * 1024
    # Collective auto-tuner (dag/tuner.py): a one-shot in-situ
    # micro-bench on each tuning-enabled ring (run lazily at the first
    # collective, cached per ring generation) replaces the static
    # crossover above — impl (star / flat ring / hierarchical) and
    # chunk size are picked per payload band from the measured
    # alpha/beta fit; the static knob stays the fallback for rings
    # that never probed. The probe costs two tiny fused rounds.
    collective_tuner: bool = True
    collective_tuner_probe_bytes: int = 1 << 20   # largest probe round
    collective_tuner_min_chunk_bytes: int = 64 * 1024
    # Topology-aware hierarchical collectives (dag/ring.py
    # HierarchicalReducer): "auto" wires the train gradient sync as a
    # ring-of-rings (per-node shm intra rings, one TCP ring over node
    # leaders, intra broadcast) whenever the worker group spans more
    # than one node with at least one multi-rank node — cross-node
    # wire traffic drops to ~1/ranks-per-node; "flat" keeps the
    # one-level ring regardless of topology.
    collective_hierarchy: str = "auto"
    # Wire codec auto-selection + error-feedback (train/collective.py,
    # dag/tuner.py codec band): allreduce_gradients(codec="auto") picks
    # the cheapest probed codec (int4 < int8 < bf16 < fp32) whose
    # observed ``allreduce_quant_error`` bound stays at or below this —
    # a lossy codec whose bound trips backs off to bf16/fp32 on the
    # next round.
    collective_codec_error_bound: float = 1e-2
    # Payloads below this many bytes always ship fp32 under
    # codec="auto": per-block scale framing plus quant error buy
    # nothing when the whole gradient fits a few channel slots.
    collective_codec_min_bytes: int = 64 * 1024
    # Error-feedback accumulation: each rank carries the quantization
    # residual (sent-minus-shipped, reconstructed from the local codec
    # round-trip — no extra wire) into the next round's gradients, the
    # EF-SGD trick that makes int8/int4 gradient sync convergence-safe
    # (ZERO_BENCH codec_convergence: int4+EF within 1e-3 relative of
    # the fp32 trajectory; no-EF int8 is NOT). With this off,
    # codec="auto" never picks a lossy codec.
    codec_error_feedback: bool = True

    # --- pipeline parallelism (train/pipeline.py) ---
    # Default microbatch schedule for train.Pipeline: "1f1b" keeps
    # in-flight activations at O(stages) with the same bubble as
    # GPipe; "gpipe" is the simple fill/drain reference.
    pipeline_schedule: str = "1f1b"
    # Ship activations/gradients across stage edges as device-path
    # TensorRef handles (runtime/device_store.py — the tensor moves at
    # most once, on the consumer's resolve; 3.6x over host staging per
    # PERF.md) instead of host-staged numpy frames. Requires the
    # cluster RPC pool for cross-process resolution; the runtime loop
    # frees every ref the moment the consumer materializes it.
    pipeline_device_transport: bool = True
    # TTL backstop on schedule-owned activation refs: a consumer that
    # dies before resolving cannot pin the producer's memory past this
    # bound (the normal path frees refs at materialization). Keep it
    # ABOVE pipeline_step_timeout_s plus the worst-case stage compile:
    # a ref must outlive any stall the pipeline itself tolerates, or a
    # slow-but-healthy consumer resolves an already-expired tensor.
    pipeline_activation_ttl_s: float = 600.0
    # Bound on one schedule step's MID-step channel waits (recv of a
    # microbatch / backpressured send): a stage dead mid-step surfaces
    # as PeerLostError within this instead of hanging the pipeline.
    # The wait for a NEW step's first microbatch is exempt (driver
    # cadence — eval/checkpoint pauses between steps are healthy);
    # a peer dead at a step boundary is detected by the driver's
    # report read, and Pipeline.teardown() injects STOP directly on
    # inter-stage edges when a dead stage can't relay it, so parked
    # survivors still unwind.
    pipeline_step_timeout_s: float = 300.0

    # --- rpc ---
    rpc_connect_timeout_s: float = 10.0
    rpc_retry_max_attempts: int = 5
    rpc_retry_backoff_s: float = 0.1
    # Deterministic fault injection, reference: src/ray/rpc/rpc_chaos.h.
    # Format: "Method=max_failures:deadline_ms,Method2=..."
    testing_rpc_failure: str = ""
    # Deterministic fault injection for the DAG CHANNEL layer (shm ring
    # + TCP channels — the collective plane's transport), the data-
    # plane sibling of testing_rpc_failure: elasticity and recovery
    # paths are exercised by repeatable injected failures instead of
    # hand-timed process kills. Comma-separated rules
    # "<op>:<action>:<nth>[:<param>]": op in {write, read}; action in
    # {delay (sleep <param> s before the op), drop (writes: silently
    # discard the frame; reads: raise ChannelTimeout), kill (SIGKILL
    # this process — a deterministic mid-collective worker death)};
    # nth = 1-based index of the matching op counted process-wide.
    # See dag/channel.py ChannelChaos.
    testing_channel_failure: str = ""

    # --- paged KV cache (llm/kvcache.py) ---
    # Token-block size of the engine's paged KV cache. The serving
    # default: fixed-size blocks from a preallocated pool, per-request
    # block tables, ref-counted prefix reuse for shared system
    # prompts — tensor-parallel engines included (the pool shards its
    # kv-head dim over the mesh). 0 selects the legacy monolithic slot
    # cache (bucketed doubling growth). The effective size is
    # gcd-adjusted to divide every prefill bucket and max_len.
    kvcache_block_size: int = 16
    # Pool size in blocks (0 = auto: worst case — every slot at
    # max_len — plus one chain of prefix-cache headroom, capped at
    # half the free HBM when the devmon gauges know it).
    kvcache_pool_blocks: int = 0
    # Prefix reuse: hash-chained full prompt blocks enter a cached
    # index at request finish; a later request sharing the prefix
    # adopts those blocks ref-counted and prefills only its suffix.
    # Off: blocks free immediately at request finish.
    kvcache_prefix_cache: bool = True
    # Paged decode attention impl: "paged_flash" walks each slot's
    # block table directly in the pallas kernel
    # (ops/pallas/paged_attention.py — no gathered (slots, max_len)
    # view, no O(slots x max_len x layers) HBM copy per token);
    # "gather" materializes the view per layer (the debug/parity
    # path); "auto" = paged_flash on a real TPU backend, gather
    # elsewhere. Engines also take this per-instance via
    # LLMEngine(kv_impl=...).
    paged_attn_impl: str = "auto"
    # Force the pallas interpreter for the paged-flash kernel (it is
    # forced automatically off-TPU so kv_impl="paged_flash" still runs
    # the real kernel logic under JAX_PLATFORMS=cpu; the knob exists
    # to debug kernel/compiler divergence ON a TPU).
    paged_attn_interpret: bool = False

    # --- speculative decoding (llm/spec.py) ---
    # Draft-and-verify generation in the paged engine (speculative
    # sampling, arxiv 2211.17192): a model-free prompt-lookup drafter
    # proposes up to spec_draft_tokens tokens by matching the
    # request's recent suffix against its own prompt+output history;
    # the engine scores all k+1 positions in one batched forward and
    # accepts the longest agreeing prefix (exact greedy match at
    # temperature<=0, rejection sampling otherwise so the output
    # distribution is unchanged). Off by default; engines also take
    # this per-instance via LLMEngine(spec=...).
    spec_decode: bool = False
    # Max draft tokens per verify round (the k in draft-and-verify).
    # Verify widths are padded to a small bucket set derived from
    # this, so distinct accepted lengths never compile new programs.
    spec_draft_tokens: int = 4
    # Longest suffix n-gram the prompt-lookup drafter tries to match
    # (it backs down to 1-grams before giving up).
    spec_ngram_max: int = 3
    # Accept-rate backoff: the drafter tracks acceptance over a
    # sliding window of this many drafted tokens and stops proposing
    # when the windowed accept rate drops below ~25%, re-probing
    # periodically — adversarial low-hit prompts degrade to vanilla
    # decode instead of paying verify overhead forever.
    spec_backoff_window: int = 16

    # --- serve fault tolerance ---
    # Default per-request deadline budget (seconds) when the client
    # sends no X-Request-Deadline header. The budget is spent across
    # queueing, routing, retries, and the replica call; once spent the
    # proxy answers 504 and downstream work is cancelled.
    serve_default_deadline_s: float = 120.0
    # Proxy admission control: requests beyond the deployment's live
    # capacity (running replicas x max_ongoing_requests) wait in a
    # bounded queue; past this depth — or when the predicted queue wait
    # exceeds the request's remaining deadline budget — the proxy sheds
    # with a fast 503 + Retry-After instead of letting the request ride
    # to its full deadline.
    serve_queue_limit: int = 128
    # Budgeted retry policy (route refresh, reroute-on-submit-failure):
    # attempts are jittered-exponential-backoff spaced and always capped
    # by the request's remaining deadline.
    serve_retry_max_attempts: int = 3
    # Replica circuit breaker (caller-side routing table): eject a
    # replica after this many CONSECUTIVE infrastructure failures;
    # half-open recovery probes admit one trial request after the
    # cooldown (ping probes can shortcut or extend it).
    serve_cb_failure_threshold: int = 3
    serve_cb_cooldown_s: float = 2.0
    # Latency ejection: >0 arms it — this many consecutive calls slower
    # than the threshold eject the replica like failures do. 0 = off.
    serve_cb_latency_threshold_s: float = 0.0
    serve_cb_latency_count: int = 3
    # Graceful draining: a DRAINING replica (scale-down / redeploy)
    # finishes its in-flight requests (incl. streams) and accepts no
    # new ones; after this many seconds the controller stops waiting.
    serve_drain_timeout_s: float = 30.0
    # --- SLO-driven replica autoscaling (serve/autoscale.py) ---
    # A deployment opts in with autoscaling_config={"policy": "slo",
    # ...}; the controller then scales it from the health plane's
    # burn_advice (page-tier burn -> scale up; the proxy's
    # shed-while-burning hint is the fast path) instead of the legacy
    # target_ongoing_requests loop. Seconds between burn-advice
    # fetches / per-deployment decision ticks:
    serve_autoscale_interval_s: float = 2.0
    # Minimum seconds between two scale changes of one deployment
    # (hysteresis: a flapping alert cannot thrash replica counts).
    serve_autoscale_cooldown_s: float = 15.0
    # Replicas added per scale-up decision.
    serve_autoscale_step: int = 1
    # Utilization deadband: below low (sustained for the window, and
    # only while no budget is burning) scale down one replica — the
    # victim DRAINS, in-flight streams finish; above high with a
    # warn-tier burn, scale up before the page tier fires. Between
    # the thresholds the target holds.
    serve_autoscale_low_util: float = 0.25
    serve_autoscale_low_util_window_s: float = 30.0
    serve_autoscale_high_util: float = 0.85

    # Deterministic fault injection for the SERVE data path, the
    # serving sibling of testing_rpc_failure / testing_channel_failure
    # (reference: src/ray/rpc/rpc_chaos.h + serve.proto health checks).
    # Comma-separated rules "<site>:<action>:<nth>[:<param>]": site in
    # {proxy (handle -> replica submission), replica (replica -> user
    # code / engine)}; action in {error (raise an injected failure),
    # delay (sleep <param> s), drop (replica only: never respond — the
    # caller's deadline fires), kill (SIGKILL this process)}; nth =
    # 1-based index of the matching site's requests, counted
    # process-wide. See serve/chaos.py ServeChaos.
    testing_serve_failure: str = ""

    # --- tasks / actors ---
    default_max_task_retries: int = 3
    default_max_actor_restarts: int = 0
    actor_call_queue_depth: int = 10_000
    # how long an actor's __init__ may run (model-loading actors — an
    # LLM replica binding hundreds of MB of weights over a slow device
    # link — legitimately take minutes)
    actor_init_timeout_s: float = 600.0

    # --- memory monitor (0 = disabled) ---
    memory_monitor_interval_s: float = 0.0
    memory_usage_threshold: float = 0.95    # node-wide usage fraction
    worker_rss_limit_bytes: int = 0         # per-worker soft cap (monitor)
    worker_cgroup_memory_bytes: int = 0     # per-worker KERNEL cap (cgroup)

    # --- observability ---
    event_buffer_size: int = 65536
    # Collective tracing (dag/ring.py): span granularity recorded into
    # the "collective" event category. "off" = no timing at all (hot
    # path untouched); "round" = one span + recv-wait/straggler
    # attribution per collective round (default — a round moves MBs,
    # the extra clock reads are noise); "chunk" = additionally one
    # span per chunk send / recv-wait / reduce (post-mortem depth;
    # bounded by the category's event-buffer sub-budget).
    collective_trace_level: str = "round"
    # Flight recorder: per-rank ring of the last K rounds' timing
    # records, dumped to JSON when a collective raises (peer death,
    # ERROR relay, protocol desync) — the dump path is attached to the
    # raised exception. 0 disables.
    collective_flight_rounds: int = 8
    collective_flight_dir: str = ""         # "" = <tmp>/ray_tpu_flight
    metrics_export_interval_s: float = 5.0
    metrics_port: int = -1                  # -1 off, 0 ephemeral, >0 fixed
    log_dir: str = ""                       # "" = workers inherit stdio
    # Request tracing (util/tracing.py request layer): tail-based
    # sampling at the proxy when a request FINISHES. Error /
    # deadline-exceeded traces and traces slower than
    # trace_slow_threshold_s are always kept; healthy ones keep with
    # this probability (deterministic on the trace id). 1.0 = keep
    # everything (small clusters), 0.0 = only errors/slow survive
    # (high-QPS production). Segment spans are budget-capped in the
    # "request" event category either way; sampling gates which traces
    # SURFACE (root span recorded), not which record.
    trace_sample_rate: float = 1.0
    trace_slow_threshold_s: float = 1.0
    # Device-plane observability (util/devmon.py; master switch is the
    # RAY_TPU_DEVMON env var, read at process start like the tracing
    # flags). A function compiled >= devmon_recompile_threshold times
    # within devmon_recompile_window_s seconds flags a recompile STORM
    # (xla_recompile_storms_total counter + a log naming the function)
    # — the silent mid-serving recompile loop no host profiler can
    # see. 0 disables the gate.
    # The default sits above the engine's LEGITIMATE warmup variants
    # (one compile per prefill bucket; log2(steps_per_sync)+1 decode
    # block variants x2 filter modes) so healthy cold starts don't
    # flag; a real storm — an unbucketed shape reaching a jit boundary
    # on the request path — blows past it within a few requests.
    devmon_recompile_threshold: int = 10
    devmon_recompile_window_s: float = 60.0
    # HBM snapshot cadence (per-device used/limit/peak gauges + the
    # "device" events behind `/devices` and `ray-tpu devices`), and
    # the trailing horizon the device_duty_cycle gauge integrates
    # device-compute windows over.
    devmon_hbm_interval_s: float = 5.0
    devmon_duty_horizon_s: float = 30.0
    # Goodput ledger (util/goodput.py): per-rank, per-step wall-time
    # anatomy (compute / comm_exposed / bubble / ckpt_stall / compile
    # / idle, summing exactly to step wall). "off" = every clock read
    # removed (same discipline as collective_trace_level); "step" =
    # one row per training step (default — a handful of perf_counter
    # reads per step is noise against a step that moves MBs).
    goodput_level: str = "step"
    # Online straggler detection (train controller): robust z-score a
    # rank's p50 (compute - comm_exposed) must clear against the
    # ring's median/MAD before it is named in a "goodput"/"straggler"
    # event + the goodput_straggler_rank gauge, and the rolling
    # per-rank step window the p50s are taken over.
    goodput_straggler_z: float = 6.0
    goodput_straggler_window_steps: int = 32
    # Hang & desync forensics (util/forensics.py): the bounded
    # per-rank collective ledger (group/seq/kind/codec/options-sig,
    # enqueued|in_flight|done|aborted). On by default — recording is
    # two dict writes per round riding the clock reads the round-level
    # trace already pays (FORENSICS_BENCH.json: within noise). Off =
    # no ledger, no watchdog signal, autopsy bundles carry no ledgers.
    forensics_ledger: bool = True
    forensics_ledger_size: int = 256
    # Controller watchdog: a collective in_flight on any rank past
    # this deadline (or a persistent straggler signal) triggers the
    # cross-rank ledger audit — pull every rank's ledger, diff, name
    # the culprit as a collective_stall/collective_desync event + the
    # forensics_stall_rank health sentinel + a postmortem bundle.
    forensics_stall_timeout_s: float = 60.0
    # Opt-in pre-flight desync guard (train/collective.py): "step"
    # agrees the options-signature across ranks once per train step,
    # "round" before every collective — turning a codec/options
    # desync into a typed, named CollectiveDesyncError instead of a
    # ring hang. Costs one rendezvous-actor round trip per check, so
    # it is off by default (a debugging lever, per the PERF runbook).
    forensics_verify_level: str = "off"
    # Where postmortem-<step>.json bundles land ("" = <tmp>/ray_tpu_forensics).
    forensics_dir: str = ""

    # --- durable checkpoint plane (train/ckptio.py) ---
    # How long the rank-0 commit coordinator waits for every rank's
    # shard (payload + per-shard meta) of one step to become visible
    # in storage before abandoning the commit. An abandoned save is
    # INVISIBLE to restore by construction (no manifest = no
    # checkpoint) — the previous committed step keeps resolving.
    ckpt_commit_timeout_s: float = 60.0
    # Verify each shard's recorded content hash at restore. A corrupt
    # shard then fails loudly (and the controller's auto-resume falls
    # back to the previous complete checkpoint) instead of loading
    # silently-wrong optimizer state. Off trades the sha256 pass for
    # restore speed on storage you trust end-to-end.
    ckpt_verify_hash: bool = True
    # Host staging slots for the async writer's double buffering: the
    # step path only pays the snapshot copy while a free slot exists;
    # when the background writer falls this many saves behind, save()
    # blocks until a slot frees (backpressure, never silent drops).
    ckpt_stage_buffers: int = 2
    # Deterministic fault injection for the CHECKPOINT plane, sibling
    # of testing_channel_failure / testing_serve_failure. Rules
    # "<site>:<action>:<nth>[:<param>]" (comma-separated): site in
    # {shard (the per-rank payload write), commit (the manifest
    # marker write)}; action in {kill (SIGKILL this process — a
    # deterministic crash mid-save / mid-commit), error, delay
    # (sleep <param> s), torn (corrupt the write: truncated payload /
    # truncated manifest reaches the FINAL name, exercising hash and
    # parse validation)}; nth = 1-based per-site op index counted
    # process-wide. See train/ckptio.py.
    testing_ckpt_failure: str = ""

    # --- preemption-aware shutdown (runtime/worker.py + ckptio) ---
    # Grace window a worker gets on SIGTERM before the exit backstop
    # fires: preemption hooks run inside it — finish flushing the
    # in-flight async checkpoint save (+ rank-0 manifest commit),
    # mirror the ZeRO shard to the ring successor, drain metrics.
    # TPU preemption delivers SIGTERM with advance notice; this is
    # how much of that notice the worker spends saving work instead
    # of dying with it. 0 restores die-now semantics.
    preempt_grace_s: float = 5.0

    # --- cluster health plane (util/timeseries.py + util/health.py) ---
    # Master runtime off-switch for the head-side metrics time-series
    # store + SLO engine (the RAY_TPU_HEALTH env var is the process-
    # start master switch, same pattern as RAY_TPU_DEVMON). Off: no
    # store, no evaluation loop, report_metrics keeps only the latest
    # snapshot as before.
    health_enabled: bool = True
    # Raw-resolution window width and retention. Rollups derive from
    # these (timeseries.RESOLUTION_SCALES): 10s raw for 15 min, 1-min
    # for 2 h, 10-min for 24 h by default.
    health_window_s: float = 10.0
    health_retention_s: float = 900.0
    # Memory bound: max labelled series tracked; past it the least-
    # recently-updated series is evicted (health_series_dropped_total).
    health_max_series: int = 4096
    # Pinned regression baselines for the sentinels ("" = look for
    # HEALTH_BASELINE.json in the working directory).
    health_baseline_path: str = ""
    # SLO engine (Google-SRE multi-window multi-burn-rate): the "page"
    # tier fires when the error-budget burn rate exceeds slo_fast_burn
    # over BOTH fast windows ("short,long" seconds — short detects
    # fast, long stops one bad scrape from paging); the "warn" tier
    # uses the slow windows at slo_slow_burn. Defaults scale the SRE
    # workbook's 5m/1h page pair down to the store's 15-min raw
    # retention.
    slo_eval_interval_s: float = 10.0
    slo_fast_burn: float = 14.4
    slo_fast_windows_s: str = "60,300"
    slo_slow_burn: float = 3.0
    slo_slow_windows_s: str = "300,1800"
    # Derived default objectives (per-deployment ingress latency +
    # availability, collective straggler, HBM headroom) and their
    # shared latency bound / target. False = only objectives user code
    # registered via health.add_objective().
    slo_default_objectives: bool = True
    slo_latency_threshold_s: float = 1.0
    slo_target: float = 0.99

    # --- control-plane fault tolerance ---
    # Directory for durable control tables (GCS-persistence analog,
    # runtime/persistence.py). "" = in-memory only.
    control_persist_dir: str = ""

    extra: dict = field(default_factory=dict)

    @classmethod
    def from_env(cls, **overrides) -> "Config":
        """Defaults <- RAY_TPU_* environment <- explicit overrides.
        Explicit kwargs always beat the environment, even when their value
        equals the class default."""
        kw = {}
        for f in fields(cls):
            if f.name == "extra":
                continue
            kw[f.name] = _env(f.name, f.default, _FIELD_TYPES.get(f.name, str))
        kw.update(overrides)
        return cls(**kw)

    def update(self, overrides: dict[str, Any] | None) -> "Config":
        for k, v in (overrides or {}).items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self


# dataclasses.fields gives string annotations under future-annotations;
# resolve each field's concrete type once so _env can coerce env overrides.
_TYPES = {"str": str, "int": int, "float": float, "bool": bool,
          "dict": dict, "list": list}
_FIELD_TYPES = {
    f.name: _TYPES.get(str(f.type).replace("builtins.", ""), str)
    for f in fields(Config)
}


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config.from_env()
    return _global_config


def set_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
