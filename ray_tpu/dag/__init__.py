"""Compiled actor DAGs: pinned pipelines over shared-memory channels.

The compiled-graph analog (reference: python/ray/dag/compiled_dag_node.py:805,
dag/input_node.py, experimental/channel/shared_memory_channel.py): build a
static graph of actor method calls with `.bind()`, `compile()` it once —
every edge gets a pre-allocated SPSC shm ring, every actor enters a pinned
execution loop — then `execute()` streams items through with all stages
overlapped and bounded buffering for backpressure.

    with InputNode() as inp:
        h = stage1.fwd.bind(inp)
        out = stage2.fwd.bind(h)
    cd = compile(out)
    futs = [cd.execute(batch) for batch in batches]   # pipelined
    results = [f.get() for f in futs]
    cd.teardown()

Same-node edges ride POSIX shm rings (two memcpys, no RPC); cross-node
edges ride TCP channels with the same bounded-ring semantics
(dag/channel.py TcpChannel) — the DCN substrate for pipeline-parallel
inference across hosts/slices. WITHIN a slice, cross-chip tensor
movement still belongs to jit'd collectives over ICI, not the object
plane.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import (DATA, ERROR, STOP, ChannelTimeout,
                                 ShmRingChannel)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob, serialize

__all__ = ["InputNode", "MethodNode", "compile", "CompiledDag",
           "DagFuture"]


class InputNode:
    """Placeholder for the value passed to execute()."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MethodNode:
    def __init__(self, handle, method: str, args: tuple):
        self.handle = handle
        self.method = method
        self.args = args

    def experimental_compile(self, **kw) -> "CompiledDag":
        return compile(self, **kw)


class DagFuture:
    def __init__(self, dag: "CompiledDag", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._dag._result(self._seq, timeout)


class CompiledDag:
    def __init__(self, sink: MethodNode, *, nslots: int, slot_bytes: int,
                 zero_copy: bool = False):
        if not isinstance(sink, MethodNode):
            raise TypeError("compile() expects the dag's output node")
        self._nslots = nslots
        self._slot_bytes = slot_bytes
        self._zero_copy = zero_copy
        self._nodes: List[MethodNode] = []
        self._topo(sink, set())
        self._validate()
        self._channels: List[ShmRingChannel] = []
        # edge channels: producer node -> list of (consumer, arg position)
        self._in_chans: Dict[int, List[dict]] = {}   # node idx -> specs
        self._templates: Dict[int, list] = {}
        self._out_chans: Dict[int, List[dict]] = {}
        self._input_chans: List[ShmRingChannel] = []
        self._build(sink)
        self._loops = []
        self._start()
        self._next_seq = 0
        self._read_seq = 0
        self._results: Dict[int, tuple] = {}
        self._lock = threading.Lock()
        self._torn_down = False

    # --- graph wiring ---------------------------------------------------

    def _topo(self, node, seen):
        if id(node) in seen or not isinstance(node, MethodNode):
            return
        seen.add(id(node))
        for a in node.args:
            self._topo(a, seen)
        self._nodes.append(node)  # post-order == topological

    def _validate(self):
        """Reject dag shapes that would hang opaquely at runtime, and
        record each node's placement: same-node edges get shm rings,
        cross-node edges get TCP channels (the DCN substrate for
        pipeline-parallel inference across hosts/slices — reference:
        experimental/channel/ crosses nodes via plasma + torch channel;
        here a credit-windowed socket preserves ring semantics)."""
        from ray_tpu.api import _require_init, _run
        ctx = _require_init()
        self._driver_node = ctx.node_id
        self._node_placement = []      # node idx -> cluster node_id
        seen_actors = set()
        for n in self._nodes:
            aid = n.handle._actor_id
            if aid in seen_actors:
                # One pinned loop holds the actor's lock + executor
                # thread for its lifetime; a second would never start.
                raise ValueError(
                    "compiled dags pin one exec loop per actor — use a "
                    "distinct actor for each dag node")
            seen_actors.add(aid)
            _run(ctx.pool.call(ctx.head_addr, "wait_actor_alive",
                               actor_id=aid, wait_timeout=60.0))
            info = _run(ctx.pool.call(ctx.head_addr, "get_actor",
                                      actor_id=aid))
            self._node_placement.append(
                (info or {}).get("node_id") or ctx.node_id)

    def _local(self, i: Optional[int]) -> bool:
        """True when dag node i (None = the driver) runs on the
        driver's cluster node — only then is a POSIX shm ring valid
        (created driver-side, attached by name)."""
        return i is None or self._node_placement[i] == self._driver_node

    def _new_edge(self, producer: Optional[int],
                  consumer: Optional[int]) -> dict:
        """Channel spec for one edge; driver-owned endpoints are
        constructed eagerly (shm segment, or the tcp endpoint for the
        driver's side of a cross-node edge). Co-located NON-driver
        stages get a lazily-created shm ring (consumer creates it at
        attach); only genuinely cross-node edges pay TCP."""
        import uuid as _uuid

        from ray_tpu.dag.channel import TcpChannel, new_tcp_spec
        if self._local(producer) and self._local(consumer):
            ch = ShmRingChannel(create=True, nslots=self._nslots,
                                slot_bytes=self._slot_bytes)
            self._channels.append(ch)
            if producer is None:
                self._input_chans.append(ch)
            if consumer is None:
                self._sink_chan = ch
            return ch.spec()
        if producer is not None and consumer is not None and \
                self._node_placement[producer] == \
                self._node_placement[consumer]:
            # same remote node: shm ring created by the consumer side
            return {"name": f"rtch-{_uuid.uuid4().hex[:16]}",
                    "nslots": self._nslots,
                    "slot_bytes": self._slot_bytes, "lazy": True}
        spec = new_tcp_spec(self._nslots, self._slot_bytes)
        if producer is None:
            # nonblocking: the driver must always be able to return to
            # draining the sink (it is the sink's only reader); frames
            # enqueue under credit and flush from the sink pump
            ch = TcpChannel(spec, "producer", nonblocking_writes=True)
            self._channels.append(ch)
            self._input_chans.append(ch)
        if consumer is None:
            ch = TcpChannel(spec, "consumer")  # publishes endpoint now
            self._channels.append(ch)
            self._sink_chan = ch
        return spec

    def _build(self, sink: MethodNode):
        idx = {id(n): i for i, n in enumerate(self._nodes)}
        for i, n in enumerate(self._nodes):
            self._in_chans[i] = []
            self._out_chans[i] = []
            self._templates[i] = []
        for i, n in enumerate(self._nodes):
            for a in n.args:
                if isinstance(a, InputNode):
                    spec = self._new_edge(None, i)
                    self._in_chans[i].append(spec)
                    self._templates[i].append(("chan", None))
                elif isinstance(a, MethodNode):
                    spec = self._new_edge(idx[id(a)], i)
                    self._out_chans[idx[id(a)]].append(spec)
                    self._in_chans[i].append(spec)
                    self._templates[i].append(("chan", None))
                else:
                    self._templates[i].append(("const", dumps_oob(a)))
        # sink -> driver
        self._out_chans[idx[id(sink)]].append(
            self._new_edge(idx[id(sink)], None))

    def _start(self):
        from ray_tpu.api import ActorMethod
        for i, n in enumerate(self._nodes):
            spec = {"method": n.method,
                    "in_channels": self._in_chans[i],
                    "arg_template": self._templates[i],
                    "out_channels": self._out_chans[i],
                    "zero_copy": self._zero_copy}
            # retries pinned to 0: a replayed loop would attach a second
            # consumer to SPSC rings and race on the sequence counters.
            m = ActorMethod(n.handle, "__dag_exec_loop__",
                            max_task_retries=0)
            self._loops.append(m.remote(spec))

    # --- execution ------------------------------------------------------

    def execute(self, value: Any,
                timeout: Optional[float] = None) -> DagFuture:
        """Feed one item; returns a future. When the input ring is full,
        completed results are drained off the sink while waiting — so
        submitting arbitrarily many items ahead of get() can't deadlock
        the pipeline (driver blocked on full input ↔ stages blocked on
        an unread sink)."""
        if self._torn_down:
            raise RuntimeError("dag torn down")
        ser = serialize(value)
        deadline = None if timeout is None else time.monotonic() + timeout
        # Wait for space on ALL input rings BEFORE writing any: a partial
        # write followed by a timeout would leave fan-in channels skewed,
        # silently pairing mismatched items forever after. Space only
        # grows (the consumers are the stages), so write-after-check
        # cannot block.
        while not all(ch.has_space() for ch in self._input_chans):
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeout("input ring full")
            with self._lock:
                self._pump_sink(blocking=False)
            time.sleep(200e-6)
        for ch in self._input_chans:
            ch.write(ser, DATA)
        seq = self._next_seq
        self._next_seq += 1
        return DagFuture(self, seq)

    def _pump_sink(self, blocking: bool, timeout: Optional[float] = None):
        """Move any completed frames sink -> _results. Caller holds
        self._lock. Also flushes any enqueued (nonblocking) input
        frames — the pump is the driver's one guaranteed-periodic
        touchpoint, so a tail frame can never starve unflushed."""
        for ch in self._input_chans:
            if hasattr(ch, "flush"):
                try:
                    ch.flush(0.0)
                except Exception:
                    pass   # surfaced by the next write/get on that edge
        while True:
            try:
                kind, payload = self._sink_chan.read_bytes(
                    timeout if blocking else 0.0)
            except ChannelTimeout:
                if blocking:
                    raise
                return
            if kind == STOP:
                raise RuntimeError("dag torn down mid-stream")
            self._results[self._read_seq] = (kind, payload)
            self._read_seq += 1
            if blocking:
                return

    def _result(self, seq: int, timeout: Optional[float]) -> Any:
        with self._lock:
            while seq not in self._results:
                self._pump_sink(blocking=True, timeout=timeout)
        kind, payload = self._results.pop(seq)
        if kind == ERROR:
            err = loads_oob(payload)
            raise err if isinstance(err, BaseException) else \
                RuntimeError(str(err))
        return loads_oob(payload)

    def teardown(self, timeout: float = 30.0):
        if self._torn_down:
            return
        self._torn_down = True
        deadline = time.monotonic() + timeout
        from ray_tpu import api
        from ray_tpu.dag.channel import ChannelClosed
        for ch in self._input_chans:
            try:
                ch.write(b"", STOP, timeout=timeout)
                if hasattr(ch, "flush"):
                    ch.flush(min(timeout, 5.0))
            except (ChannelTimeout, ChannelClosed):
                pass    # stalled or dead stage: the drain below and
                        # close() still run
        # Drain the sink until STOP flows out: stages blocked writing
        # results into a full sink must unblock to ever see the STOP —
        # otherwise their loops would spin (holding the actor's executor
        # thread) against channels we are about to unlink.
        while time.monotonic() < deadline:
            try:
                kind, _ = self._sink_chan.read_bytes(timeout=1.0)
            except ChannelTimeout:
                continue
            except ChannelClosed:
                break     # sink stage died: nothing more will arrive
            if kind == STOP:
                break
        try:
            api.get(self._loops,
                    timeout=max(1.0, deadline - time.monotonic()))
        except Exception:
            pass
        for ch in self._channels:
            ch.close()
            ch.unlink()

    def __del__(self):
        try:
            self.teardown(timeout=1.0)
        except Exception:
            pass


def compile(sink: MethodNode, *, nslots: int = 8,
            slot_bytes: int = 4 << 20,
            zero_copy: bool = False) -> CompiledDag:
    """zero_copy=True deserializes single-input stage args directly from
    the ring slot (no copy) — only safe when stage methods do NOT retain
    references to their array arguments past the call."""
    return CompiledDag(sink, nslots=nslots, slot_bytes=slot_bytes,
                       zero_copy=zero_copy)
