"""Compiled actor DAGs: pinned pipelines over shared-memory channels.

The compiled-graph analog (reference: python/ray/dag/compiled_dag_node.py:805,
dag/input_node.py, experimental/channel/shared_memory_channel.py): build a
static graph of actor method calls with `.bind()`, `compile()` it once —
every edge gets a pre-allocated SPSC shm ring, every actor enters a pinned
execution loop — then `execute()` streams items through with all stages
overlapped and bounded buffering for backpressure.

    with InputNode() as inp:
        h = stage1.fwd.bind(inp)
        out = stage2.fwd.bind(h)
    cd = compile(out)
    futs = [cd.execute(batch) for batch in batches]   # pipelined
    results = [f.get() for f in futs]
    cd.teardown()

Same-node edges ride POSIX shm rings (two memcpys, no RPC); cross-node
edges ride TCP channels with the same bounded-ring semantics
(dag/channel.py TcpChannel) — the DCN substrate for pipeline-parallel
inference across hosts/slices. WITHIN a slice, cross-chip tensor
movement still belongs to jit'd collectives over ICI, not the object
plane.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ray_tpu.dag.channel import (DATA, ERROR, STOP, ChannelTimeout,
                                 ShmRingChannel)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob, serialize

__all__ = ["InputNode", "MethodNode", "MultiOutputNode", "allreduce",
           "compile", "CompiledDag", "DagFuture"]


class InputNode:
    """Placeholder for the value passed to execute()."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MethodNode:
    def __init__(self, handle, method: str, args: tuple):
        self.handle = handle
        self.method = method
        self.args = args

    def experimental_compile(self, **kw) -> "CompiledDag":
        return compile(self, **kw)


class MultiOutputNode:
    """Gathers several nodes' outputs into one list per executed item
    (reference: dag/output_node.py MultiOutputNode) — the sink shape for
    SPMD patterns where every participant's result matters, e.g.
    ``compile(MultiOutputNode(allreduce([...])))``."""

    def __init__(self, nodes):
        self.nodes = list(nodes)
        if not self.nodes:
            raise ValueError("MultiOutputNode needs at least one node")


class AllReduceNode:
    """Output of one participant in a dag collective. Created only by
    allreduce(); its value is the elementwise reduction of every
    participant's parent output for the same item."""

    def __init__(self, parent: MethodNode, group: dict, rank: int):
        self.parent = parent
        self.group = group
        self.rank = rank


_REDUCE_OPS = ("sum", "mean", "max", "min")


def allreduce(nodes, op: str = "sum", *, quantize: Optional[str] = None,
              chunk_bytes: Optional[int] = None,
              impl: Optional[str] = None,
              payload_bytes: Optional[int] = None):
    """Bind an allreduce across DAG actors (reference:
    dag/collective_node.py:252 + experimental/collective/operations.py —
    which lower to NCCL; here the collective rides the host object plane
    over the same placement-aware channels as data edges, shm when
    co-located, TCP across nodes. Within one process holding a mesh,
    tensor reductions belong to jit'd psum over ICI, not the DAG).

    Groups of more than two participants compile to a chunked ring
    reduce-scatter + allgather (dag/ring.py): per-participant bandwidth
    is O(S) independent of group size, and segments pipeline around the
    ring. Two-participant groups keep the star reduce (same traffic,
    fewer hops). ``quantize="int8"`` ships chunks block-quantized
    (~26% of the fp32 wire bytes; float32 accumulation, per-round error
    bound exported as the ``allreduce_quant_error`` gauge).
    ``chunk_bytes`` tunes the pipeline granularity (default 1 MB,
    clamped to the channel slot size).

    ``impl`` defaults to "auto": with a ``payload_bytes`` hint (the
    approximate serialized size of ONE participant's value), the
    topology is chosen by the in-situ auto-tuner's table when one has
    been measured (dag/tuner.py), else by the static crossover — star
    at or below ``Config.allreduce_star_max_bytes`` (default 4 MB: a
    ring round is 3(N-1) sequential hops, and hop latency beats the
    root's O(N·S) traffic on small frames — ALLREDUCE_BENCH's
    1 MB/4p row has the star at 0.8x the ring), ring above it.
    Without a hint the choice falls back to group size (ring for N>2,
    hierarchical when the participants additionally span nodes with
    co-located pairs). Explicit "star"/"ring"/"hier" always win
    ("hier" degrades to the flat ring when the placement has no
    two-level topology); ``quantize`` forces a ring family (the star
    has no wire codec). "hier" compiles the group as a ring-of-rings
    (per-node shm intra rings + one TCP ring over node leaders +
    intra broadcast): cross-node wire drops to ~1/ranks-per-node, and
    codecs apply to the cross-node leg only.

    Takes one upstream MethodNode per participant actor; returns one
    AllReduceNode per participant, each carrying the reduced value. The
    raw parent outputs are consumed by the collective and cannot also be
    bound elsewhere."""
    nodes = list(nodes)
    if len(nodes) < 2:
        raise ValueError("allreduce needs at least 2 participants")
    if op not in _REDUCE_OPS:
        raise ValueError(f"op must be one of {_REDUCE_OPS}, got {op!r}")
    if quantize not in (None, "int8"):
        raise ValueError(f"quantize must be None or 'int8', "
                         f"got {quantize!r}")
    if impl not in (None, "auto", "star", "ring", "hier"):
        raise ValueError(f"impl must be None, 'auto', 'star', 'ring' "
                         f"or 'hier', got {impl!r}")
    if impl == "star" and quantize is not None:
        raise ValueError("the star reduce does not support quantize; "
                         "use impl='ring' (or leave impl unset)")
    if payload_bytes is not None and payload_bytes < 0:
        raise ValueError("payload_bytes must be >= 0")
    for n in nodes:
        if not isinstance(n, MethodNode):
            raise TypeError(
                "allreduce participants must be bound method nodes")
    import uuid as _uuid
    group = {"id": _uuid.uuid4().hex[:16], "op": op, "size": len(nodes),
             "quantize": quantize, "chunk_bytes": chunk_bytes,
             "impl": impl, "payload_bytes": payload_bytes,
             "members": []}
    out = [AllReduceNode(n, group, rank) for rank, n in enumerate(nodes)]
    group["members"] = out
    return out


def _resolve_impl(group: dict, hier_ok: bool = False) -> str:
    """Star vs ring vs ring-of-rings for one collective group, resolved
    at compile time (the topologies wire different channels, so the
    choice cannot move per-round). Explicit impl wins; quantize forces
    a ring family; a payload hint consults the in-situ auto-tuner's
    table when one exists (dag/tuner.py — populated by any
    tuning-enabled ring's first collective, or a bench run) and falls
    back to the static benchmarked crossover
    (Config.allreduce_star_max_bytes) otherwise; no hint falls back to
    group size. ``hier_ok`` says the participants actually span nodes
    with co-located pairs — without that the hierarchical topology
    does not exist and "hier" degrades to the flat ring."""
    impl = group.get("impl")
    if impl in ("star", "ring"):
        return impl
    if impl == "hier":
        return "hier" if hier_ok else "ring"
    if group["size"] < 2:
        return "star"            # a ring needs two ranks to exist
    if group.get("quantize"):
        # a codec needs a ring; the hierarchy additionally confines it
        # to the cross-node leg
        if hier_ok:
            pb = group.get("payload_bytes")
            from ray_tpu.dag import tuner
            t = tuner.choose_impl(pb, group["size"], hierarchical=True)
            if t == "hier":
                return "hier"
        return "ring"
    pb = group.get("payload_bytes")
    if pb is not None:
        from ray_tpu.config import get_config
        from ray_tpu.dag import tuner
        tuned = tuner.choose_impl(pb, group["size"],
                                  hierarchical=hier_ok)
        if tuned is not None:
            return tuned
        thr = getattr(get_config(), "allreduce_star_max_bytes",
                      4 * 1024 * 1024)
        return "star" if pb <= thr else "ring"
    if hier_ok and group["size"] > 2:
        return "hier"
    return "ring" if group["size"] > 2 else "star"


class DagFuture:
    def __init__(self, dag: "CompiledDag", seq: int):
        self._dag = dag
        self._seq = seq

    def get(self, timeout: Optional[float] = None) -> Any:
        return self._dag._result(self._seq, timeout)


class CompiledDag:
    def __init__(self, sink, *, nslots: int, slot_bytes: int,
                 zero_copy: bool = False, overlap: bool = True,
                 collective_timeout_s: float = 600.0):
        self._coll_timeout = collective_timeout_s
        if isinstance(sink, MultiOutputNode):
            self._sink_members = list(sink.nodes)
            self._unwrap_single = False   # 1-member MultiOutput -> [v]
        elif isinstance(sink, (MethodNode, AllReduceNode)):
            self._sink_members = [sink]
            self._unwrap_single = True
        else:
            raise TypeError("compile() expects the dag's output node")
        for m in self._sink_members:
            if not isinstance(m, (MethodNode, AllReduceNode)):
                raise TypeError(
                    "MultiOutputNode members must be dag nodes")
        self._nslots = nslots
        self._slot_bytes = slot_bytes
        self._zero_copy = zero_copy
        self._overlap = overlap and not zero_copy
        self._nodes: List[MethodNode] = []
        self._groups: List[dict] = []       # allreduce groups in the dag
        self._groups_seen = set()
        seen = set()
        for m in self._sink_members:
            self._topo(m, seen)
        self._validate()
        self._channels: List[ShmRingChannel] = []
        # edge channels: producer node -> list of (consumer, arg position)
        self._in_chans: Dict[int, List[dict]] = {}   # node idx -> specs
        self._templates: Dict[int, list] = {}
        self._out_chans: Dict[int, List[dict]] = {}
        self._input_chans: List[ShmRingChannel] = []
        self._sink_chans: List = []
        self._coll_spec: Dict[int, dict] = {}        # node idx -> role spec
        self._build()
        self._loops = []
        self.stage_stats: Optional[List[dict]] = None
        self._start()
        self._next_seq = 0
        self._read_seq = 0
        self._results: Dict[int, list] = {}
        self._sink_bufs: List[list] = [[] for _ in self._sink_chans]
        self._lock = threading.Lock()
        self._torn_down = False

    # --- graph wiring ---------------------------------------------------

    def _topo(self, node, seen):
        if isinstance(node, AllReduceNode):
            # Reaching ANY participant pulls in the WHOLE group: every
            # member's parent must run a loop or the collective hangs.
            g = node.group
            if g["id"] not in self._groups_seen:
                self._groups_seen.add(g["id"])
                self._groups.append(g)
                for m in g["members"]:
                    self._topo(m.parent, seen)
            return
        if id(node) in seen or not isinstance(node, MethodNode):
            return
        seen.add(id(node))
        for a in node.args:
            self._topo(a, seen)
        self._nodes.append(node)  # post-order == topological

    def _validate(self):
        """Reject dag shapes that would hang opaquely at runtime, and
        record each node's placement: same-node edges get shm rings,
        cross-node edges get TCP channels (the DCN substrate for
        pipeline-parallel inference across hosts/slices — reference:
        experimental/channel/ crosses nodes via plasma + torch channel;
        here a credit-windowed socket preserves ring semantics)."""
        from ray_tpu.api import _require_init, _run
        ctx = _require_init()
        self._driver_node = ctx.node_id
        self._node_placement = []      # node idx -> cluster node_id
        seen_actors = set()
        for n in self._nodes:
            aid = n.handle._actor_id
            if aid in seen_actors:
                # One pinned loop holds the actor's lock + executor
                # thread for its lifetime; a second would never start.
                raise ValueError(
                    "compiled dags pin one exec loop per actor — use a "
                    "distinct actor for each dag node")
            seen_actors.add(aid)
            _run(ctx.pool.call(ctx.head_addr, "wait_actor_alive",
                               actor_id=aid, wait_timeout=60.0))
            info = _run(ctx.pool.call(ctx.head_addr, "get_actor",
                                      actor_id=aid))
            self._node_placement.append(
                (info or {}).get("node_id") or ctx.node_id)
        # Collective participants: the raw parent output is consumed by
        # the reduce — binding it elsewhere too would need a second fan-out
        # edge carrying the UNreduced value, which allreduce() forbids.
        parents = {}
        for g in self._groups:
            for m in g["members"]:
                if id(m.parent) in parents:
                    raise ValueError(
                        "a node cannot participate in two allreduce groups")
                parents[id(m.parent)] = g["id"]
        if parents:
            consumers = [a for n in self._nodes for a in n.args]
            consumers += self._sink_members
            for a in consumers:
                if isinstance(a, MethodNode) and id(a) in parents:
                    raise ValueError(
                        "a collective participant's raw output cannot be "
                        "bound downstream — bind its AllReduceNode instead")
        # Shape checks belong HERE, before _build creates any channel:
        # raising mid-build would leak shm segments / TCP listeners
        # (CompiledDag.__init__ aborts with nothing to teardown).
        sink_nodes = [m.parent if isinstance(m, AllReduceNode) else m
                      for m in self._sink_members]
        if len({id(n) for n in sink_nodes}) != len(sink_nodes):
            raise ValueError(
                "the same node cannot appear twice in MultiOutputNode")

    def _local(self, i: Optional[int]) -> bool:
        """True when dag node i (None = the driver) runs on the
        driver's cluster node — only then is a POSIX shm ring valid
        (created driver-side, attached by name)."""
        return i is None or self._node_placement[i] == self._driver_node

    def _new_edge(self, producer: Optional[int],
                  consumer: Optional[int]) -> dict:
        """Channel spec for one edge; driver-owned endpoints are
        constructed eagerly (shm segment, or the tcp endpoint for the
        driver's side of a cross-node edge). Co-located NON-driver
        stages get a lazily-created shm ring (consumer creates it at
        attach); only genuinely cross-node edges pay TCP."""
        import uuid as _uuid

        from ray_tpu.dag.channel import TcpChannel, new_tcp_spec
        if self._local(producer) and self._local(consumer):
            ch = ShmRingChannel(create=True, nslots=self._nslots,
                                slot_bytes=self._slot_bytes)
            self._channels.append(ch)
            if producer is None:
                self._input_chans.append(ch)
            if consumer is None:
                self._sink_chans.append(ch)
            return ch.spec()
        if producer is not None and consumer is not None and \
                self._node_placement[producer] == \
                self._node_placement[consumer]:
            # same remote node: shm ring created by the consumer side
            return {"name": f"rtch-{_uuid.uuid4().hex[:16]}",
                    "nslots": self._nslots,
                    "slot_bytes": self._slot_bytes, "lazy": True}
        spec = new_tcp_spec(self._nslots, self._slot_bytes)
        if producer is None:
            # nonblocking: the driver must always be able to return to
            # draining the sink (it is the sink's only reader); frames
            # enqueue under credit and flush from the sink pump
            ch = TcpChannel(spec, "producer", nonblocking_writes=True)
            self._channels.append(ch)
            self._input_chans.append(ch)
        if consumer is None:
            ch = TcpChannel(spec, "consumer")  # publishes endpoint now
            self._channels.append(ch)
            self._sink_chans.append(ch)
        return spec

    def _build(self):
        idx = {id(n): i for i, n in enumerate(self._nodes)}
        for i, n in enumerate(self._nodes):
            self._in_chans[i] = []
            self._out_chans[i] = []
            self._templates[i] = []
        for i, n in enumerate(self._nodes):
            for a in n.args:
                if isinstance(a, InputNode):
                    spec = self._new_edge(None, i)
                    self._in_chans[i].append(spec)
                    self._templates[i].append(("chan", None))
                elif isinstance(a, (MethodNode, AllReduceNode)):
                    # An AllReduceNode's value leaves from its PARENT's
                    # loop (the reduce happens in-loop before writes).
                    src = idx[id(a.parent)] if isinstance(a, AllReduceNode) \
                        else idx[id(a)]
                    spec = self._new_edge(src, i)
                    self._out_chans[src].append(spec)
                    self._in_chans[i].append(spec)
                    self._templates[i].append(("chan", None))
                else:
                    self._templates[i].append(("const", dumps_oob(a)))
        # collective wiring. Ring (N>2, and every quantized group): one
        # directed edge rank r -> rank (r+1)%N; chunked reduce-scatter +
        # allgather makes per-participant traffic O(S) independent of N
        # (dag/ring.py). Star (N<=2 fallback): rank 0 hosts the reduce,
        # every other participant sends up / receives the result down.
        for g in self._groups:
            idxs = [idx[id(m.parent)] for m in g["members"]]
            # the hierarchical topology exists only when the members
            # span >1 cluster node AND some node hosts >=2 of them
            # (otherwise there is no intra ring to save bytes with)
            plc = [self._node_placement[i] for i in idxs]
            by_node: Dict[str, list] = {}
            for r, p in enumerate(plc):
                by_node.setdefault(p, []).append(r)
            hier_ok = len(by_node) > 1 and \
                max(len(v) for v in by_node.values()) > 1
            impl = _resolve_impl(g, hier_ok=hier_ok)
            if impl == "hier":
                self._build_hier_group(g, idxs, by_node)
                continue
            if impl == "ring":
                n = g["size"]
                edges = [self._new_edge(idxs[r], idxs[(r + 1) % n])
                         for r in range(n)]
                for r, i in enumerate(idxs):
                    self._coll_spec[i] = {
                        "role": "ring", "rank": r, "size": n,
                        "op": g["op"],
                        # distinct trace lane per collective group —
                        # to_chrome keys flow edges by (group, cid),
                        # so two rings sharing a label would get
                        # cross-wired arrows
                        "group": g["id"][:12],
                        "timeout_s": self._coll_timeout,
                        "quantize": g.get("quantize"),
                        "chunk_bytes": g.get("chunk_bytes"),
                        "to_next": edges[r],
                        "from_prev": edges[(r - 1) % n]}
                continue
            root = idxs[0]
            root_spec = {"role": "root", "op": g["op"], "size": g["size"],
                         "timeout_s": self._coll_timeout,
                         "up": [], "down": []}
            for leaf in idxs[1:]:
                up = self._new_edge(leaf, root)
                down = self._new_edge(root, leaf)
                root_spec["up"].append(up)
                root_spec["down"].append(down)
                self._coll_spec[leaf] = {"role": "leaf", "op": g["op"],
                                         "size": g["size"],
                                         "timeout_s": self._coll_timeout,
                                         "up": up, "down": down}
            self._coll_spec[root] = root_spec
        # sinks -> driver: one channel per member, combined in lockstep
        # (duplicates were rejected in _validate, before channels exist)
        for m in self._sink_members:
            si = idx[id(m.parent)] if isinstance(m, AllReduceNode) \
                else idx[id(m)]
            self._out_chans[si].append(self._new_edge(si, None))

    def _build_hier_group(self, g: dict, idxs: List[int],
                          by_node: Dict[str, list]) -> None:
        """Wire one collective group as a ring-of-rings (dag/ring.py
        HierarchicalReducer): per-node intra rings over shm edges, one
        cross-node ring over the first member of each node (the
        elected leader), and the intra broadcast riding the same intra
        edges. Codec options apply to the inter (TCP) leg only — the
        wiring puts them in the inter sub-spec and nowhere else."""
        from ray_tpu.dag.ring import build_hier_specs
        gid = g["id"][:12]
        nodes = list(by_node.values())       # member positions per node
        leaders = [mlist[0] for mlist in nodes]
        L = len(leaders)
        specs = build_hier_specs(
            [len(v) for v in nodes],
            # intra: co-located members (shm / lazy shm by placement)
            lambda i, j: self._new_edge(
                idxs[nodes[i][j]],
                idxs[nodes[i][(j + 1) % len(nodes[i])]]),
            # inter: node leaders (cross-node: TCP by placement)
            lambda i: self._new_edge(idxs[leaders[i]],
                                     idxs[leaders[(i + 1) % L]]),
            op=g["op"], timeout_s=self._coll_timeout, group=gid,
            quantize=g.get("quantize"),
            chunk_bytes=g.get("chunk_bytes"))
        flat_positions = [pos for mlist in nodes for pos in mlist]
        for pos, spec in zip(flat_positions, specs):
            self._coll_spec[idxs[pos]] = spec

    def _start(self):
        from ray_tpu.api import ActorMethod
        for i, n in enumerate(self._nodes):
            spec = {"method": n.method,
                    "in_channels": self._in_chans[i],
                    "arg_template": self._templates[i],
                    "out_channels": self._out_chans[i],
                    "zero_copy": self._zero_copy,
                    "overlap": self._overlap,
                    "collective": self._coll_spec.get(i)}
            # retries pinned to 0: a replayed loop would attach a second
            # consumer to SPSC rings and race on the sequence counters.
            m = ActorMethod(n.handle, "__dag_exec_loop__",
                            max_task_retries=0)
            self._loops.append(m.remote(spec))

    # --- execution ------------------------------------------------------

    def execute(self, value: Any,
                timeout: Optional[float] = None) -> DagFuture:
        """Feed one item; returns a future. When the input ring is full,
        completed results are drained off the sink while waiting — so
        submitting arbitrarily many items ahead of get() can't deadlock
        the pipeline (driver blocked on full input ↔ stages blocked on
        an unread sink)."""
        if self._torn_down:
            raise RuntimeError("dag torn down")
        ser = serialize(value)
        deadline = None if timeout is None else time.monotonic() + timeout
        # Wait for space on ALL input rings BEFORE writing any: a partial
        # write followed by a timeout would leave fan-in channels skewed,
        # silently pairing mismatched items forever after. Space only
        # grows (the consumers are the stages), so write-after-check
        # cannot block.
        while not all(ch.has_space() for ch in self._input_chans):
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeout("input ring full")
            with self._lock:
                self._pump_sink(blocking=False)
            time.sleep(200e-6)
        for ch in self._input_chans:
            ch.write(ser, DATA)
        seq = self._next_seq
        self._next_seq += 1
        return DagFuture(self, seq)

    def _pump_sink(self, blocking: bool, timeout: Optional[float] = None):
        """Move any completed frames sink -> _results. Caller holds
        self._lock. Also flushes any enqueued (nonblocking) input
        frames — the pump is the driver's one guaranteed-periodic
        touchpoint, so a tail frame can never starve unflushed."""
        for ch in self._input_chans:
            if hasattr(ch, "flush"):
                try:
                    ch.flush(0.0)
                except Exception:
                    pass   # surfaced by the next write/get on that edge
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            # complete ONE seq: a frame from EVERY sink channel, in
            # lockstep (partial fills survive a timeout in _sink_bufs)
            for j, ch in enumerate(self._sink_chans):
                if self._sink_bufs[j]:
                    continue
                if blocking:
                    left = None if deadline is None else \
                        max(deadline - time.monotonic(), 0.0)
                    self._sink_bufs[j].append(ch.read_bytes(left))
                else:
                    try:
                        self._sink_bufs[j].append(ch.read_bytes(0.0))
                    except ChannelTimeout:
                        return
            frames = [buf.pop(0) for buf in self._sink_bufs]
            if any(k == STOP for k, _ in frames):
                raise RuntimeError("dag torn down mid-stream")
            self._results[self._read_seq] = frames
            self._read_seq += 1
            if blocking:
                return

    def _result(self, seq: int, timeout: Optional[float]) -> Any:
        with self._lock:
            while seq not in self._results:
                self._pump_sink(blocking=True, timeout=timeout)
        frames = self._results.pop(seq)
        for kind, payload in frames:
            if kind == ERROR:
                err = loads_oob(payload)
                raise err if isinstance(err, BaseException) else \
                    RuntimeError(str(err))
        vals = [loads_oob(p) for _, p in frames]
        return vals[0] if self._unwrap_single else vals

    def teardown(self, timeout: float = 30.0):
        if self._torn_down:
            return
        self._torn_down = True
        deadline = time.monotonic() + timeout
        from ray_tpu import api
        from ray_tpu.dag.channel import ChannelClosed
        stop_seen = [False] * len(self._sink_chans)

        def _drain_sinks(block_s: float):
            """Pull whatever sits in the sinks; mark channels whose STOP
            arrived. Draining is what unwinds a wedged pipeline: stages
            blocked writing results into a full sink must unblock to
            ever see the STOP — otherwise their loops would spin
            (holding the actor's executor thread) against channels we
            are about to unlink."""
            for j, ch in enumerate(self._sink_chans):
                if stop_seen[j]:
                    continue
                wait = block_s
                try:
                    while True:
                        kind, _ = ch.read_bytes(wait)
                        if kind == STOP:
                            stop_seen[j] = True
                            break
                        wait = 0.0   # opportunistic after the first
                except ChannelTimeout:
                    pass
                except ChannelClosed:
                    stop_seen[j] = True   # stage died: nothing more

        # Phase 1: place STOP on every input edge. A wedged pipeline
        # (stage blocked writing a full sink -> prefetch queue full ->
        # reader not consuming -> input ring full) only unwinds if the
        # sink is drained WHILE trying — never burn the whole budget
        # blocking on one full input ring.
        pending_stop = list(self._input_chans)
        while pending_stop and time.monotonic() < deadline:
            for ch in list(pending_stop):
                try:
                    ch.write(b"", STOP, timeout=0.2)
                    if hasattr(ch, "flush"):
                        ch.flush(0.0)
                    pending_stop.remove(ch)
                except ChannelTimeout:
                    pass                      # ring still full: drain more
                except ChannelClosed:
                    pending_stop.remove(ch)   # consumer stage is gone
            _drain_sinks(0.0)
        # Phase 2: drain until STOP flows out of every sink.
        while not all(stop_seen) and time.monotonic() < deadline:
            _drain_sinks(0.5)
        try:
            # Keep the per-stage results: timing/overlap stats
            # ({processed, timing, items}) readable via stage_stats.
            self.stage_stats = api.get(
                self._loops, timeout=max(1.0, deadline - time.monotonic()))
        except Exception:
            pass
        for ch in self._channels:
            ch.close()
            ch.unlink()

    def __del__(self):
        try:
            self.teardown(timeout=1.0)
        except Exception:
            pass


def compile(sink, *, nslots: int = 8,
            slot_bytes: int = 4 << 20,
            zero_copy: bool = False,
            overlap: bool = True,
            collective_timeout_s: float = 600.0) -> CompiledDag:
    """zero_copy=True deserializes single-input stage args directly from
    the ring slot (no copy) — only safe when stage methods do NOT retain
    references to their array arguments past the call (and disables
    overlap: the slot window cannot outlive a prefetch).

    overlap=True (default) compiles each stage to an overlapped operation
    schedule — a reader thread prefetches the NEXT item's inputs while
    the current item computes (reference: dag/dag_node_operation.py:86
    compiles per-actor READ/COMPUTE/WRITE schedules for the same reason).
    Cross-node TCP receives hide under compute; per-item recv/compute
    spans land in the trace and in CompiledDag.stage_stats."""
    return CompiledDag(sink, nslots=nslots, slot_bytes=slot_bytes,
                       zero_copy=zero_copy, overlap=overlap,
                       collective_timeout_s=collective_timeout_s)
