"""Single-producer/single-consumer shared-memory ring channel.

Transport for compiled actor DAGs (reference:
python/ray/experimental/channel/shared_memory_channel.py — which
round-trips through plasma; here slots live in one pre-allocated POSIX
shm segment, so steady-state transfers are two memcpys and no RPC).

Layout: [128B header | nslots * (8B len+kind | slot_bytes payload)].
Header holds write_seq (offset 0) and read_seq (offset 64) on separate
cache lines. SPSC with monotonic sequence counters needs no locks on
x86-64 (TSO: the payload store is visible before the seq increment;
aligned 8-byte stores are atomic). Readers/writers poll with a short
adaptive sleep — the microsecond-scale cost only matters at rest.

Frames are tagged DATA / ERROR / STOP so exceptions and teardown ride
the same path as values.
"""

from __future__ import annotations

import time
import uuid
from multiprocessing import shared_memory
from typing import Optional

HDR = 128
SLOT_HDR = 8  # u32 length + u8 kind + 3B pad

DATA, ERROR, STOP = 0, 1, 2


class ChannelClosed(Exception):
    pass


class ChannelTimeout(Exception):
    pass


class ShmRingChannel:
    """One direction, one producer process, one consumer process."""

    def __init__(self, name: Optional[str] = None, *, nslots: int = 8,
                 slot_bytes: int = 1 << 20, create: bool = False):
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        size = HDR + nslots * (SLOT_HDR + slot_bytes)
        if create:
            name = name or f"rtch-{uuid.uuid4().hex[:16]}"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=size)
            self._shm.buf[:HDR] = b"\x00" * HDR
        else:
            assert name is not None
            self._shm = shared_memory.SharedMemory(name=name)
        self.name = name
        self._seqs = self._shm.buf.cast("Q")  # [0]=write_seq, [8]=read_seq
        # Native fast path (portable atomics + GIL-free waits + C memcpy);
        # None -> pure-Python fallback below.
        from ray_tpu._native import load_ringbuf
        self._lib = load_ringbuf()
        self._cbase = None
        if self._lib is not None:
            import ctypes
            self._cbase = ctypes.cast(
                (ctypes.c_uint8 * size).from_buffer(self._shm.buf),
                ctypes.POINTER(ctypes.c_uint8))

    # seq accessors -----------------------------------------------------
    @property
    def _wseq(self) -> int:
        return self._seqs[0]

    @_wseq.setter
    def _wseq(self, v: int):
        self._seqs[0] = v

    @property
    def _rseq(self) -> int:
        return self._seqs[8]

    @_rseq.setter
    def _rseq(self, v: int):
        self._seqs[8] = v

    def _slot(self, seq: int):
        off = HDR + (seq % self.nslots) * (SLOT_HDR + self.slot_bytes)
        return off

    # producer ----------------------------------------------------------
    def has_space(self) -> bool:
        """True if a write would not block. Only the consumer can change
        this from False to True, so a single producer may rely on it."""
        if self._lib is not None and self._cbase is not None:
            return bool(self._lib.rb_has_space(self._cbase, self.nslots))
        return self._wseq - self._rseq < self.nslots

    def write(self, payload, kind: int = DATA,
              timeout: Optional[float] = None):
        """payload: bytes-like, or an object with (frame_nbytes,
        write_into) — ray_tpu Serialized — written zero-copy."""
        if hasattr(payload, "write_into"):
            n = payload.frame_nbytes
        else:
            n = len(payload)
        if n > self.slot_bytes:
            raise ValueError(
                f"frame of {n} B exceeds channel slot size "
                f"{self.slot_bytes} B; compile the dag with a larger "
                f"slot_bytes")
        native = self._lib is not None and self._cbase is not None
        if native and not hasattr(payload, "write_into"):
            data = bytes(payload)  # n re-derived: a memoryview's len()
            n = len(data)          # counts items, not bytes
            if n > self.slot_bytes:
                raise ValueError(
                    f"frame of {n} B exceeds channel slot size "
                    f"{self.slot_bytes} B")
            rc = self._lib.rb_write(
                self._cbase, self.nslots, self.slot_bytes,
                data, n, kind,
                -1.0 if timeout is None else float(timeout))
            if rc == -1:
                raise ChannelTimeout("channel full")
            return
        seq = self._wseq
        if native:
            # Zero-copy (Serialized) path: block for space in native
            # code (GIL-free), fill the slot from Python, then publish
            # WITH a futex wake — a sleeping native reader would
            # otherwise only notice at its re-check cap.
            rc = self._lib.rb_wait_space(
                self._cbase, self.nslots,
                -1.0 if timeout is None else float(timeout))
            if rc == -1:
                raise ChannelTimeout("channel full")
        else:
            self._wait(lambda: seq - self._rseq < self.nslots, timeout,
                       "channel full")
        off = self._slot(seq)
        buf = self._shm.buf
        if hasattr(payload, "write_into"):
            payload.write_into(buf[off + SLOT_HDR:off + SLOT_HDR + n])
        else:
            buf[off + SLOT_HDR:off + SLOT_HDR + n] = bytes(payload)
        buf[off:off + 4] = n.to_bytes(4, "little")
        buf[off + 4] = kind
        if native:
            self._lib.rb_publish_write(self._cbase)
        else:
            self._wseq = seq + 1  # release: makes the slot visible

    # consumer ----------------------------------------------------------
    def read_with(self, fn, timeout: Optional[float] = None):
        """Run fn(kind, memoryview-of-frame) on the next frame WITHOUT
        copying; the slot is released only after fn returns, so the view
        (and anything deserialized zero-copy from it) must not escape."""
        if self._lib is not None and self._cbase is not None:
            off = self._lib.rb_wait_readable(  # GIL-free wait
                self._cbase, self.nslots, self.slot_bytes,
                -1.0 if timeout is None else float(timeout))
            if off < 0:
                raise ChannelTimeout("channel empty")
            buf = self._shm.buf
            n = int.from_bytes(buf[off:off + 4], "little")
            kind = buf[off + 4]
            try:
                return fn(kind, buf[off + SLOT_HDR:off + SLOT_HDR + n])
            finally:
                self._lib.rb_release(self._cbase)
        seq = self._rseq
        self._wait(lambda: self._wseq > seq, timeout, "channel empty")
        off = self._slot(seq)
        buf = self._shm.buf
        n = int.from_bytes(buf[off:off + 4], "little")
        kind = buf[off + 4]
        try:
            return fn(kind, buf[off + SLOT_HDR:off + SLOT_HDR + n])
        finally:
            self._rseq = seq + 1  # release the slot for the producer

    def read_bytes(self, timeout: Optional[float] = None):
        # read_with already uses the native GIL-free wait when available
        # and copies exactly once.
        return self.read_with(lambda k, mv: (k, bytes(mv)), timeout)

    @staticmethod
    def _wait(cond, timeout, what):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 20e-6
        while not cond():
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeout(what)
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)

    # lifecycle ---------------------------------------------------------
    def close(self):
        self._cbase = None  # drop the ctypes buffer export first
        try:
            self._seqs.release()
        except Exception:
            pass
        self._seqs = None
        # tolerant close: a reader may still hold a zero-copy payload
        # view; leak the mapping rather than arm a raising finalizer
        from ray_tpu.runtime.object_store import _safe_close
        _safe_close(self._shm)

    def unlink(self):
        try:
            self._shm.unlink()
        except Exception:
            pass

    def spec(self) -> dict:
        return {"name": self.name, "nslots": self.nslots,
                "slot_bytes": self.slot_bytes}

    @classmethod
    def attach(cls, spec: dict) -> "ShmRingChannel":
        return cls(spec["name"], nslots=spec["nslots"],
                   slot_bytes=spec["slot_bytes"])
