"""Single-producer/single-consumer shared-memory ring channel.

Transport for compiled actor DAGs (reference:
python/ray/experimental/channel/shared_memory_channel.py — which
round-trips through plasma; here slots live in one pre-allocated POSIX
shm segment, so steady-state transfers are two memcpys and no RPC).

Layout: [128B header | nslots * (8B len+kind | slot_bytes payload)].
Header holds write_seq (offset 0) and read_seq (offset 64) on separate
cache lines. SPSC with monotonic sequence counters needs no locks on
x86-64 (TSO: the payload store is visible before the seq increment;
aligned 8-byte stores are atomic). Readers/writers poll with a short
adaptive sleep — the microsecond-scale cost only matters at rest.

Frames are tagged DATA / ERROR / STOP so exceptions and teardown ride
the same path as values.
"""

from __future__ import annotations

import threading
import time
import uuid
from multiprocessing import shared_memory
from typing import Optional

HDR = 128
SLOT_HDR = 8  # u32 length + u8 kind + 3B pad

DATA, ERROR, STOP = 0, 1, 2


class ChannelClosed(Exception):
    pass


class ChannelTimeout(Exception):
    pass


class ChannelAttachRefused(ChannelClosed):
    """A producer's connect was refused for the whole per-call budget
    BEFORE any connection existed. Still a ChannelClosed for ordinary
    callers (dag teardown reads it as "consumer stage is gone"), but
    sliced ring waits retry it up to the ring timeout: during elastic
    re-formation a consumer mid-restart refuses connects for longer
    than one 0.25 s abort slice, and giving up on the first slice
    would frame a live peer as dead and collapse the reshard into a
    full restart. Resets on an ESTABLISHED connection stay instantly
    fatal — only the attach phase is ambiguous."""


class ChaosInjectedTimeout(ChannelTimeout):
    """A testing_channel_failure read-drop. Subclasses ChannelTimeout
    so ordinary timeout handling applies, but sliced-wait retry loops
    (RingReducer._op_sliced) re-raise it instead of retrying — an
    injected fault fires exactly once, so retrying would silently
    nullify it (the counter is already past nth)."""
    chaos_injected = True


# --- deterministic chaos plane ------------------------------------------
#
# The channel-layer sibling of the RPC plane's fault injection
# (runtime/rpc.py ChaosPlan, reference: src/ray/rpc/rpc_chaos.h):
# Config.testing_channel_failure arms repeatable faults on the DAG
# transports so elastic-training recovery is exercised by injection,
# not by hand-timed process kills. Rules fire on the Nth matching op
# counted PROCESS-WIDE — in a ring collective each participant's op
# sequence is deterministic, so "write:kill:17" dies at the same
# pipeline position every run.

class ChannelChaos:
    """Parsed testing_channel_failure rules + per-op trigger counters.

    Spec: comma-separated ``<op>:<action>:<nth>[:<param>]`` —
      op      "write" | "read" (both channel flavors)
      action  "delay" (sleep ``param`` seconds, then proceed)
              "drop"  (write: silently discard the frame — the peer
                       starves and times out, a lossy-link simulation;
                       read: raise ChannelTimeout once)
              "kill"  (SIGKILL this process: a deterministic
                       mid-collective worker death)
      nth     1-based index of the matching op in this process
      param   seconds (delay only; default 0.1)
    """

    _ACTIONS = ("delay", "drop", "kill")

    def __init__(self, spec: str):
        self.rules = []
        for part in filter(None, (spec or "").split(",")):
            bits = part.strip().split(":")
            if len(bits) < 3:
                raise ValueError(
                    f"testing_channel_failure rule {part!r}: expected "
                    f"<op>:<action>:<nth>[:<param>]")
            op, action, nth = bits[0], bits[1], int(bits[2])
            if op not in ("write", "read"):
                raise ValueError(
                    f"testing_channel_failure op must be write|read, "
                    f"got {op!r}")
            if action not in self._ACTIONS:
                raise ValueError(
                    f"testing_channel_failure action must be one of "
                    f"{self._ACTIONS}, got {action!r}")
            if nth < 1:
                raise ValueError(
                    f"testing_channel_failure nth must be >= 1, "
                    f"got {nth}")
            param = float(bits[3]) if len(bits) > 3 else 0.1
            self.rules.append(
                {"op": op, "action": action, "nth": nth,
                 "param": param, "count": 0})

    def fire(self, op: str) -> Optional[str]:
        """Advance counters for ``op``; returns the action to apply at
        this call site ("drop") after executing side-effectful ones
        (delay sleeps here, kill never returns)."""
        out = None
        for r in self.rules:
            if r["op"] != op:
                continue
            r["count"] += 1
            if r["count"] != r["nth"]:
                continue
            if r["action"] == "delay":
                time.sleep(r["param"])
            elif r["action"] == "kill":
                import os
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            else:
                out = "drop"
        return out


_chaos: Optional[ChannelChaos] = None
_chaos_loaded = False
_chaos_tl = threading.local()


def chaos_mark_retry(flag: bool) -> None:
    """Nth-op counters are per LOGICAL op: a sliced wait that re-enters
    the same channel op after a ChannelTimeout (RingReducer._op_sliced
    retries every abort slice) marks itself here so retries don't
    advance the counters — otherwise a stall anywhere in the ring would
    turn the op index into a wall-clock count and "kill at op 17" would
    fire at a different pipeline position per run."""
    _chaos_tl.retry = flag


def _chaos_op(op: str) -> Optional[str]:
    """Per-op chaos hook for both channel flavors; near-zero cost when
    testing_channel_failure is empty (one module-global check)."""
    global _chaos, _chaos_loaded
    if not _chaos_loaded:
        from ray_tpu.config import get_config
        spec = getattr(get_config(), "testing_channel_failure", "")
        _chaos = ChannelChaos(spec) if spec else None
        _chaos_loaded = True
    if _chaos is None or getattr(_chaos_tl, "retry", False):
        return None
    return _chaos.fire(op)


def reset_channel_chaos() -> None:
    """Re-read testing_channel_failure on the next channel op (tests
    flip the config mid-process; counters restart from zero)."""
    global _chaos, _chaos_loaded
    _chaos = None
    _chaos_loaded = False


def _as_u8(payload) -> memoryview:
    """A flat uint8 memoryview of any bytes-like / buffer-protocol
    payload, without copying when the buffer is C-contiguous (numpy
    array views, bytearrays, bytes)."""
    mv = payload if isinstance(payload, memoryview) \
        else memoryview(payload)
    if mv.ndim != 1 or mv.format != "B":
        try:
            mv = mv.cast("B")
        except TypeError:            # non-contiguous: pay one copy
            mv = memoryview(bytes(mv))
    return mv


class ShmRingChannel:
    """One direction, one producer process, one consumer process."""

    def __init__(self, name: Optional[str] = None, *, nslots: int = 8,
                 slot_bytes: int = 1 << 20, create: bool = False):
        self.nslots = nslots
        self.slot_bytes = slot_bytes
        size = HDR + nslots * (SLOT_HDR + slot_bytes)
        if create:
            name = name or f"rtch-{uuid.uuid4().hex[:16]}"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=size)
            self._shm.buf[:HDR] = b"\x00" * HDR
        else:
            assert name is not None
            self._shm = shared_memory.SharedMemory(name=name)
        self.name = name
        self._seqs = self._shm.buf.cast("Q")  # [0]=write_seq, [8]=read_seq
        # Native fast path (portable atomics + GIL-free waits + C memcpy);
        # None -> pure-Python fallback below.
        from ray_tpu._native import load_ringbuf
        self._lib = load_ringbuf()
        self._cbase = None
        if self._lib is not None:
            import ctypes
            self._cbase = ctypes.cast(
                (ctypes.c_uint8 * size).from_buffer(self._shm.buf),
                ctypes.POINTER(ctypes.c_uint8))

    # seq accessors -----------------------------------------------------
    @property
    def _wseq(self) -> int:
        return self._seqs[0]

    @_wseq.setter
    def _wseq(self, v: int):
        self._seqs[0] = v

    @property
    def _rseq(self) -> int:
        return self._seqs[8]

    @_rseq.setter
    def _rseq(self, v: int):
        self._seqs[8] = v

    def _slot(self, seq: int):
        off = HDR + (seq % self.nslots) * (SLOT_HDR + self.slot_bytes)
        return off

    # producer ----------------------------------------------------------
    def has_space(self) -> bool:
        """True if a write would not block. Only the consumer can change
        this from False to True, so a single producer may rely on it."""
        if self._lib is not None and self._cbase is not None:
            return bool(self._lib.rb_has_space(self._cbase, self.nslots))
        return self._wseq - self._rseq < self.nslots

    def write(self, payload, kind: int = DATA,
              timeout: Optional[float] = None):
        """payload: bytes-like / any C-contiguous buffer (numpy views —
        e.g. ring-allreduce chunk slices — are written without an
        intermediate bytes() copy), or an object with (frame_nbytes,
        write_into) — ray_tpu Serialized — written zero-copy."""
        if _chaos is not None or not _chaos_loaded:
            if _chaos_op("write") == "drop":
                return              # injected lossy link: frame vanishes
        mv = None
        if hasattr(payload, "write_into"):
            n = payload.frame_nbytes
        else:
            mv = _as_u8(payload)
            n = mv.nbytes
        if n > self.slot_bytes:
            raise ValueError(
                f"frame of {n} B exceeds channel slot size "
                f"{self.slot_bytes} B; compile the dag with a larger "
                f"slot_bytes")
        native = self._lib is not None and self._cbase is not None
        if native and mv is not None:
            import ctypes
            if isinstance(payload, bytes):
                data = payload           # ctypes takes bytes directly
            elif mv.readonly:
                # from_buffer refuses readonly views (e.g. staged
                # jax arrays); borrow the raw pointer via numpy — mv
                # stays referenced across the synchronous rb_write, so
                # the buffer cannot move or be freed under the copy
                import numpy as _np
                data = ctypes.cast(ctypes.c_void_p(
                    _np.frombuffer(mv, dtype=_np.uint8).ctypes.data
                    if n else 0), ctypes.c_char_p)
            else:
                data = ctypes.cast((ctypes.c_char * n).from_buffer(mv),
                                   ctypes.c_char_p)
            rc = self._lib.rb_write(
                self._cbase, self.nslots, self.slot_bytes,
                data, n, kind,
                -1.0 if timeout is None else float(timeout))
            if rc == -1:
                raise ChannelTimeout("channel full")
            return
        seq = self._wseq
        if native:
            # Zero-copy (Serialized) path: block for space in native
            # code (GIL-free), fill the slot from Python, then publish
            # WITH a futex wake — a sleeping native reader would
            # otherwise only notice at its re-check cap.
            rc = self._lib.rb_wait_space(
                self._cbase, self.nslots,
                -1.0 if timeout is None else float(timeout))
            if rc == -1:
                raise ChannelTimeout("channel full")
        else:
            self._wait(lambda: seq - self._rseq < self.nslots, timeout,
                       "channel full")
        off = self._slot(seq)
        buf = self._shm.buf
        if hasattr(payload, "write_into"):
            payload.write_into(buf[off + SLOT_HDR:off + SLOT_HDR + n])
        else:
            buf[off + SLOT_HDR:off + SLOT_HDR + n] = mv
        buf[off:off + 4] = n.to_bytes(4, "little")
        buf[off + 4] = kind
        if native:
            self._lib.rb_publish_write(self._cbase)
        else:
            self._wseq = seq + 1  # release: makes the slot visible

    # consumer ----------------------------------------------------------
    def read_with(self, fn, timeout: Optional[float] = None):
        """Run fn(kind, memoryview-of-frame) on the next frame WITHOUT
        copying; the slot is released only after fn returns, so the view
        (and anything deserialized zero-copy from it) must not escape."""
        if _chaos is not None or not _chaos_loaded:
            if _chaos_op("read") == "drop":
                raise ChaosInjectedTimeout("chaos: injected read drop")
        if self._lib is not None and self._cbase is not None:
            off = self._lib.rb_wait_readable(  # GIL-free wait
                self._cbase, self.nslots, self.slot_bytes,
                -1.0 if timeout is None else float(timeout))
            if off < 0:
                raise ChannelTimeout("channel empty")
            buf = self._shm.buf
            n = int.from_bytes(buf[off:off + 4], "little")
            kind = buf[off + 4]
            try:
                return fn(kind, buf[off + SLOT_HDR:off + SLOT_HDR + n])
            finally:
                self._lib.rb_release(self._cbase)
        seq = self._rseq
        self._wait(lambda: self._wseq > seq, timeout, "channel empty")
        off = self._slot(seq)
        buf = self._shm.buf
        n = int.from_bytes(buf[off:off + 4], "little")
        kind = buf[off + 4]
        try:
            return fn(kind, buf[off + SLOT_HDR:off + SLOT_HDR + n])
        finally:
            self._rseq = seq + 1  # release the slot for the producer

    def read_bytes(self, timeout: Optional[float] = None):
        # read_with already uses the native GIL-free wait when available
        # and copies exactly once.
        return self.read_with(lambda k, mv: (k, bytes(mv)), timeout)

    @staticmethod
    def _wait(cond, timeout, what):
        deadline = None if timeout is None else time.monotonic() + timeout
        delay = 20e-6
        while not cond():
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeout(what)
            time.sleep(delay)
            delay = min(delay * 2, 1e-3)

    # lifecycle ---------------------------------------------------------
    def close(self):
        self._cbase = None  # drop the ctypes buffer export first
        try:
            self._seqs.release()
        except Exception:
            pass
        self._seqs = None
        # tolerant close: a reader may still hold a zero-copy payload
        # view; leak the mapping rather than arm a raising finalizer
        from ray_tpu.runtime.object_store import _safe_close
        _safe_close(self._shm)

    def unlink(self):
        try:
            self._shm.unlink()
        except Exception:
            pass

    def spec(self) -> dict:
        return {"name": self.name, "nslots": self.nslots,
                "slot_bytes": self.slot_bytes}

    @classmethod
    def attach(cls, spec: dict) -> "ShmRingChannel":
        return cls(spec["name"], nslots=spec["nslots"],
                   slot_bytes=spec["slot_bytes"])


# --- cross-host channel ------------------------------------------------

_FRAME_HDR = 5          # u32 length (LE) + u8 kind
_ACK = b"\x06"


def _kv(method, **kw):
    from ray_tpu import api
    ctx = api._require_init()
    return api._run(ctx.pool.call(ctx.head_addr, method, **kw))


def _advertise_host() -> str:
    """The address peers on OTHER hosts can reach this process at: the
    node agent's bind host (workers carry it in RAY_TPU_AGENT_HOST;
    real multi-host deployments start nodes with --node-host <ip>).
    The listener itself binds 0.0.0.0, so any routable name works."""
    import os
    h = os.environ.get("RAY_TPU_AGENT_HOST")
    if h and h != "0.0.0.0":
        return h
    from ray_tpu import api
    ctx = api._require_init()
    if getattr(api._g, "agent", None) is not None and \
            api._g.agent.addr and api._g.agent.addr[0] != "0.0.0.0":
        return api._g.agent.addr[0]
    return ctx.addr[0] if ctx.addr else "127.0.0.1"


class TcpChannel:
    """SPSC channel across HOSTS: the DCN substrate compiled graphs
    need for pipeline-parallel inference across slices (reference:
    experimental/channel/shared_memory_channel.py crosses nodes by
    round-tripping plasma; here frames flow producer -> consumer over
    one TCP connection with credit-based flow control that preserves
    the shm ring's bounded-buffer semantics: at most `nslots` frames
    in flight, each ACKed when the consumer releases its slot).

    Endpoint negotiation rides the control KV — the one address every
    participant already shares: the consumer binds an ephemeral port on
    its host and publishes ``host:port`` under the channel id; the
    producer polls the key and connects. Same duck-type as
    ShmRingChannel (write / read_with / read_bytes / has_space /
    close / unlink / spec), so the dag runtime treats edges uniformly.
    """

    KV_PREFIX = "__dagch:"
    CONNECT_TIMEOUT_S = 120.0   # bound for "consumer never came up"

    def __init__(self, spec: dict, role: str,
                 nonblocking_writes: bool = False):
        """``nonblocking_writes``: write() ENQUEUES the frame (credit
        permitting) and flushes opportunistically instead of blocking
        on the kernel send buffer. The DRIVER's input channels use this
        — the driver is the sink's only drainer, so a write that blocks
        on a stalled pipeline would deadlock it (stage channels keep
        blocking writes: a stage SHOULD stall when downstream is
        full)."""
        assert role in ("producer", "consumer"), role
        self.id = spec["id"]
        self.nslots = spec["nslots"]
        self.slot_bytes = spec["slot_bytes"]
        self.role = role
        self.nonblocking_writes = nonblocking_writes
        self._sock = None
        self._listener = None
        self._inflight = 0          # producer: un-ACKed frames
        self._rbuf = bytearray()    # consumer: partial-read resume
        self._wbuf = bytearray()    # producer: unflushed frame bytes
        self._ident_left = 0        # consumer: handshake bytes pending
        self._pending_hdr = None    # consumer: parsed frame header
        if role == "consumer":
            import socket
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind(("0.0.0.0", 0))
            self._listener.listen(1)
            port = self._listener.getsockname()[1]
            _kv("kv_put", key=self.KV_PREFIX + self.id,
                value=f"{_advertise_host()}:{port}".encode())

    # --- connection ----------------------------------------------------

    def _ensure_conn(self, timeout: Optional[float]):
        if self._sock is not None:
            if self._ident_left:     # resume a half-done handshake
                self._check_ident(timeout)
            return
        import socket
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        if self.role == "consumer":
            self._listener.settimeout(timeout)
            try:
                self._sock, _ = self._listener.accept()
            except (socket.timeout, BlockingIOError):
                # BlockingIOError: timeout == 0.0 puts the socket in
                # non-blocking mode (driver-side opportunistic polls)
                raise ChannelTimeout("no producer connected")
            self._sock.setsockopt(socket.IPPROTO_TCP,
                                  socket.TCP_NODELAY, 1)
            self._ident_left = len(self.id)
            self._check_ident(timeout)
            return
        else:
            # never poll forever: a consumer that died before attaching
            # would otherwise hang the producer with no diagnosis.
            # Endpoint polling AND refused connects retry with jittered
            # exponential backoff bounded by the caller's deadline: a
            # peer mid-restart during elastic re-formation (endpoint
            # not yet republished, or listener not yet accepting) must
            # neither burn a CPU in a tight loop nor flake the attach —
            # the KV is re-read each attempt, so a consumer that
            # rebinds a fresh port under the same channel id is picked
            # up as soon as it publishes.
            if deadline is None:
                deadline = time.monotonic() + self.CONNECT_TIMEOUT_S
            import random
            attempt = 0
            last_err: Optional[str] = None
            while True:
                blob = _kv("kv_get", key=self.KV_PREFIX + self.id)
                if blob:
                    host, port = blob.decode().rsplit(":", 1)
                    try:
                        self._sock = socket.create_connection(
                            (host, int(port)),
                            timeout=max(1.0,
                                        deadline - time.monotonic()))
                        self._sock.sendall(self.id.encode())
                        break
                    except socket.timeout:
                        self._sock = None
                        raise ChannelTimeout(
                            "connect to consumer timed out")
                    except OSError as e:
                        # refused/reset: the consumer may be restarting
                        # — back off and retry until the deadline
                        self._sock = None
                        last_err = str(e)
                if time.monotonic() >= deadline:
                    if last_err is not None:
                        raise ChannelAttachRefused(
                            f"connect failed: {last_err}")
                    raise ChannelTimeout(
                        f"consumer endpoint for channel {self.id} not "
                        f"published (peer dead before attach?)")
                delay = min(1.0, 0.02 * (2 ** min(attempt, 10))) \
                    * (0.5 + random.random())
                attempt += 1
                time.sleep(min(delay,
                               max(0.0,
                                   deadline - time.monotonic())))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _check_ident(self, timeout: Optional[float]):
        """Finish the producer's channel-id handshake; resumable — a
        0-timeout poll that catches the connection mid-handshake keeps
        its progress instead of desynchronizing the frame stream."""
        while self._ident_left > 0:
            got = self._fill(self._ident_left, timeout)
            self._ident_left -= got
        ident = bytes(self._rbuf[:len(self.id)])
        del self._rbuf[:len(self.id)]
        if ident.decode(errors="replace") != self.id:
            self._sock.close()
            self._sock = None
            raise ChannelClosed("wrong channel id from producer")

    def _fill(self, want: int, timeout: Optional[float]) -> int:
        """recv up to `want` bytes into the resume buffer; returns the
        count (>=1) or raises ChannelTimeout with progress KEPT."""
        import socket
        self._sock.settimeout(timeout)
        try:
            chunk = self._sock.recv(max(want, 1))
        except (socket.timeout, BlockingIOError):
            raise ChannelTimeout("channel recv timed out")
        except OSError as e:           # reset/aborted: channel-typed
            raise ChannelClosed(f"peer connection lost: {e}")
        if not chunk:
            raise ChannelClosed("peer closed")
        self._rbuf += chunk
        return len(chunk)

    def _recv_exact(self, n: int, timeout: Optional[float]) -> bytes:
        """Read exactly n bytes honoring the caller's TOTAL budget;
        partial progress survives a timeout in self._rbuf, so the next
        call resumes the same frame instead of tearing the protocol."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while len(self._rbuf) < n:
            left = None
            if deadline is not None:
                left = deadline - time.monotonic()
                if len(self._rbuf) < n and left <= 0 \
                        and timeout != 0.0:
                    raise ChannelTimeout("channel recv timed out")
                left = max(left, 0.0)
            self._fill(n - len(self._rbuf), left)
        out = bytes(self._rbuf[:n])
        del self._rbuf[:n]
        return out

    # --- producer ------------------------------------------------------

    def _drain_acks(self, block_timeout: Optional[float] = 0.0):
        """Consume pending ACK bytes; with a timeout, wait for at least
        one (credit recovery when the window is full)."""
        import select
        import socket
        if self._sock is None or self._inflight == 0:
            return
        want_block = block_timeout != 0.0
        while self._inflight > 0:
            r, _, _ = select.select([self._sock], [], [],
                                    block_timeout if want_block else 0.0)
            if not r:
                if want_block:
                    raise ChannelTimeout("channel full (no ACK)")
                return
            self._sock.settimeout(0.0)
            try:
                data = self._sock.recv(self._inflight)
            except (BlockingIOError, socket.timeout):
                return
            except OSError as e:
                raise ChannelClosed(f"peer connection lost: {e}")
            if not data:
                raise ChannelClosed("peer closed")
            self._inflight -= len(data)
            want_block = False   # got credit; opportunistic from here

    def has_space(self) -> bool:
        # ChannelClosed propagates: reporting space on a dead peer
        # would let a fan-in driver write the other inputs first and
        # skew the streams permanently (the invariant execute() keeps)
        if self._sock is None:
            return True          # connection not yet up: first write ok
        self.flush(0.0)
        self._drain_acks(0.0)
        return self._inflight < self.nslots

    def flush(self, timeout: Optional[float] = 0.0):
        """Push enqueued frame bytes to the socket. 0.0 = best-effort
        non-blocking (the driver calls this from its sink pump); None /
        >0 = block for full drain within the budget."""
        import socket
        if not self._wbuf or self._sock is None:
            return
        self._sock.settimeout(timeout)
        while self._wbuf:
            try:
                sent = self._sock.send(self._wbuf)
            except (socket.timeout, BlockingIOError):
                if timeout == 0.0:
                    return
                raise ChannelTimeout("channel flush timed out")
            except OSError as e:
                raise ChannelClosed(f"peer connection lost: {e}")
            del self._wbuf[:sent]

    def write(self, payload, kind: int = DATA,
              timeout: Optional[float] = None):
        """Blocking-mode (stages): the whole frame is on the wire when
        this returns — a frame is never abandoned mid-send, so the
        length-prefixed stream cannot desynchronize (the timeout covers
        connect + credit; transmission completes unconditionally).
        Nonblocking-mode (driver inputs): the frame is ENQUEUED once
        credit allows and flushed opportunistically — the driver can
        always return to draining the sink, which is what ultimately
        frees the pipeline."""
        if _chaos is not None or not _chaos_loaded:
            if _chaos_op("write") == "drop":
                return              # injected lossy link: frame vanishes
        if hasattr(payload, "write_into"):
            n = payload.frame_nbytes
            data = bytearray(n)
            payload.write_into(memoryview(data))
        elif isinstance(payload, (bytes, bytearray)):
            data = payload
            n = len(data)
        else:
            # buffer-protocol payloads (numpy chunk views) go to
            # sendmsg/enqueue without an intermediate bytes() copy
            data = _as_u8(payload)
            n = data.nbytes
        if n > self.slot_bytes:
            raise ValueError(
                f"frame of {n} B exceeds channel slot size "
                f"{self.slot_bytes} B; compile the dag with a larger "
                f"slot_bytes")
        self._ensure_conn(timeout)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        while self._inflight >= self.nslots:
            self.flush(0.0)
            left = None
            if deadline is not None:
                left = deadline - time.monotonic()
                if left <= 0:
                    raise ChannelTimeout("channel full (no ACK)")
            self._drain_acks(left)
        hdr = n.to_bytes(4, "little") + bytes([kind])
        self._inflight += 1
        if self.nonblocking_writes:
            self._wbuf += hdr
            self._wbuf += data
            self.flush(0.0)
            return
        import socket
        # one gathered syscall, zero concatenation copies; completion
        # is unconditional (see docstring)
        self._sock.settimeout(None)
        try:
            sent = self._sock.sendmsg([hdr, data])
            want = len(hdr) + n
            if sent < want:      # short gathered send: finish the rest
                rest = (hdr + bytes(data))[sent:] if sent < len(hdr) \
                    else memoryview(data)[sent - len(hdr):]
                self._sock.sendall(rest)
        except OSError as e:
            raise ChannelClosed(f"peer connection lost: {e}")

    # --- consumer ------------------------------------------------------

    def read_with(self, fn, timeout: Optional[float] = None):
        """Resumable frame read: a timeout mid-header or mid-payload
        keeps all progress (buffered bytes + parsed header) for the
        next call — driver-side 0-timeout polls interleave safely with
        blocking gets on the same channel."""
        if _chaos is not None or not _chaos_loaded:
            if _chaos_op("read") == "drop":
                raise ChaosInjectedTimeout("chaos: injected read drop")
        self._ensure_conn(timeout)
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        if self._pending_hdr is None:
            hdr = self._recv_exact(_FRAME_HDR, timeout)
            self._pending_hdr = (int.from_bytes(hdr[:4], "little"),
                                 hdr[4])
        n, kind = self._pending_hdr
        left = timeout
        if deadline is not None and timeout != 0.0:
            left = max(deadline - time.monotonic(), 0.0)
        payload = self._recv_exact(n, left) if n else b""
        self._pending_hdr = None
        try:
            return fn(kind, memoryview(payload))
        finally:
            try:
                self._sock.sendall(_ACK)   # slot released: return credit
            except OSError:
                pass

    def read_bytes(self, timeout: Optional[float] = None):
        return self.read_with(lambda k, mv: (k, bytes(mv)), timeout)

    # --- lifecycle ------------------------------------------------------

    def close(self):
        try:
            self.flush(1.0)      # best-effort: don't strand a frame
        except Exception:
            pass
        for s in (self._sock, self._listener):
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._sock = self._listener = None
        if self.role == "consumer":
            try:
                _kv("kv_del", key=self.KV_PREFIX + self.id)
            except Exception:
                pass

    def unlink(self):
        pass                     # no named OS resource beyond the socket

    def spec(self) -> dict:
        return {"type": "tcp", "id": self.id, "nslots": self.nslots,
                "slot_bytes": self.slot_bytes}


def new_tcp_spec(nslots: int, slot_bytes: int) -> dict:
    return {"type": "tcp", "id": uuid.uuid4().hex[:16],
            "nslots": nslots, "slot_bytes": slot_bytes}


def spec_transport(spec: dict) -> str:
    """"tcp" or "shm" for an edge spec — the transport label
    ``RingReducer.from_spec`` stamps onto its flight-recorder summary,
    so a collective post-mortem says whether the hung/slow edge was a
    cross-host TCP link or same-host shm without the spec in hand."""
    return "tcp" if spec.get("type") == "tcp" else "shm"


def attach_channel(spec: dict, role: str, timeout: float = 60.0,
                   abort=None):
    """Attach either channel flavor: shm specs are role-agnostic, tcp
    specs bind/connect per role ('producer' | 'consumer').

    ``abort``: optional zero-arg predicate polled by the lazy-shm
    producer wait (the only attach path that blocks); returning True
    raises ChannelTimeout immediately — elastic training points this
    at its regroup event so a group rewire can interrupt an attach
    against a dead incarnation's specs instead of waiting it out.

    ``lazy`` shm specs cover co-located NON-driver stages: the driver
    can't create a segment on a remote host, so the consumer creates it
    at attach (and owns the unlink) while the producer polls for the
    name — same-host peers still get the two-memcpy ring instead of
    paying the TCP path."""
    if spec.get("type") == "tcp":
        return TcpChannel(spec, role)
    if spec.get("lazy"):
        if role == "consumer":
            try:
                ch = ShmRingChannel(spec["name"], nslots=spec["nslots"],
                                    slot_bytes=spec["slot_bytes"],
                                    create=True)
            except FileExistsError:
                # The consumer OWNS this name; an existing segment is a
                # stale leak from a crashed previous incarnation (names
                # are incarnation-unique) — reclaim it, don't fail.
                from multiprocessing import shared_memory as _shm
                _shm.SharedMemory(name=spec["name"]).unlink()
                ch = ShmRingChannel(spec["name"], nslots=spec["nslots"],
                                    slot_bytes=spec["slot_bytes"],
                                    create=True)
            ch._lazy_owner = True
            return ch
        deadline = time.monotonic() + timeout
        while True:
            try:
                return ShmRingChannel.attach(spec)
            except (FileNotFoundError, ValueError):
                # ValueError ("cannot mmap an empty file"): the
                # consumer is mid-create — shm_open done, ftruncate
                # not yet — so the name exists at 0 bytes for a
                # moment; the same transient as not-yet-created
                if abort is not None and abort():
                    raise ChannelTimeout(
                        f"attach of lazy shm channel {spec['name']} "
                        f"aborted (group reshaped)")
                if time.monotonic() > deadline:
                    raise ChannelTimeout(
                        f"lazy shm channel {spec['name']} never "
                        f"created by its consumer")
                time.sleep(0.01)
    return ShmRingChannel.attach(spec)
