"""Chunked ring allreduce over dag channels: the collective plane's
bandwidth-optimal path.

Replaces the star reduce for N>2 participants (reference for the shape:
NCCL's ring allreduce; the papers behind this PR: The Big Send-off,
arxiv 2504.18658 — chunked, pipelined collectives are what make
large-scale gradient exchange performant — and EQuARX, arxiv 2506.17615
— block-quantized allreduce recovers most of the interconnect bandwidth
with negligible quality loss). Topology: rank r owns one directed edge
to rank (r+1)%N — any mix of ShmRingChannel (same host) and TcpChannel
(cross host) works, the engine only needs write/read_with/slot_bytes.

A round has three phases:

1. **Header relay** (N-1 small frames): every participant sends a header
   carrying its layout signature — or the ERROR frame it entered the
   round with — and forwards whatever it received. After N-1 steps every
   rank holds every header, so an ERROR injected at ANY rank reaches ALL
   ranks in one round (no deadlock, channels stay aligned for the next
   round), and layout mismatches turn into the same deterministic error
   everywhere instead of a garbled reduce.
2. **Reduce-scatter** (N-1 steps): the flattened value is split into N
   segments, segments into chunks of ``chunk_bytes``; at step s rank r
   sends segment (r-s)%N chunk-by-chunk while receiving and accumulating
   segment (r-s-1)%N — the chunk pipelining: chunk k+1 is being copied
   into the ring while the consumer reduces chunk k. Accumulation is
   fused (np.add(src, incoming, out=buf)) and always happens in a
   float32-or-wider wire dtype, so low-precision inputs neither overflow
   nor drift across rounds. Per-participant traffic is O(S), independent
   of N — the star root's O(N*S) ingress+egress is gone.
3. **Allgather** (N-1 steps): each rank broadcasts the segment it now
   owns; received frames are forwarded VERBATIM (quantized payloads are
   not re-quantized hop by hop), so every rank reconstructs bitwise
   identical results — SPMD training state cannot diverge.

Opt-in int8 block quantization (``quantize="int8"``): each chunk ships
as [per-256-element float32 scales | int8 payload] — about 26% of the
fp32 wire bytes. The elementwise error of one quantization event is
bounded by scale/2 = max|block|/254; partial sums are requantized once
per reduce-scatter hop and the final value once, so a round's total
bound is (N*max_scale)/2 — exported per round as the
``allreduce_quant_error`` gauge. Accumulators stay float32/float64, so
the error does not compound across rounds.

Every round is traced (``_RingTrace``): round-level spans by default
(collective id, op, bytes, codec, send/recv-wait/header timing, train
step), per-chunk spans at ``collective_trace_level="chunk"`` — all in
the bounded "collective" event category so ``timeline(all_nodes=True)``
renders per-rank ring lanes with cross-rank flow edges. Straggler
attribution piggybacks each rank's recv-wait on the next round's
header relay (zero extra frames -> the ``allreduce_straggler_rank``
gauge), and a bounded flight recorder dumps the last K rounds' timing
to JSON when a round dies, attaching the path to the raised exception.

Phases 2 and 3 are ALSO standalone collective ops
(``RingReducer.reduce_scatter`` / ``RingReducer.allgather``, surfaced
through ``_Collective`` and the train plane): reduce-scatter hands each
rank its owned contiguous shard of the flat reduced value — the ZeRO-1
unit (arxiv 2004.13336: shard the weight update and optimizer state
across replicas) — and allgather reassembles shards into the full
pytree, with an opt-in ``wire_dtype="bfloat16"`` cast codec (half the
fp32 bytes, one rounding event, owner round-tripped so results stay
bitwise identical across ranks). The fused allreduce round is exactly
these two phases back to back over one buffer.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.dag.channel import (DATA, ERROR, ChannelAttachRefused,
                                 ChannelClosed, ChannelTimeout,
                                 attach_channel, chaos_mark_retry)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob
from ray_tpu.util import events, forensics

_UNSET = object()              # "use the constructor default" sentinel
DEFAULT_CHUNK_BYTES = 1 << 20
QUANT_BLOCK = 256           # elements per int8/int4 quantization block
_QUANTIZE_MODES = (None, "int8", "int4")


class RingPeerDead(Exception):
    """A ring neighbor stopped responding (peer death / teardown):
    terminal for the group — bounded reads surfaced it within
    timeout_s on every surviving participant."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


class RingProtocolError(Exception):
    """A frame kind the protocol cannot produce arrived mid-phase:
    the channels are desynced beyond repair for this group."""


class _AbortedOp(Exception):
    """Internal: ``RingReducer.abort()`` interrupted a blocked channel
    op — surfaced to callers as RingPeerDead with a reshape message."""


# How finely blocked ring waits are sliced so abort() can interrupt
# them: the worst-case extra latency an aborted participant pays, and
# the wakeup period while blocked (waits with data ready return
# immediately; the slicing only costs when genuinely stalled).
_ABORT_SLICE_S = 0.25


def allreduce_metrics() -> dict:
    """Get-or-create the collective plane's series (shared process
    registry; worker processes push them to the head via
    util/metrics.push_loop, so the head /metrics serves cluster-wide
    allreduce telemetry like the other PR-2 aggregated series).

      allreduce_round_s       wall time of one full allreduce round
      reduce_scatter_round_s  wall time of one STANDALONE
                              reduce-scatter round (headers + N-1 steps)
      allgather_round_s       wall time of one STANDALONE allgather
                              round (headers + N-1 steps)
      collective_recv_wait_s  per-round blocked-on-predecessor time
                              (straggler attribution input; rank tag)
      allreduce_straggler_rank  rank that dominated the previous
                              round's critical path (see _RingTrace)
      allreduce_bytes_total   wire bytes this participant wrote
      allreduce_quant_error   elementwise error bound of the last
                              round per wire codec ({codec=int8|int4|
                              bf16|fp32}): (N * max_block_scale) / 2
                              where scale = max|block|/127 (int8) or
                              max|block|/7 (int4); 0 for the lossless
                              and cast codecs
      allreduce_hier_inter_bytes_total  wire bytes written by this
                              participant on the CROSS-NODE (inter)
                              leg of hierarchical collectives — the
                              number the ring-of-rings exists to
                              shrink (~1/ranks-per-node of the flat
                              ring's cross-node traffic)
      collective_bcast_round_s  wall time of one intra-node broadcast
                              round (the hierarchical fan-out phase)
      collective_tuner_regime   impl the in-situ auto-tuner chose for
                              the last collective payload: 0 = star,
                              1 = flat ring, 2 = hierarchical
      allreduce_bucket_overlap_s  per gradient sync, the staging time
                              that was hidden under in-flight ring
                              rounds by bucketed sync (train plane)
    """
    from ray_tpu.util import metrics as m
    return {
        "round": m.Histogram(
            "allreduce_round_s",
            "Wall time of one collective-plane allreduce round "
            "(header relay + reduce-scatter + allgather)"),
        "rs_round": m.Histogram(
            "reduce_scatter_round_s",
            "Wall time of one standalone reduce-scatter round "
            "(header relay + N-1 pipelined chunk steps; the ZeRO "
            "gradient-shard sync)"),
        "ag_round": m.Histogram(
            "allgather_round_s",
            "Wall time of one standalone allgather round (header "
            "relay + N-1 pipelined chunk steps; the ZeRO parameter "
            "reassembly)"),
        "bytes": m.Counter(
            "allreduce_bytes_total",
            "Wire bytes written by this participant across collective "
            "rounds (headers + chunk frames; allreduce, reduce-scatter "
            "and allgather all meter here)"),
        "recv_wait": m.Histogram(
            "collective_recv_wait_s",
            "Time this rank spent BLOCKED waiting on its "
            "ring-predecessor per collective round: the first header "
            "read (direct wait for the predecessor to enter) plus all "
            "data-phase reads — header RELAY waits are excluded, they "
            "smear a late entrant's delay over every rank. The "
            "cross-rank argmax is the straggler signal: the rank "
            "AFTER the straggler waits longest. Tagged with this "
            "participant's rank",
            tag_keys=("rank",)),
        "straggler": m.Gauge(
            "allreduce_straggler_rank",
            "Rank whose slowness dominated the PREVIOUS collective "
            "round's critical path — computed identically on every "
            "rank from the recv-wait map each participant piggybacks "
            "on the next round's header relay (zero extra frames). "
            "-1 when no rank's wait dominated (healthy round); unset "
            "until a full round of attribution data exists"),
        "quant_err": m.Gauge(
            "allreduce_quant_error",
            "Elementwise error bound of the last round over the "
            "quantization events this participant OBSERVED (frames "
            "sent or received), labelled by wire codec "
            "(codec=int8|int4|bf16|fp16|fp32): (N*max_scale)/2, "
            "scale = max|block|/127 (int8) or max|block|/7 (int4). "
            "Exact when gradient magnitudes are comparable across "
            "ranks; partial sums quantized at non-adjacent hops can "
            "exceed it under cross-rank magnitude skew with "
            "cancellation. +inf when a non-finite gradient was "
            "NaN-poisoned through the wire; 0 for cast and fp32 "
            "rounds",
            tag_keys=("codec",)),
        "hier_inter_bytes": m.Counter(
            "allreduce_hier_inter_bytes_total",
            "Wire bytes this participant wrote on the cross-node "
            "(inter) leg of hierarchical collectives — the traffic "
            "the ring-of-rings shrinks to ~1/ranks-per-node of the "
            "flat ring's cross-node bytes"),
        "bc_round": m.Histogram(
            "collective_bcast_round_s",
            "Wall time of one intra-node broadcast round (header "
            "relay + pipelined chunk forwarding from the node "
            "leader; the hierarchical fan-out phase)"),
        "tuner_regime": m.Gauge(
            "collective_tuner_regime",
            "Impl the in-situ collective auto-tuner chose for the "
            "last payload it was consulted about: 0 = star, 1 = flat "
            "ring, 2 = hierarchical (unset until the first tuned "
            "decision)"),
        "bucket_overlap": m.Histogram(
            "allreduce_bucket_overlap_s",
            "Per bucketed gradient sync: host staging time that was "
            "hidden under in-flight ring rounds (the compute/comm "
            "overlap the bucket pipeline creates)"),
    }


# --- pytree flatten/unflatten (host plane: no jax import) ----------------


def _flatten(value) -> Tuple[List[np.ndarray], Any, tuple]:
    """(leaves, rebuild, sig): rebuild(iter_of_arrays) reconstructs the
    pytree; sig is a picklable, comparable structure descriptor —
    participants whose sigs differ cannot be reduced together."""
    leaves: List[np.ndarray] = []
    sig: List[tuple] = []

    def walk(v):
        if isinstance(v, dict):
            keys = list(v)
            sig.append(("dict", tuple(str(k) for k in keys)))
            fns = [walk(v[k]) for k in keys]
            t = type(v)

            def rb(it, keys=keys, fns=fns, t=t):
                out = {k: f(it) for k, f in zip(keys, fns)}
                return out if t is dict else t(out)
            return rb
        if isinstance(v, tuple) and hasattr(v, "_fields"):  # NamedTuple
            sig.append(("namedtuple", tuple(v._fields)))
            fns = [walk(x) for x in v]
            t = type(v)

            def rb(it, fns=fns, t=t):
                return t(*(f(it) for f in fns))
            return rb
        if isinstance(v, (list, tuple)):
            sig.append(("seq", type(v).__name__, len(v)))
            fns = [walk(x) for x in v]
            t = type(v)

            def rb(it, fns=fns, t=t):
                return t(f(it) for f in fns)
            return rb
        a = np.asarray(v)
        scalar = not isinstance(v, np.ndarray) and a.ndim == 0
        sig.append(("leaf", a.shape, a.dtype.str))
        leaves.append(a)

        def rb(it, scalar=scalar):
            out = next(it)
            return out.item() if scalar else out
        return rb

    rebuild = walk(value)
    return leaves, rebuild, tuple(sig)


def accumulation_dtype(dt: np.dtype, op: str) -> Optional[np.dtype]:
    """THE low-precision promotion policy, shared by the star's
    per-leaf reduce (runtime._tree_reduce) and the ring's wire dtype
    so the N<=2 fallback and the ring agree numerically. None = reduce
    in the input dtype. sum over sub-64-bit ints accumulates in int64;
    mean over integers accumulates in float64 (and the RESULT stays
    float64, matching numpy's int/len division — means of ints must
    not truncate); sub-32-bit floats (fp16, and bfloat16/fp8 which
    register as kind 'V') accumulate in float32."""
    if op not in ("sum", "mean"):
        return None              # max/min cannot overflow
    if dt.kind in "iub":
        if op == "mean":
            # int64/uint64 divisions already yield float64 stepwise
            return np.dtype(np.float64) if dt.itemsize < 8 else None
        return np.dtype(np.int64) if dt.itemsize < 8 else None
    if dt.kind == "f":
        return np.dtype(np.float32) if dt.itemsize < 4 else None
    if dt.kind == "V":           # ml_dtypes floats
        try:
            if np.finfo(dt).bits < 32:
                return np.dtype(np.float32)
        except ValueError:
            pass
    return None


def _keeps_wide(dt: np.dtype, op: str) -> bool:
    """True when the reduced result stays in the accumulation dtype
    instead of casting back: integer means are float64 results (the
    pre-ring star semantics; casting back would truncate)."""
    return op == "mean" and dt.kind in "iub"


def _wire_dtype(dtypes: List[np.dtype], op: str) -> np.dtype:
    rt = np.result_type(*dtypes) if dtypes else np.dtype(np.float32)
    p = accumulation_dtype(rt, op)
    if p is not None:
        return p
    if rt.kind in "iub":         # 64-bit ints
        return np.dtype(np.float64) if op == "mean" else rt
    if rt.kind in "cf":
        return rt
    try:                          # ml_dtypes floats >= 32 bits
        info = np.finfo(rt)
    except ValueError:
        raise TypeError(f"cannot ring-reduce dtype {rt}")
    return np.dtype(np.float32) if info.bits <= 32 else np.dtype(np.float64)


# --- int8 block quantization (EQuARX-style wire format) ------------------


def _quantize(x: np.ndarray) -> Tuple[bytearray, float]:
    """[nblocks float32 scales | n int8] — returns (frame, max_scale).
    Per-block scale = max|block|/127, so |q| <= 127 without clipping
    and the per-element dequantization error is bounded by scale/2.
    All-zero blocks ship scale 0 (exact). Blocks containing NaN/Inf
    ship scale NaN — dequantization NaN-poisons the whole block, so a
    diverged gradient SURFACES like it would unquantized instead of
    silently becoming finite garbage; max_scale reports +inf."""
    n = x.size
    nb = -(-n // QUANT_BLOCK)
    xb = np.zeros(nb * QUANT_BLOCK, np.float32)
    xb[:n] = x
    xb = xb.reshape(nb, QUANT_BLOCK)
    absmax = xb.__abs__().max(axis=1)
    finite = np.isfinite(absmax)
    div = np.where(finite & (absmax > 0.0), absmax / 127.0,
                   np.float32(1.0)).astype(np.float32)
    q = np.rint(np.where(finite[:, None], xb, np.float32(0.0))
                / div[:, None]).astype(np.int8)
    scales = np.where(finite,
                      np.where(absmax > 0.0, absmax / 127.0,
                               np.float32(0.0)),
                      np.float32(np.nan)).astype(np.float32)
    if not n:
        max_scale = 0.0
    elif finite.all():
        max_scale = float(absmax.max()) / 127.0
    else:
        max_scale = float("inf")
    frame = bytearray(4 * nb + n)
    frame[:4 * nb] = scales.tobytes()
    frame[4 * nb:] = q.reshape(-1)[:n].tobytes()
    return frame, max_scale


def _dequantize(frame, n: int) -> np.ndarray:
    nb = -(-n // QUANT_BLOCK)
    scales = np.frombuffer(frame, np.float32, nb)
    q = np.frombuffer(frame, np.int8, n, offset=4 * nb)
    out = np.zeros(nb * QUANT_BLOCK, np.float32)
    out[:n] = q
    out = out.reshape(nb, QUANT_BLOCK)
    out *= scales[:, None]
    # NaN scales must poison the ENTIRE block (q==0 elements included:
    # 0 * nan is already nan, so the multiply above covers every lane)
    return out.reshape(-1)[:n]


def _scales_max(frame, n: int) -> float:
    """Largest block scale carried by a received quantized frame —
    folded into the error-bound gauge so the bound reflects OTHER
    ranks' quantization events (their gradient magnitudes), not just
    this rank's own."""
    nb = -(-n // QUANT_BLOCK)
    if not nb:
        return 0.0
    m = float(np.frombuffer(frame, np.float32, nb).max())
    return m if np.isfinite(m) else float("inf")


def _quantize4(x: np.ndarray) -> Tuple[bytearray, float]:
    """[nblocks float32 scales | ceil(n/2) packed bytes] — two 4-bit
    two's-complement values per byte (even element in the low nibble),
    per-block scale = max|block|/7 so |q| <= 7 without clipping and
    the per-element dequantization error is bounded by scale/2. The
    zero / NaN semantics match ``_quantize``: all-zero blocks ship
    scale 0 (exact), non-finite blocks ship scale NaN (the whole block
    NaN-poisons on decode; max_scale reports +inf)."""
    n = x.size
    nb = -(-n // QUANT_BLOCK)
    xb = np.zeros(nb * QUANT_BLOCK, np.float32)
    xb[:n] = x
    xb = xb.reshape(nb, QUANT_BLOCK)
    absmax = xb.__abs__().max(axis=1)
    finite = np.isfinite(absmax)
    div = np.where(finite & (absmax > 0.0), absmax / 7.0,
                   np.float32(1.0)).astype(np.float32)
    q = np.rint(np.where(finite[:, None], xb, np.float32(0.0))
                / div[:, None]).astype(np.int8).reshape(-1)[:n]
    # pack pairs into bytes: QUANT_BLOCK is even, so only the tail of
    # an odd-length payload pads — the pad nibble is 0 and never read
    if n % 2:
        q = np.concatenate([q, np.zeros(1, np.int8)])
    u = q.view(np.uint8) & 0x0F
    packed = (u[0::2] | (u[1::2] << 4)).astype(np.uint8)
    scales = np.where(finite,
                      np.where(absmax > 0.0, absmax / 7.0,
                               np.float32(0.0)),
                      np.float32(np.nan)).astype(np.float32)
    if not n:
        max_scale = 0.0
    elif finite.all():
        max_scale = float(absmax.max()) / 7.0
    else:
        max_scale = float("inf")
    frame = bytearray(4 * nb + (n + 1) // 2)
    frame[:4 * nb] = scales.tobytes()
    frame[4 * nb:] = packed.tobytes()
    return frame, max_scale


def _dequantize4(frame, n: int) -> np.ndarray:
    nb = -(-n // QUANT_BLOCK)
    scales = np.frombuffer(frame, np.float32, nb)
    packed = np.frombuffer(frame, np.uint8, (n + 1) // 2,
                           offset=4 * nb)
    u = np.empty(2 * packed.size, np.uint8)
    u[0::2] = packed & 0x0F
    u[1::2] = packed >> 4
    # sign-extend the 4-bit two's-complement nibbles
    q = ((u.astype(np.int16) ^ 8) - 8).astype(np.float32)
    out = np.zeros(nb * QUANT_BLOCK, np.float32)
    out[:n] = q[:n]
    out = out.reshape(nb, QUANT_BLOCK)
    out *= scales[:, None]
    # NaN scales poison the ENTIRE block, same as _dequantize
    return out.reshape(-1)[:n]


# --- wire codecs ---------------------------------------------------------
#
# A codec transforms chunk frames on the wire while accumulation stays
# in the float32-or-wider buffer dtype: `encode` turns a buffer slice
# into the frame that ships, `decode` turns a received frame back into
# the accumulation dtype. Two codecs exist: int8 block quantization
# (above) and a plain low-precision cast (bfloat16/float16 — half the
# fp32 bytes, no per-block scales). The allgather phase forwards
# ENCODED frames verbatim and the segment owner round-trips its own
# copy, so every rank reconstructs bitwise identical results whichever
# codec is active.


class _Int8Codec:
    tag = "int8"

    def __init__(self):
        self.max_scale = 0.0     # feeds the allreduce_quant_error gauge

    def encode(self, arr: np.ndarray) -> bytes:
        frame, smax = _quantize(arr)
        self.max_scale = max(self.max_scale, smax)
        return bytes(frame)

    def decode(self, frame, n: int, wire: np.dtype) -> np.ndarray:
        self.max_scale = max(self.max_scale, _scales_max(frame, n))
        out = _dequantize(frame, n)
        return out if wire == np.float32 else out.astype(wire)


class _Int4Codec:
    """Two quantized values per byte with per-block scales — ~12.9% of
    the fp32 wire bytes (4-bit payload + f32 scales per 256 elements).
    Coarser than int8 (15 levels per block), so gradient sync with
    this codec NEEDS error-feedback accumulation (train/collective.py)
    to stay convergence-safe; the bound rides the same
    ``allreduce_quant_error`` gauge under {codec=int4}."""

    tag = "int4"

    def __init__(self):
        self.max_scale = 0.0     # feeds the allreduce_quant_error gauge

    def encode(self, arr: np.ndarray) -> bytes:
        frame, smax = _quantize4(arr)
        self.max_scale = max(self.max_scale, smax)
        return bytes(frame)

    def decode(self, frame, n: int, wire: np.dtype) -> np.ndarray:
        self.max_scale = max(self.max_scale, _scales_max(frame, n))
        out = _dequantize4(frame, n)
        return out if wire == np.float32 else out.astype(wire)


class _CastCodec:
    """Ship chunks cast to a narrower float dtype (bfloat16: half the
    fp32 wire bytes, ~2^-8 relative rounding per cast event); received
    frames cast back up into the accumulation dtype."""

    max_scale = 0.0              # cast codecs don't report a quant bound

    def __init__(self, wdt: np.dtype):
        self.wdt = wdt
        self.tag = wdt.str

    def encode(self, arr: np.ndarray) -> bytes:
        return arr.astype(self.wdt, copy=False).tobytes()

    def decode(self, frame, n: int, wire: np.dtype) -> np.ndarray:
        return np.frombuffer(frame, self.wdt, n).astype(wire)


def resolve_wire_dtype(wire_dtype) -> Optional[np.dtype]:
    """Map the user-facing ``wire_dtype`` option to a numpy dtype.
    Accepts None, "bfloat16", "float16" (or their dtype objects)."""
    if wire_dtype is None:
        return None
    if isinstance(wire_dtype, str) and wire_dtype == "bfloat16" \
            or getattr(wire_dtype, "name", None) == "bfloat16":
        try:
            import ml_dtypes
        except ImportError:
            raise ValueError(
                "wire_dtype='bfloat16' needs the ml_dtypes package "
                "(ships with jax)")
        return np.dtype(ml_dtypes.bfloat16)
    try:
        dt = np.dtype(wire_dtype)
    except TypeError:
        dt = None
    if dt == np.float16:
        return dt
    raise ValueError(
        f"wire_dtype must be None, 'bfloat16' or 'float16', "
        f"got {wire_dtype!r}")


def _make_codec(quantize: Optional[str], wdt: Optional[np.dtype]):
    if quantize == "int8":
        return _Int8Codec()
    if quantize == "int4":
        return _Int4Codec()
    if wdt is not None:
        return _CastCodec(wdt)
    return None


def codec_roundtrip(x: np.ndarray, quantize: str) -> np.ndarray:
    """What a lossy wire codec would RECONSTRUCT from ``x`` — the local
    encode/decode round-trip error-feedback accumulation subtracts to
    recover the residual the wire dropped, without any extra frames
    (train/collective.py ErrorFeedback). Block boundaries here follow
    the flat vector; the ring chunks by channel slot, so per-element
    scales can differ slightly — EF only needs the residual to be a
    faithful estimate, not bitwise wire parity."""
    codec = _make_codec(quantize, None)
    if codec is None:
        return np.asarray(x, np.float32)
    flat = np.ascontiguousarray(np.asarray(x, np.float32)).reshape(-1)
    return codec.decode(codec.encode(flat), flat.size,
                        np.dtype(np.float32))


# Last observed per-codec error bound in THIS process (what _finish
# just pushed to the tagged gauge) — the live signal
# ``allreduce_gradients(codec="auto")`` consults to back off a codec
# whose bound tripped. Keyed by codec tag ("int8"/"int4"/"bf16"/...).
_LAST_QUANT_ERR: Dict[str, float] = {}


def last_quant_error(tag: str) -> Optional[float]:
    """The most recent ``allreduce_quant_error`` this process observed
    for one codec tag, or None when that codec never ran here."""
    return _LAST_QUANT_ERR.get(tag)


def _codec_gauge_tag(q: Optional[str], codec) -> str:
    """The {codec=...} label value for one round: the quantize mode
    when set, the cast codec's short name, "fp32" otherwise."""
    if q:
        return q
    wdt = getattr(codec, "wdt", None)
    if wdt is not None:
        return "bf16" if "bfloat16" in str(wdt) else "fp16"
    return "fp32"


def rebuild_from_layout(flat: np.ndarray, layout: dict):
    """Reassemble a flat vector into the pytree a reduce-scatter-style
    layout describes: {"rebuild": closure, "leaves": [(shape, size,
    out_dtype)]}. THE single flat->pytree path — ring.allgather, the
    train world_size==1 twin, and ShardedOptimizer all rebuild through
    here so the cast-back policy cannot drift between them."""
    outs, off = [], 0
    for shape, size, dt in layout["leaves"]:
        outs.append(flat[off:off + size].reshape(shape)
                    .astype(dt, copy=False))
        off += size
    return layout["rebuild"](iter(outs))


# --- collective tracing + flight recorder --------------------------------


TRACE_LEVELS = ("off", "round", "chunk")


class _RingTrace:
    """Per-participant collective tracing and flight recorder.

    Levels (Config.collective_trace_level, overridable per ring spec):

      "round"  one structured span per collective round — collective
               id, op, payload bytes, codec, send/recv-wait/header
               timing — recorded into the bounded "collective" event
               category (util/events) so it rides the existing
               worker -> agent -> head collection into
               ``timeline(all_nodes=True)`` / ``to_chrome`` as
               per-rank ring lanes with cross-rank flow edges.
      "chunk"  additionally one span per chunk send / recv-wait /
               reduce-decode, tagged with phase, segment and round —
               the depth that localizes a slow link to a specific
               pipeline position.

    **Straggler attribution** costs zero extra frames: each rank
    piggybacks its previous round's recv-wait total on the header
    relay (headers already reach every rank), so during round k+1
    every rank holds every rank's round-k wait and computes the SAME
    straggler — the rank *preceding* the argmax waiter, because a slow
    rank starves its downstream neighbor's reads. Exported as the
    head-aggregated ``allreduce_straggler_rank`` gauge plus per-rank
    ``collective_recv_wait_s`` histograms.

    The **flight recorder** keeps the last K rounds' timing records in
    a bounded deque regardless of event-buffer pressure; when a round
    dies (peer death, agreed ERROR frame, protocol desync) ``dump()``
    writes them to a JSON file and ``attach()`` stitches the path into
    the raised exception's message and ``flight_recorder_path``
    attribute — the first hang in a 600 s-timeout job stays
    diagnosable after the process is gone.
    """

    _KIND = {"round": "allreduce", "rs_round": "reduce_scatter",
             "ag_round": "allgather", "bc_round": "broadcast"}

    def __init__(self, rank: int, size: int, level: str, group: str,
                 metrics: dict, flight_rounds: int, flight_dir: str,
                 ring_level: Optional[str] = None):
        self.rank, self.size = int(rank), int(size)
        self.level = level
        # hierarchy level tag stamped on every span this sub-ring
        # records ("intra"/"inter"; broadcast rounds override to
        # "bcast"); None for a flat ring. Keeps to_chrome lanes and
        # straggler attribution from cross-wiring the two levels —
        # each sub-ring also carries a distinct group id.
        self.ring_level = ring_level
        self.group = group or "ring"
        self._m = metrics
        self.flight: "deque" = deque(maxlen=max(1, int(flight_rounds or 1)))
        self.flight_dir = flight_dir
        self.round_no = -1
        self.step: Optional[int] = None   # train-step tag (callers set)
        self.prev_wait: Optional[float] = None
        self.last_rw: Dict[int, float] = {}
        self.last_straggler: Optional[int] = None
        self.last_dump_path: Optional[str] = None
        self._last_dump_ts = 0.0
        self.cur: Optional[dict] = None

    # -- round lifecycle --------------------------------------------------

    def begin(self) -> None:
        self.round_no += 1
        self.cur = {"round": self.round_no, "t0": time.time(),
                    "kind": None, "op": None, "codec": None,
                    "level": self.ring_level,
                    "step": self.step, "send_s": 0.0, "wait_s": 0.0,
                    "apply_s": 0.0, "hdr_s": 0.0}
        if self.level == "chunk":
            self.cur["chunks"] = []

    def options(self, op: str, codec: Optional[str]) -> None:
        if self.cur is not None:
            self.cur["op"] = op
            self.cur["codec"] = codec

    def io(self, what: str, dt: float, nbytes: int, phase: str,
           seg: int, apply_s: float = 0.0) -> None:
        """One wire operation: ``what`` is "send" or "recv", ``dt`` the
        blocked time, ``apply_s`` the in-window decode/reduce time of a
        read_with callback.

        ``wait_s`` — the straggler-attribution signal — counts the
        FIRST header read (the direct wait for the predecessor to
        enter the round) plus every data-phase read. Later header
        reads are RELAY forwards: a late entrant's delay reaches every
        rank through them with nearly equal magnitude, which would
        smear the argmax across innocent ranks — those land in
        ``hdr_s`` instead."""
        cur = self.cur
        if cur is None:
            return
        if phase == "hdr":
            if what == "recv" and not cur.get("_hdr0"):
                cur["_hdr0"] = True
                cur["wait_s"] += dt
            else:
                cur["hdr_s"] += dt + apply_s
        elif what == "send":
            cur["send_s"] += dt
        else:
            cur["wait_s"] += dt
            cur["apply_s"] += apply_s
        if "chunks" in cur and phase != "hdr":
            cur["chunks"].append(
                {"name": what, "ts": time.time() - dt - apply_s,
                 "dur": dt, "apply_s": round(apply_s, 6),
                 "phase": phase, "seg": seg, "bytes": nbytes})

    def header_extra(self) -> dict:
        ex: dict = {"rn": self.round_no}
        if self.prev_wait is not None:
            ex["rw"] = self.prev_wait
        return ex

    def on_headers(self, headers: Dict[int, dict]) -> None:
        rw = {o: float(h["rw"]) for o, h in headers.items()
              if h.get("rw") is not None}
        if len(rw) != self.size:
            return                     # first round: no prior data yet
        self.last_rw = rw
        waits = sorted(rw.values())
        top = max(rw, key=lambda o: rw[o])
        # significance gate: only attribute when one rank's wait
        # DOMINATES (>= 5 ms absolute and >= 2x the median of the
        # OTHER ranks' waits — overall median would equal the max for
        # N=2 and block attribution there) — a healthy round's argmax
        # is scheduler noise, and pinning a gauge to an innocent rank
        # is worse than saying "none"
        rest = waits[:-1]
        med = rest[len(rest) // 2]
        if rw[top] >= 0.005 and rw[top] >= 2.0 * med:
            # everyone's reads stalled behind the rank BEFORE the
            # longest waiter: that predecessor is the straggler
            self.last_straggler = (top - 1) % self.size
        else:
            self.last_straggler = None
        try:
            self._m["straggler"].set(
                -1 if self.last_straggler is None
                else self.last_straggler)
        except Exception:
            pass

    def end(self, key: str, wrote: int,
            err: Optional[BaseException]) -> None:
        cur, self.cur = self.cur, None
        if cur is None:
            return
        kind = self._KIND.get(key, key)
        cur.pop("_hdr0", None)
        dur = time.time() - cur["t0"]
        cur.update(kind=kind, dur=round(dur, 6), bytes=int(wrote),
                   error=repr(err) if err is not None else None)
        self.prev_wait = cur["wait_s"]
        chunks = cur.pop("chunks", None)
        self.flight.append(dict(cur, chunks=chunks) if chunks is not None
                           else cur)
        try:
            self._m["recv_wait"].observe(
                cur["wait_s"], tags={"rank": str(self.rank)})
        except Exception:
            pass
        try:
            # the round's recv wait is by construction NOT hidden under
            # compute (the caller is blocked in the collective) — it is
            # the goodput ledger's comm_exposed category, attributed to
            # whatever step window is open on this thread
            from ray_tpu.util import goodput
            goodput.add("comm_exposed", cur["wait_s"])
        except Exception:
            pass
        events.record(
            "collective", "round", ph="X", ts=cur["t0"], dur=dur,
            kind=kind, op=cur["op"], codec=cur["codec"],
            level=cur.get("level"),
            group=self.group, cid=cur["round"], rank=self.rank,
            size=self.size, step=cur["step"], bytes=cur["bytes"],
            send_s=round(cur["send_s"], 6),
            recv_wait_s=round(cur["wait_s"], 6),
            headers_s=round(cur["hdr_s"], 6),
            straggler=self.last_straggler,
            error=err is not None, pid=os.getpid())
        for c in chunks or ():
            events.record(
                "collective", c["name"], ph="X", ts=c["ts"],
                dur=c["dur"] + c["apply_s"], phase=c["phase"],
                seg=c["seg"], bytes=c["bytes"], group=self.group,
                cid=cur["round"], rank=self.rank, pid=os.getpid())
        if err is not None:
            self.attach(err, self.dump(err))

    # -- post-mortem ------------------------------------------------------

    def summary(self) -> dict:
        last = None
        for r in reversed(self.flight):
            last = {k: v for k, v in r.items() if k != "chunks"}
            break
        return {"rank": self.rank, "size": self.size,
                "group": self.group,
                # set by RingReducer.from_spec (channel.spec_transport):
                # a post-mortem reader learns whether the hung edge was
                # a TCP link or same-host shm without the spec in hand
                "transports": getattr(self, "transports", None),
                "rounds_recorded": len(self.flight),
                "last_straggler": self.last_straggler,
                "recv_wait_by_rank": dict(self.last_rw),
                "last_round": last}

    def dump(self, err: Optional[BaseException]) -> Optional[str]:
        """Write the flight records to a JSON file; returns the path.
        Rate-limited (a dag loop relaying ERROR frames per item must
        not write one file per item); never raises — post-mortem
        bookkeeping must not mask the real failure."""
        now = time.time()
        if now - self._last_dump_ts < 5.0:
            return self.last_dump_path
        try:
            d = self.flight_dir or os.path.join(
                tempfile.gettempdir(), "ray_tpu_flight")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"ring-{self.group}-r{self.rank}-{os.getpid()}-"
                   f"{int(now * 1000)}.json")
            rounds = list(self.flight)
            if self.cur is not None:       # the in-flight failing round
                rounds.append(dict(self.cur))
            with open(path, "w") as f:
                json.dump({"error": repr(err) if err else None,
                           "ts": now, **self.summary(),
                           "rounds": rounds}, f, default=str)
            self._last_dump_ts = now
            self.last_dump_path = path
            return path
        except Exception:
            return None

    def attach(self, err: Optional[BaseException],
               path: Optional[str]) -> None:
        """Stitch the dump path + a per-rank summary into the raised
        exception (and its RingPeerDead ``cause``, whose message is
        what train/dag error paths re-surface). The message is only
        rewritten for rank-LOCAL terminal errors (peer death, protocol
        desync — the path is per-rank anyway); agreed error frames
        must stay byte-identical on every rank, so those carry the
        path as attributes only."""
        if err is None or path is None:
            return
        note = f" [collective flight recorder: {path}]"
        local = isinstance(err, (RingPeerDead, RingProtocolError))
        for e in (err, getattr(err, "cause", None)):
            if not isinstance(e, BaseException):
                continue
            try:
                e.flight_recorder_path = path
                e.flight_recorder_summary = self.summary()
                if local and e.args and isinstance(e.args[0], str) \
                        and path not in e.args[0]:
                    e.args = (e.args[0] + note,) + e.args[1:]
            except Exception:
                pass


# --- the ring ------------------------------------------------------------


class RingReducer:
    """One participant's endpoint pair in a ring allreduce group. Every
    participant must enter every round (with a value, or with the ERROR
    frame it would have shipped) and all per-round options (op,
    quantize) must match across the group — mismatches are detected in
    the header phase and surface as the same error on every rank."""

    def __init__(self, to_next, from_prev, *, rank: int, size: int,
                 op: str = "sum", timeout_s: float = 600.0,
                 quantize: Optional[str] = None,
                 chunk_bytes: Optional[int] = None,
                 wire_dtype=None, own: Optional[int] = None,
                 trace_level: Optional[str] = None, group: str = "",
                 level: Optional[str] = None, tune: bool = False):
        if size < 2:
            raise ValueError("ring allreduce needs at least 2 ranks")
        if quantize not in _QUANTIZE_MODES:
            raise ValueError(f"quantize must be one of {_QUANTIZE_MODES}")
        self.to_next = to_next
        self.from_prev = from_prev
        self.rank = int(rank)
        self.size = int(size)
        self.op = op
        self.timeout_s = float(timeout_s)
        self.quantize = quantize
        self.wire_dtype = resolve_wire_dtype(wire_dtype)
        # The flat value space is split into `size` contiguous segments
        # (total*i//n .. total*(i+1)//n); this rank OWNS segment `own`
        # after a reduce-scatter — the shard the ZeRO optimizer updates.
        # Ring consistency requires ownership to be a rotation:
        # own(r) = (r + shift) % n with the SAME shift on every rank —
        # validated in the header phase via the shift tag.
        self.own = self.rank if own is None else int(own)
        if not 0 <= self.own < self.size:
            raise ValueError(
                f"own segment {self.own} out of range for {size} ranks")
        slot = min(to_next.slot_bytes, from_prev.slot_bytes)
        # floor at 4096 (tiny chunks drown in per-frame overhead) but
        # NEVER exceed the slot — a chunk that can't fit its channel
        # would desync the group mid-phase
        self.chunk_bytes = min(slot, max(
            4096, min(chunk_bytes or DEFAULT_CHUNK_BYTES, slot)))
        self._m = allreduce_metrics()
        self._wrote = 0           # wire bytes this round (batched inc)
        self._layout = None       # cached by reduce_scatter for allgather
        # Group label: tags spans/flight dumps, and keys the in-situ
        # tuner cache (one profile per ring generation).
        self.group = group or ""
        # Hierarchy level of THIS ring ("intra"/"inter" for the
        # sub-rings of a HierarchicalReducer, None for a flat ring):
        # stamped on every span, and "inter" rings additionally meter
        # their writes into allreduce_hier_inter_bytes_total.
        self.level = level
        if level not in (None, "intra", "inter"):
            raise ValueError(
                f"ring level must be None, 'intra' or 'inter', "
                f"got {level!r}")
        # In-situ auto-tuning (dag/tuner.py): when set, the first
        # collective op runs two tiny probe rounds (identically on
        # every rank — probes ARE collectives) and later rounds pick
        # their chunk size from the tuned table per payload band.
        self._tune = bool(tune)
        self._tuning = False      # reentrancy guard: probes call reduce
        self._base_chunk = self.chunk_bytes
        self._payload_hint: Optional[int] = None  # last round's bytes
        # Collective tracing + flight recorder (Config default, spec
        # override). "off" skips every clock read on the hot path.
        from ray_tpu.config import get_config
        cfg = get_config()
        level = trace_level if trace_level is not None \
            else getattr(cfg, "collective_trace_level", "round")
        if level not in TRACE_LEVELS:
            raise ValueError(
                f"collective trace level must be one of {TRACE_LEVELS}, "
                f"got {level!r}")
        self._tr = None if level == "off" else _RingTrace(
            self.rank, self.size, level, group, self._m,
            getattr(cfg, "collective_flight_rounds", 8),
            getattr(cfg, "collective_flight_dir", ""),
            ring_level=self.level)
        self.step: Optional[int] = None   # train-step span tag
        self._tr_err: Optional[BaseException] = None
        self._ph = "hdr"                  # current phase for chunk spans
        self._seg_tx = self._seg_rx = -1  # current segments in flight
        self._abort = False               # set by abort() (any thread)
        # Hang/desync forensics: the process-wide collective ledger
        # this ring feeds (util/forensics.py). Resolved once here —
        # per round the cost is two dict appends when on, one None
        # check when off.
        self._fx = forensics.ledger() if forensics.enabled() else None
        self._fx_tok: Optional[int] = None

    @classmethod
    def from_spec(cls, spec: Dict[str, Any],
                  abort=None) -> "RingReducer":
        """Attach both ring edges from a controller-built spec:
        {"rank", "size", "to_next", "from_prev", "op"?, "timeout_s"?,
        "quantize"?, "chunk_bytes"?} — channel specs are the same dicts
        the dag compiler produces (shm / lazy-shm / tcp).

        The consumer side attaches FIRST: lazy shm segments are created
        by their consumer, so when every rank attaches concurrently each
        must create its inbound edge before polling for its outbound
        one — the reverse order deadlocks the whole ring at attach.
        Attach waits honor the spec's timeout_s (participants may reach
        their first round arbitrarily skewed — compile, data load), and
        an attach that still times out surfaces as RingPeerDead like
        any other unresponsive-neighbor condition. ``abort`` (polled
        by the blocking lazy-shm producer wait) interrupts an attach
        early — the elastic rewire path, where the specs belong to an
        incarnation the controller has already declared dead."""
        timeout_s = float(spec.get("timeout_s", 600.0))
        from_prev = None
        try:
            from_prev = attach_channel(spec["from_prev"], "consumer",
                                       timeout=timeout_s, abort=abort)
            to_next = attach_channel(spec["to_next"], "producer",
                                     timeout=timeout_s, abort=abort)
        except (ChannelTimeout, ChannelClosed) as e:
            if from_prev is not None:
                # we created the inbound (consumer-owned) segment;
                # don't leak it when the outbound attach fails
                try:
                    from_prev.close()
                    if getattr(from_prev, "_lazy_owner", False):
                        from_prev.unlink()
                except Exception:
                    pass
            raise RingPeerDead(RuntimeError(
                f"ring allreduce peer never attached within "
                f"{timeout_s}s (participant died before its first "
                f"round?): {e}"))
        ring = cls(to_next, from_prev,
                   rank=spec["rank"], size=spec["size"],
                   op=spec.get("op", "sum"),
                   timeout_s=timeout_s,
                   quantize=spec.get("quantize"),
                   chunk_bytes=spec.get("chunk_bytes"),
                   wire_dtype=spec.get("wire_dtype"),
                   own=spec.get("own"),
                   trace_level=spec.get("trace_level"),
                   group=spec.get("group", ""),
                   level=spec.get("level"),
                   tune=bool(spec.get("tune")))
        # transport mix for post-mortems: flight-dump summaries say
        # whether a slow/hung edge was a TCP link or same-host shm
        from ray_tpu.dag.channel import spec_transport
        ring.transports = {"from_prev": spec_transport(spec["from_prev"]),
                           "to_next": spec_transport(spec["to_next"])}
        if ring._tr is not None:
            ring._tr.transports = ring.transports
        return ring

    def channels(self) -> list:
        return [self.to_next, self.from_prev]

    def close(self):
        for ch in self.channels():
            try:
                ch.close()
                if getattr(ch, "_lazy_owner", False):
                    ch.unlink()
            except Exception:  # noqa: BLE001 — teardown
                pass

    # --- wire helpers ---------------------------------------------------

    def abort(self) -> None:
        """Interrupt any blocked ring op from ANOTHER thread (the
        elastic-training rewire path: the controller has already
        decided this incarnation is dead, so a survivor blocked on a
        dead neighbor must not wait out the full ring timeout before
        it can re-form). The next sliced wait raises RingPeerDead with
        a reshape message; the flag is sticky for this ring — a
        reshaped group attaches a FRESH ring. Any in-flight ledger
        entry is stamped terminal ``aborted`` HERE (not just when the
        blocked op unwinds) so a post-abort audit never reports a
        phantom in-flight collective from a rank that already gave
        up."""
        self._abort = True
        try:
            if self._fx is not None and self._fx_tok is not None:
                self._fx.exit(self._fx_tok, state="aborted",
                              err="abort(): ring declared dead while "
                                  "the collective was in flight")
        except Exception:   # noqa: BLE001 — bookkeeping must not mask
            pass

    def _op_sliced(self, op):
        """Run one channel op under the ring timeout, sliced into
        short waits (_ABORT_SLICE_S) so abort() can interrupt a
        blocked participant. ``op(t)`` must be safely retryable after
        a ChannelTimeout with no partial effect — both channel flavors
        guarantee that (shm waits are stateless; TcpChannel reads
        resume mid-frame and its writes only time out before any frame
        byte is committed). ChannelAttachRefused is retried too: a
        refused connect within one slice means the peer may still be
        mid-restart, and only the ring timeout decides it is dead."""
        if self._abort:
            raise _AbortedOp()
        deadline = time.monotonic() + self.timeout_s
        retrying = False
        try:
            while True:
                left = deadline - time.monotonic()
                try:
                    return op(max(1e-3, min(_ABORT_SLICE_S, left)))
                except (ChannelTimeout, ChannelAttachRefused) as e:
                    if self._abort:
                        raise _AbortedOp()
                    # an injected chaos read-drop fires exactly once —
                    # a retry would re-read the still-present frame and
                    # silently nullify the fault, so surface it as-is
                    if getattr(e, "chaos_injected", False) \
                            or time.monotonic() >= deadline:
                        raise
                    # retries re-enter the same LOGICAL channel op:
                    # keep the chaos Nth-op counters from advancing
                    retrying = True
                    chaos_mark_retry(True)
        finally:
            if retrying:
                chaos_mark_retry(False)

    def _op_fail(self, which: str, e: BaseException) -> RingPeerDead:
        if isinstance(e, _AbortedOp):
            return RingPeerDead(RuntimeError(
                f"ring collective aborted on rank {self.rank}: the "
                f"worker group is being reshaped (elastic recovery)"))
        peer = (self.rank + 1) % self.size if which == "next" \
            else (self.rank - 1) % self.size
        return RingPeerDead(RuntimeError(
            f"ring allreduce peer (rank {peer})"
            f" unresponsive for {self.timeout_s}s "
            f"(participant died?): {e}"))

    def _write(self, payload):
        mv = payload if isinstance(payload, memoryview) \
            else memoryview(payload)
        tr = self._tr
        t0 = time.monotonic() if tr is not None else 0.0
        try:
            self._op_sliced(
                lambda t: self.to_next.write(mv, DATA, timeout=t))
        except (ChannelTimeout, ChannelClosed, _AbortedOp) as e:
            if tr is not None:   # the stalled write IS the evidence
                tr.io("send", time.monotonic() - t0, mv.nbytes,
                      self._ph, self._seg_tx)
            raise self._op_fail("next", e)
        if tr is not None:
            tr.io("send", time.monotonic() - t0, mv.nbytes,
                  self._ph, self._seg_tx)
        self._wrote += mv.nbytes

    def _read_with(self, fn):
        tr = self._tr
        if tr is None:
            try:
                return self._op_sliced(
                    lambda t: self.from_prev.read_with(fn, t))
            except (ChannelTimeout, ChannelClosed, _AbortedOp) as e:
                raise self._op_fail("prev", e)
        # split the window into WAIT (blocked on the predecessor — the
        # straggler-attribution signal) and APPLY (fn: decode + reduce)
        t0 = time.monotonic()
        box = [t0, t0, 0]

        def timed(kind, mv, fn=fn):
            box[0] = time.monotonic()
            box[2] = mv.nbytes
            out = fn(kind, mv)
            box[1] = time.monotonic()
            return out

        try:
            out = self._op_sliced(
                lambda t: self.from_prev.read_with(timed, t))
        except (ChannelTimeout, ChannelClosed, _AbortedOp) as e:
            # record the fatal wait: in the flight dump THIS is the
            # row that shows where the round hung
            tr.io("recv", time.monotonic() - t0, 0,
                  self._ph, self._seg_rx)
            raise self._op_fail("prev", e)
        tr.io("recv", box[0] - t0, box[2], self._ph, self._seg_rx,
              apply_s=box[1] - box[0])
        return out

    def _read_bytes(self):
        return self._read_with(lambda k, mv: (k, bytes(mv)))

    # --- phases ---------------------------------------------------------

    def _exchange_headers(self, hdr: dict) -> Dict[int, dict]:
        """N-1 relay steps: send own header, forward what arrives.
        Every rank ends holding every rank's header — the ordered,
        deadlock-free carrier for errors and layout validation. The
        tracer piggybacks its previous-round recv-wait here (straggler
        attribution rides frames that move anyway)."""
        if self._tr is not None:
            hdr.update(self._tr.header_extra())
        if self._fx is not None and self._fx_tok is not None and \
                hdr.get("sig") is not None:
            # the ONE chokepoint every op's resolved options pass
            # through: the signature hash lands on the ledger row so a
            # cross-rank audit can diff what each rank actually sent
            self._fx.note(self._fx_tok,
                          sig=forensics.sig_hash(hdr["sig"]))
        self._ph = "hdr"
        headers = {self.rank: hdr}
        frame = dumps_oob(hdr)
        for _ in range(self.size - 1):
            self._write(frame)
            kind, data = self._read_bytes()
            if kind != DATA:
                raise RingProtocolError(
                    f"unexpected frame kind {kind} in ring header phase")
            got = loads_oob(data)
            headers[got["origin"]] = got
            frame = data
        if self._tr is not None:
            self._tr.on_headers(headers)
        return headers

    def _chunks(self, lo: int, hi: int, itemsize: int):
        step = max(1, self.chunk_bytes // itemsize)
        return [(p, min(p + step, hi)) for p in range(lo, hi, step)]

    def _send_chunk(self, arr: np.ndarray):
        if self._codec is not None:
            self._write(self._codec.encode(arr))
        else:
            self._write(arr.data.cast("B"))

    def _begin(self, op: Optional[str], quantize, wire_dtype,
               kind: str = "allreduce"):
        """Resolve + validate per-round options BEFORE any frame moves
        (a bad option discovered mid-phase would waste a collective
        round on every rank). Returns the resolved op; sets the round's
        codec. The shift tag ((own - rank) % size) rides every header
        sig: segment ownership must be the same rotation on all ranks
        or reduce-scatter results would interleave garbage.

        Safe defaults land FIRST so _finish (in the caller's finally)
        works even when validation raises — the standalone ops call
        this inside their error-frame try, turning a rank-local option
        failure (e.g. one host missing ml_dtypes) into an error frame
        every peer sees in one relay instead of a ring-timeout stall."""
        self._q = None
        self._codec = None
        self._shift = (self.own - self.rank) % self.size
        self._qmax = 0.0
        self._wrote = 0
        self._tr_err = None
        self._fx_tok = None   # cleared FIRST: a validation raise below
        #                       must not leave _finish a stale token
        self._ph = "hdr"
        self._seg_tx = self._seg_rx = -1
        if self._tr is not None:
            self._tr.step = self.step
            self._tr.begin()
        op = op or self.op
        if op not in ("sum", "mean", "max", "min"):
            raise ValueError(f"unknown op {op!r}")
        q = self.quantize if quantize is _UNSET else quantize
        if q not in _QUANTIZE_MODES:
            raise ValueError(f"quantize must be one of {_QUANTIZE_MODES}")
        wdt = self.wire_dtype if wire_dtype is _UNSET \
            else resolve_wire_dtype(wire_dtype)
        if q is not None and wdt is not None:
            raise ValueError(
                "quantize and wire_dtype are both wire codecs — pass "
                "at most one")
        self._q = q
        self._codec = _make_codec(q, wdt)
        if self._tr is not None:
            self._tr.options(op, self._codec.tag if self._codec else None)
        if self._fx is not None:
            # ledger enter AFTER option validation: a raise above never
            # reaches the wire, so it must not leave an in_flight row
            g = self.group or "ring"
            self._fx_tok = self._fx.enter(
                group=g, kind=kind, seq=self._fx.next_seq(g), op=op,
                codec=self._codec.tag if self._codec else None,
                step=self.step, size=self.size)
        return op

    def _finish(self, key: str, t0: float):
        if self._codec is not None:
            self._qmax = max(self._qmax, self._codec.max_scale)
        self._m["bytes"].inc(self._wrote)
        if self.level == "inter":
            # the cross-node leg of a hierarchical collective: THE
            # traffic the ring-of-rings exists to shrink
            self._m["hier_inter_bytes"].inc(self._wrote)
        tag = _codec_gauge_tag(self._q, self._codec)
        err = 0.5 * self._qmax * self.size if self._q else 0.0
        self._m["quant_err"].set(err, tags={"codec": tag})
        _LAST_QUANT_ERR[tag] = err
        self._m[key].observe(time.monotonic() - t0)
        if self._tr is not None:
            try:            # tracing must never mask the round's error
                self._tr.end(key, self._wrote, self._tr_err)
            except Exception:
                pass
        if self._fx is not None and self._fx_tok is not None:
            try:            # ledger close rides the same clock read
                self._fx.exit(
                    self._fx_tok,
                    state="done" if self._tr_err is None else "aborted",
                    err=None if self._tr_err is None
                    else f"{type(self._tr_err).__name__}: "
                         f"{self._tr_err}",
                    nbytes=self._wrote)
            except Exception:
                pass
            self._fx_tok = None

    # --- in-situ auto-tuning (dag/tuner.py) ------------------------------

    def _ensure_tuned(self):
        """Lazily run the one-shot in-situ micro-bench on THIS ring
        the first time any collective op is called (probes are
        themselves collective rounds, so every rank reaches them in
        lockstep and runs the identical sequence). Cached per ring
        generation — keyed by the group id, which the controller
        regenerates per incarnation — so a rewired group re-probes.
        No-op unless the spec opted in (``tune``) and
        Config.collective_tuner is on."""
        if not self._tune or self._tuning:
            return
        from ray_tpu.config import get_config
        if not getattr(get_config(), "collective_tuner", True):
            return
        from ray_tpu.dag import tuner
        if tuner.profile_for(self.group, self.size) is not None:
            return
        self._tuning = True
        try:
            tuner.probe_ring(self)
        finally:
            self._tuning = False

    def _apply_tuned_chunk(self, payload_bytes: int) -> None:
        """Per-round chunk size from the tuned table's payload band
        (falls back to the constructor chunk when untuned), plus the
        ``collective_tuner_regime`` gauge for this payload. The
        payload hint is derived from the ALREADY-flattened layout —
        never a flatten-just-to-size pass — and memoized in
        ``_payload_hint``: training steps repeat the same layout, so
        every round after the first reuses the previous decision
        instead of re-consulting the tuner table."""
        payload_bytes = int(payload_bytes)
        if not self._tune or self._tuning:
            if not self._tuning:     # probe rounds must not poison
                self._payload_hint = payload_bytes   # the memo
            return
        if payload_bytes == self._payload_hint:
            return                   # same layout as last round
        self._payload_hint = payload_bytes
        from ray_tpu.dag import tuner
        slot = min(self.to_next.slot_bytes, self.from_prev.slot_bytes)
        c = tuner.tuned_chunk(self.group, self.size, payload_bytes, slot)
        self.chunk_bytes = c if c else self._base_chunk
        tuner.choose_impl(payload_bytes, self.size,
                          hierarchical=self.level == "inter",
                          key=self.group)   # records the regime gauge

    def _check_codec_wire(self, wire: np.dtype):
        if self._codec is not None and wire.kind != "f":
            name = (f"{self._q} block quantization" if self._q
                    else f"wire_dtype={self._codec.tag!r}")
            raise TypeError(
                f"{name} requires floating-point values "
                f"(wire dtype would be {wire})")

    def round(self, kind: int, value, err_frame: Optional[bytes], *,
              op: Optional[str] = None,
              quantize=_UNSET, wire_dtype=_UNSET) -> Tuple[int, Any]:
        """One collective round. Returns (DATA, reduced_value) or
        (ERROR, frame) — the frame is an already-encoded exception every
        participant agrees on. Raises RingPeerDead when a neighbor stops
        responding (terminal for the group). ``op``/``quantize``/
        ``wire_dtype`` override the constructor defaults for this round
        (all ranks must pass the same values — validated in the header
        phase)."""
        self._ensure_tuned()
        op = self._begin(op, quantize, wire_dtype)
        t0 = time.monotonic()
        leaves = rebuild = wires = None
        hdr: Dict[str, Any] = {"origin": self.rank}
        if kind != DATA and err_frame is None:
            err_frame = dumps_oob(RuntimeError(
                "ring participant entered an error round without a "
                "frame"))
        if err_frame is None:
            try:
                leaves, rebuild, sig = _flatten(value)
                # PER-LEAF wire dtypes (star-path parity: an int64
                # counter next to float32 grads must neither widen the
                # grads to float64 nor round-trip the counter through
                # a float)
                wires = [_wire_dtype([l.dtype], op) for l in leaves]
                for w in wires:
                    self._check_codec_wire(w)
                hdr["sig"] = (sig, tuple(w.str for w in wires), op,
                              self._codec.tag if self._codec else None,
                              self._shift)
            except BaseException as e:  # noqa: BLE001 — enters as error
                try:
                    err_frame = dumps_oob(e)
                except Exception:
                    err_frame = dumps_oob(RuntimeError(
                        f"{type(e).__name__}: {e}"))
        if err_frame is not None:
            hdr["err"] = bytes(err_frame)
        try:
            headers = self._exchange_headers(hdr)
            agreed = self._agree(headers, "allreduce")
            if agreed is not None:
                # the frame is returned, not raised (the dag loop
                # forwards it downstream), so _tr_err must be set by
                # hand for the round span to record error=True; dump
                # now while the round is still in flight — reduce()
                # and other raisers attach last_dump_path
                self._tr_err = RuntimeError(
                    "collective round resolved to an agreed ERROR "
                    "frame")
                if self._tr is not None:
                    self._tr.dump(self._tr_err)
                return ERROR, agreed
            out = self._data_phases(leaves, rebuild, wires, op)
            return DATA, out
        except BaseException as e:  # noqa: BLE001 — flight recorder
            self._tr_err = e
            raise
        finally:
            self._finish("round", t0)

    def _agree(self, headers: Dict[int, dict],
               what: str) -> Optional[bytes]:
        """Post-header agreement: returns the ERROR frame every rank
        deterministically settles on (lowest-origin error, or a layout
        mismatch), or None when the round is clean."""
        err_origins = sorted(o for o, h in headers.items()
                             if h.get("err") is not None)
        if err_origins:
            return headers[err_origins[0]]["err"]
        sigs = {o: h["sig"] for o, h in headers.items()}
        if len(set(sigs.values())) != 1:
            lines = "; ".join(
                f"rank {o}: {sigs[o]!r}" for o in sorted(sigs))
            return dumps_oob(RuntimeError(
                f"ring {what} value layouts differ across "
                f"participants — {lines}"))
        return None

    def reduce(self, value, *, op: Optional[str] = None,
               quantize=_UNSET, wire_dtype=_UNSET):
        """Convenience wrapper: reduced value, or raises the group's
        agreed error (the train gradient-sync entrypoint)."""
        kind, out = self.round(DATA, value, None, op=op,
                               quantize=quantize, wire_dtype=wire_dtype)
        if kind == ERROR:
            err = loads_oob(out)
            if not isinstance(err, BaseException):
                err = RuntimeError(str(err))
            if self._tr is not None:
                self._tr.attach(err, self._tr.last_dump_path)
            raise err
        return out

    @staticmethod
    def _raise(frame):
        err = loads_oob(frame)
        raise err if isinstance(err, BaseException) \
            else RuntimeError(str(err))

    # --- standalone collective ops (the ZeRO building blocks) -----------

    def seg_bounds(self, total: int, seg: Optional[int] = None) -> \
            Tuple[int, int]:
        """(lo, hi) of segment ``seg`` (default: this rank's OWNED
        segment) in a flat length-``total`` value space — the canonical
        contiguous N-way split every collective op here uses."""
        s = self.own if seg is None else seg
        n = self.size
        return total * s // n, total * (s + 1) // n

    def reduce_scatter(self, value, *, op: Optional[str] = None,
                       quantize=_UNSET):
        """Standalone reduce-scatter: one header relay (layout/option
        validation + error propagation, exactly like a fused round)
        then the N-1 pipelined chunk steps — and NO allgather. Returns
        this rank's owned flat shard of the elementwise reduction: a
        1-D array, ``seg_bounds(total)`` of the flattened value space,
        mean already divided.

        Unlike the fused allreduce (which reduces per-leaf wire-dtype
        groups), the whole pytree is flattened into ONE wire dtype
        (numpy promotion over the leaves, low-precision floats widened
        to float32) — the flat shard is the unit the ZeRO optimizer
        updates. The layout is cached so a following allgather() can
        reassemble the full pytree. Raises the group's agreed error on
        layout mismatch / participant failure, RingPeerDead on a dead
        neighbor."""
        self._ensure_tuned()
        t0 = time.monotonic()
        leaves = rebuild = wire = None
        hdr: Dict[str, Any] = {"origin": self.rank}
        err_frame = None
        try:
            # option resolution INSIDE the try: a rank-local failure
            # ships as an error frame and reaches every peer in one
            # header relay instead of stalling them to ring timeout
            op = self._begin(op, quantize, _UNSET,
                             kind="reduce_scatter")
            leaves, rebuild, sig = _flatten(value)
            wire = _wire_dtype([l.dtype for l in leaves], op) \
                if leaves else np.dtype(np.float32)
            self._check_codec_wire(wire)
            hdr["sig"] = ("rs", sig, wire.str, op,
                          self._codec.tag if self._codec else None,
                          self._shift)
        except BaseException as e:  # noqa: BLE001 — enters as error
            try:
                err_frame = dumps_oob(e)
            except Exception:
                err_frame = dumps_oob(RuntimeError(
                    f"{type(e).__name__}: {e}"))
        if err_frame is not None:
            hdr["err"] = bytes(err_frame)
        try:
            headers = self._exchange_headers(hdr)
            agreed = self._agree(headers, "reduce_scatter")
            if agreed is not None:
                self._raise(agreed)
            src, total = self._flat_src(leaves, wire)
            self._apply_tuned_chunk(total * wire.itemsize)
            buf = np.empty(total, wire)
            bounds = [self.seg_bounds(total, i) for i in range(self.size)]
            self._rs_phase(src, buf, bounds, wire, op)
            lo, hi = bounds[self.own]
            if op == "mean":
                buf[lo:hi] /= self.size
            self._layout = {
                "rebuild": rebuild, "total": total, "wire": wire,
                "leaves": [(l.shape, l.size,
                            wire if _keeps_wide(l.dtype, op)
                            else l.dtype) for l in leaves]}
            return buf[lo:hi].copy()
        except BaseException as e:  # noqa: BLE001 — flight recorder
            self._tr_err = e
            raise
        finally:
            self._finish("rs_round", t0)

    def allgather(self, shard, *, wire_dtype=_UNSET, total_hint=None,
                  rebuild: bool = True):
        """Standalone allgather: every rank contributes its owned flat
        shard; after the header relay (shard lengths + dtype/option
        validation) and N-1 verbatim-forwarded chunk steps, every rank
        holds the full flat vector — reassembled into the cached
        reduce_scatter pytree layout when one matches (leaves cast back
        to their input dtypes), else returned flat.

        ``wire_dtype="bfloat16"`` ships every frame cast to bfloat16 —
        half the fp32 wire bytes, one ~2^-8-relative rounding event per
        element (the owner round-trips its own shard through the cast so
        all ranks stay bitwise identical). That is the ZeRO parameter
        reassembly: updated shards out, full (optionally bf16-shipped)
        parameters back.

        The cached layout is matched by ``total_hint`` when given, else
        by owned-slice length — a coincidental length match on an
        unrelated allgather reuses the stale layout; pass
        ``rebuild=False`` to ignore the cache entirely (flat vector
        back, wire dtype taken from the shard itself — what a caller
        that reassembles its own pytree wants, e.g. ShardedOptimizer
        rebuilding with PARAMETER leaf dtypes, not gradient ones)."""
        self._ensure_tuned()
        t0 = time.monotonic()
        hdr: Dict[str, Any] = {"origin": self.rank}
        err_frame = None
        layout = wire = None
        try:
            # everything that can fail on THIS rank's inputs — option
            # resolution included — happens inside the try: the failure
            # ships as an error frame and reaches every peer in one
            # header relay, instead of leaving them blocked for the
            # full ring timeout
            self._begin(None, _UNSET, wire_dtype, kind="allgather")
            shard = np.ascontiguousarray(np.asarray(shard)).reshape(-1)
            layout = self._layout if rebuild else None
            if layout is not None:
                # use the cached reduce_scatter layout only when this
                # shard plausibly IS that round's owned slice (explicit
                # total_hint, or matching owned-segment length) — a
                # stale layout must not silently recast an unrelated
                # allgather's wire dtype
                lo, hi = self.seg_bounds(layout["total"])
                if (layout["total"] != total_hint
                        if total_hint is not None
                        else hi - lo != shard.size):
                    layout = None
            wire = layout["wire"] if layout is not None else shard.dtype
            shard = np.ascontiguousarray(shard, dtype=wire)
            self._check_codec_wire(wire)
            hdr["n"] = shard.size
            hdr["sig"] = ("ag", wire.str,
                          self._codec.tag if self._codec else None,
                          self._shift)
        except BaseException as e:  # noqa: BLE001
            try:
                err_frame = dumps_oob(e)
            except Exception:
                err_frame = dumps_oob(RuntimeError(
                    f"{type(e).__name__}: {e}"))
        if err_frame is not None:
            hdr["err"] = bytes(err_frame)
        try:
            headers = self._exchange_headers(hdr)
            agreed = self._agree(headers, "allgather")
            if agreed is not None:
                self._raise(agreed)
            total = sum(h["n"] for h in headers.values())
            bounds = [self.seg_bounds(total, i) for i in range(self.size)]
            bad = sorted(
                o for o, h in headers.items()
                if h["n"] != (lambda b: b[1] - b[0])(
                    bounds[(o + self._shift) % self.size]))
            if bad:
                raise RuntimeError(
                    f"allgather shard lengths do not tile the flat "
                    f"value space: total {total}, offending rank(s) "
                    f"{bad} of {self.size} (every rank must pass "
                    f"exactly its seg_bounds(total) slice)")
            self._apply_tuned_chunk(total * wire.itemsize)
            buf = np.empty(total, wire)
            lo, hi = bounds[self.own]
            buf[lo:hi] = shard
            self._ag_phase(buf, bounds, wire)
            if layout is None or layout["total"] != total:
                return buf
            return rebuild_from_layout(buf, layout)
        except BaseException as e:  # noqa: BLE001 — flight recorder
            self._tr_err = e
            raise
        finally:
            self._finish("ag_round", t0)

    def broadcast(self, value, *, root: int = 0):
        """Pipelined ring broadcast of a FLAT array from ``root``: one
        header relay (root ships length + dtype; errors propagate like
        any other round) then the chunks flow root -> root+1 -> ... ->
        root-1, each intermediate rank forwarding VERBATIM — every
        rank ends holding bitwise-identical bytes. Non-root ranks pass
        ``value=None``. This is the hierarchical collective's fan-out
        phase (node leader -> members over shm); spans record kind
        "broadcast" with level tag "bcast" so timeline lanes can't
        cross-wire it with the reduce legs."""
        t0 = time.monotonic()
        hdr: Dict[str, Any] = {"origin": self.rank}
        err_frame = None
        arr = None
        try:
            self._begin(None, None, None,   # broadcasts ship raw bytes
                        kind="broadcast")
            if self._tr is not None and self._tr.cur is not None:
                self._tr.cur["level"] = "bcast"
                self._tr.options("bcast", None)
            root = int(root)
            if not 0 <= root < self.size:
                raise ValueError(
                    f"broadcast root {root} out of range for "
                    f"{self.size} ranks")
            if self.rank == root:
                arr = np.ascontiguousarray(np.asarray(value)).reshape(-1)
                hdr["bn"] = int(arr.size)
                hdr["bd"] = arr.dtype.str
            hdr["sig"] = ("bc", root)
        except BaseException as e:  # noqa: BLE001 — enters as error
            try:
                err_frame = dumps_oob(e)
            except Exception:
                err_frame = dumps_oob(RuntimeError(
                    f"{type(e).__name__}: {e}"))
        if err_frame is not None:
            hdr["err"] = bytes(err_frame)
        try:
            headers = self._exchange_headers(hdr)
            agreed = self._agree(headers, "broadcast")
            if agreed is not None:
                self._raise(agreed)
            rh = headers[root]
            n, dt = int(rh["bn"]), np.dtype(rh["bd"])
            self._ph = "bc"
            self._seg_tx = self._seg_rx = root
            if self.rank == root:
                for lo, hi in self._chunks(0, n, dt.itemsize):
                    self._write(arr[lo:hi].data.cast("B"))
                return arr
            buf = np.empty(n, dt)
            # the rank whose successor is the root terminates the chain
            forward = (self.rank + 1) % self.size != root
            for lo, hi in self._chunks(0, n, dt.itemsize):
                def apply(kind, mv, lo=lo, hi=hi):
                    if kind != DATA:
                        raise RingProtocolError(
                            f"unexpected frame kind {kind} in ring "
                            f"broadcast")
                    buf[lo:hi] = np.frombuffer(mv, dt)
                    return bytes(mv) if forward else None
                frame = self._read_with(apply)
                if forward:
                    self._write(frame)
            return buf
        except BaseException as e:  # noqa: BLE001 — flight recorder
            self._tr_err = e
            raise
        finally:
            self._finish("bc_round", t0)

    # --- data movement --------------------------------------------------

    def _data_phases(self, leaves, rebuild, wires, op):
        """Group leaves by wire dtype and run reduce-scatter+allgather
        once per group (deterministic first-appearance order, identical
        on every rank since the header phase validated leaf dtypes).
        Homogeneous pytrees — the common case — stay a single pass;
        mixed trees keep per-leaf accumulation exactness (an int64
        leaf never round-trips through float, a float32 leaf never
        pays float64 wire bytes)."""
        order: List[str] = []
        groups: Dict[str, List[int]] = {}
        for i, w in enumerate(wires):
            if w.str not in groups:
                order.append(w.str)
            groups.setdefault(w.str, []).append(i)
        outs: List[Optional[np.ndarray]] = [None] * len(leaves)
        for wstr in order:
            idxs = groups[wstr]
            reduced = self._reduce_group(
                [leaves[i] for i in idxs], np.dtype(wstr), op)
            for i, seg in zip(idxs, reduced):
                if not _keeps_wide(leaves[i].dtype, op):
                    seg = seg.astype(leaves[i].dtype, copy=False)
                outs[i] = seg
        return rebuild(iter(outs))

    def _flat_src(self, leaves, wire) -> Tuple[np.ndarray, int]:
        """Concatenate leaves into one flat wire-dtype vector (zero-copy
        when a single C-contiguous leaf already matches)."""
        total = int(sum(l.size for l in leaves))
        if len(leaves) == 1 and leaves[0].dtype == wire \
                and leaves[0].flags.c_contiguous:
            return leaves[0].reshape(-1), total
        src = np.empty(total, wire)
        off = 0
        for l in leaves:
            src[off:off + l.size] = np.asarray(
                l, dtype=wire).reshape(-1)
            off += l.size
        return src, total

    def _rs_phase(self, src, buf, bounds, wire, op):
        """The reduce-scatter phase: N-1 pipelined chunk steps; after
        them this rank holds the complete reduction of segment
        ``self.own`` in buf (NOT mean-divided — the caller owns that,
        it differs between the fused and standalone paths only in
        where it happens). Accumulation is fused
        (fuse(src, incoming, out=buf)) so buf needs no pre-fill, and
        always in the float32-or-wider wire dtype."""
        n, own = self.size, self.own
        itemsize = wire.itemsize
        fuse = {"sum": np.add, "mean": np.add,
                "max": np.maximum, "min": np.minimum}[op]
        # first-sent segment a0 = own - 1: each rank starts one segment
        # "behind" its owned one, so after N-1 accumulate-and-forward
        # steps the segment that lands complete is exactly `own`
        a0 = (own - 1) % n
        self._ph = "rs"
        for s in range(n - 1):
            send_seg = (a0 - s) % n
            recv_seg = (a0 - s - 1) % n
            self._seg_tx, self._seg_rx = send_seg, recv_seg
            frm = src if s == 0 else buf    # step 0 ships pristine input
            send_chunks = self._chunks(*bounds[send_seg], itemsize)
            recv_chunks = self._chunks(*bounds[recv_seg], itemsize)
            for k in range(max(len(send_chunks), len(recv_chunks))):
                if k < len(send_chunks):
                    lo, hi = send_chunks[k]
                    self._send_chunk(frm[lo:hi])
                if k < len(recv_chunks):
                    lo, hi = recv_chunks[k]

                    def apply(kind, mv, lo=lo, hi=hi):
                        if kind != DATA:
                            raise RingProtocolError(
                                f"unexpected frame kind {kind} in ring "
                                f"reduce-scatter")
                        if self._codec is not None:
                            inc = self._codec.decode(mv, hi - lo, wire)
                        else:
                            inc = np.frombuffer(mv, wire)
                        # fused init+accumulate: buf needs no pre-fill
                        fuse(src[lo:hi], inc, out=buf[lo:hi])
                    self._read_with(apply)

    def _ag_phase(self, buf, bounds, wire):
        """The allgather phase: this rank broadcasts its owned segment
        (complete in buf); received frames are forwarded VERBATIM, so
        codec payloads (int8 / bf16) are encoded exactly once — by the
        segment owner, which round-trips its own copy — and every rank
        reconstructs bitwise identical results."""
        n, own = self.size, self.own
        itemsize = wire.itemsize
        codec = self._codec
        outgoing: Optional[List[bytes]] = None
        if codec is not None:
            outgoing = []
            for lo, hi in self._chunks(*bounds[own], itemsize):
                frame = codec.encode(buf[lo:hi])
                # the owner applies its own encode/decode roundtrip so
                # its result matches what everyone else decodes
                buf[lo:hi] = codec.decode(frame, hi - lo, wire)
                outgoing.append(frame)
        self._ph = "ag"
        for s in range(n - 1):
            send_seg = (own - s) % n
            recv_seg = (own - s - 1) % n
            self._seg_tx, self._seg_rx = send_seg, recv_seg
            send_chunks = self._chunks(*bounds[send_seg], itemsize)
            recv_chunks = self._chunks(*bounds[recv_seg], itemsize)
            incoming: List[bytes] = []
            for k in range(max(len(send_chunks), len(recv_chunks))):
                if k < len(send_chunks):
                    if outgoing is not None:
                        self._write(outgoing[k])
                    else:
                        lo, hi = send_chunks[k]
                        self._write(buf[lo:hi].data.cast("B"))
                if k < len(recv_chunks):
                    lo, hi = recv_chunks[k]
                    if outgoing is not None:
                        kind, frame = self._read_bytes()
                        if kind != DATA:
                            raise RingProtocolError(
                                f"unexpected frame kind {kind} in ring "
                                f"allgather")
                        buf[lo:hi] = codec.decode(frame, hi - lo, wire)
                        incoming.append(frame)
                    else:
                        def apply(kind, mv, lo=lo, hi=hi):
                            if kind != DATA:
                                raise RingProtocolError(
                                    f"unexpected frame kind {kind} in "
                                    f"ring allgather")
                            buf[lo:hi] = np.frombuffer(mv, wire)
                        self._read_with(apply)
            if outgoing is not None:
                outgoing = incoming

    def _reduce_group(self, leaves, wire, op) -> List[np.ndarray]:
        """One reduce-scatter + allgather pass over leaves sharing one
        wire dtype; returns the reduced leaves (wire dtype, original
        shapes). This IS the fused allreduce: the same two standalone
        phases back to back over one flat buffer — no duplicated
        phase logic."""
        n = self.size
        src, total = self._flat_src(leaves, wire)
        self._apply_tuned_chunk(total * wire.itemsize)
        buf = np.empty(total, wire)         # filled by RS + AG below
        bounds = [self.seg_bounds(total, i) for i in range(n)]
        self._rs_phase(src, buf, bounds, wire, op)
        own_lo, own_hi = bounds[self.own]
        if op == "mean":
            buf[own_lo:own_hi] /= n
        self._ag_phase(buf, bounds, wire)
        # split back into per-leaf views of buf (cast-back to input
        # dtype happens in _data_phases, which knows the leaf policy)
        outs = []
        off = 0
        for l in leaves:
            outs.append(buf[off:off + l.size].reshape(l.shape))
            off += l.size
        return outs


# --- hierarchical (ring-of-rings) collectives ----------------------------


class _PoisonValue:
    """``np.asarray`` of this raises the carried exception — the hook
    for injecting an already-raised error into a collective leg's
    error-frame entry path: the leg's own prep try/except turns it
    into the err frame every peer agrees on in one header relay,
    instead of stalling them to the ring timeout."""

    def __init__(self, err: BaseException):
        self.err = err

    def __array__(self, *a, **kw):  # noqa: D105 — numpy hook
        raise self.err


def hier_seg_bounds(total: int, node_counts, world_rank: int):
    """(lo, hi) of ``world_rank``'s owned slice under the two-level
    split: the flat space is first split across nodes by the inter
    ring's even L-way split (total*i//L), then each node segment is
    split across its members by the intra ring's even k-way split.
    This nests EXACTLY with what the sub-rings' own ``seg_bounds``
    produce (the flat N-way split does not, for small totals), so
    hierarchical reduce-scatter shards always tile and validate."""
    counts = [int(c) for c in node_counts]
    L = len(counts)
    r = int(world_rank)
    node = 0
    while node < L and r >= counts[node]:
        r -= counts[node]
        node += 1
    if node >= L:
        raise ValueError(
            f"world rank {world_rank} out of range for nodes {counts}")
    base = total * node // L
    nlen = total * (node + 1) // L - base
    k = counts[node]
    return base + nlen * r // k, base + nlen * (r + 1) // k


def build_hier_specs(node_counts, intra_edge, inter_edge, *, op: str,
                     timeout_s: float, group: str,
                     quantize: Optional[str] = None,
                     chunk_bytes: Optional[int] = None,
                     tune: bool = False) -> List[Dict[str, Any]]:
    """THE ring-of-rings spec builder every plane shares (the train
    controller, the dag compiler, the bench): given per-node rank
    counts and two edge factories — ``intra_edge(i, j)`` returns the
    edge from local rank j to local rank (j+1)%k of node i,
    ``inter_edge(i)`` the edge from leader i to leader (i+1)%L — it
    emits one ``HierarchicalReducer.from_spec`` spec per world rank
    (world order), with codec/tuner options riding the INTER sub-spec
    only and distinct trace groups per sub-ring. One builder means
    the spec contract cannot drift between planes."""
    counts = [int(c) for c in node_counts]
    L = len(counts)
    intra_edges = [[intra_edge(i, j) for j in range(k)] if k > 1
                   else None for i, k in enumerate(counts)]
    inter_edges = [inter_edge(i) for i in range(L)]
    specs: List[Dict[str, Any]] = []
    for i, k in enumerate(counts):
        for j in range(k):
            intra = None
            if k > 1:
                intra = {"rank": j, "size": k, "op": op,
                         "timeout_s": timeout_s,
                         "chunk_bytes": chunk_bytes,
                         "group": f"{group}.n{i}", "level": "intra",
                         "to_next": intra_edges[i][j],
                         "from_prev": intra_edges[i][(j - 1) % k]}
            inter = None
            if j == 0:
                inter = {"rank": i, "size": L, "op": op,
                         "timeout_s": timeout_s,
                         "quantize": quantize,
                         "chunk_bytes": chunk_bytes,
                         "group": f"{group}.x", "level": "inter",
                         "tune": tune,
                         "to_next": inter_edges[i],
                         "from_prev": inter_edges[(i - 1) % L]}
            specs.append({"role": "hier", "rank": len(specs),
                          "size": sum(counts), "node": i, "local": j,
                          "nodes": counts, "op": op,
                          "timeout_s": timeout_s,
                          "quantize": quantize, "group": group,
                          "intra": intra, "inter": inter})
    return specs


class HierarchicalReducer:
    """Topology-aware two-level collective group: per-node intra rings
    (shm), one cross-node ring over elected node leaders (TCP), and an
    intra-node broadcast fan-out — the ring-of-rings decomposition of
    "The Big Send-off" (arxiv 2504.18658). Cross-node wire traffic
    drops to ~1/ranks-per-node of the flat ring's: only the leaders'
    node-combined values ride the inter ring, and the existing wire
    codecs (int8 block quantization, bf16 cast) apply on THAT leg only
    — shm legs ship full precision for free.

    Same collective surface as ``RingReducer`` (``reduce`` /
    ``reduce_scatter`` / ``allgather`` / ``round`` / ``seg_bounds`` /
    ``abort`` / ``step`` / ``timeout_s``), so the train plane,
    ``ShardedOptimizer`` and the dag ``_Collective`` use it
    interchangeably. Shard ownership follows ``hier_seg_bounds`` (the
    nested two-level split); results are bitwise identical on every
    rank — the inter ring's owner round-trip plus verbatim broadcast
    forwarding guarantee it whichever codec is active.

    One collective here is: intra reduce-scatter + intra allgather
    (node members combine into the node value, kept flat in the wide
    accumulation dtype), the inter leg over leaders, then an intra
    broadcast of the leader's result. An error in ANY leg — a dead
    leader mid-inter-ring included — is injected into every remaining
    leg as an error frame, so all world ranks surface the same failure
    (with their flight-recorder dumps) instead of stalling."""

    def __init__(self, *, node: int, local: int, node_counts,
                 intra: Optional[RingReducer],
                 inter: Optional[RingReducer],
                 op: str = "sum", timeout_s: float = 600.0,
                 quantize: Optional[str] = None, wire_dtype=None,
                 group: str = ""):
        self.node_counts = [int(c) for c in node_counts]
        self.nnodes = len(self.node_counts)
        if self.nnodes < 2:
            raise ValueError(
                "hierarchical collectives need at least 2 nodes — use "
                "a flat ring for single-node groups")
        self.node, self.local = int(node), int(local)
        self.size = sum(self.node_counts)
        self.rank = sum(self.node_counts[:self.node]) + self.local
        k = self.node_counts[self.node]
        if (intra is None) != (k == 1):
            raise ValueError(
                f"node {node} has {k} member(s): intra ring must be "
                f"{'absent' if k == 1 else 'present'}")
        if (inter is None) != (self.local != 0):
            raise ValueError(
                "exactly the node leaders (local rank 0) carry the "
                "inter ring")
        self.intra, self.inter = intra, inter
        self.op = op
        self.quantize = quantize
        self.wire_dtype = resolve_wire_dtype(wire_dtype)
        self.group = group
        self._timeout_s = float(timeout_s)
        self.timeout_s = self._timeout_s     # fan out to the legs
        self._layout = None
        self._step: Optional[int] = None

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def from_spec(cls, spec: Dict[str, Any],
                  abort=None) -> "HierarchicalReducer":
        """Attach both sub-rings from a controller/compiler-built spec:
        {"kind": "hier", "node", "local", "nodes": [k_0..k_L-1],
        "intra": ring spec | None, "inter": ring spec | None (leaders
        only), "op"?, "timeout_s"?, "quantize"?, "group"?}. The intra
        ring attaches first (consumer-first within each ring, as
        RingReducer.from_spec guarantees); an inter attach failure
        releases the intra channels instead of leaking them."""
        intra = RingReducer.from_spec(spec["intra"], abort=abort) \
            if spec.get("intra") else None
        inter = None
        try:
            inter = RingReducer.from_spec(spec["inter"], abort=abort) \
                if spec.get("inter") else None
        except BaseException:
            if intra is not None:
                intra.close()
            raise
        return cls(node=spec["node"], local=spec["local"],
                   node_counts=spec["nodes"], intra=intra, inter=inter,
                   op=spec.get("op", "sum"),
                   timeout_s=float(spec.get("timeout_s", 600.0)),
                   quantize=spec.get("quantize"),
                   wire_dtype=spec.get("wire_dtype"),
                   group=spec.get("group", ""))

    def _legs(self):
        return [g for g in (self.intra, self.inter) if g is not None]

    def channels(self) -> list:
        return [ch for g in self._legs() for ch in g.channels()]

    def close(self):
        for g in self._legs():
            g.close()

    def abort(self) -> None:
        for g in self._legs():
            g.abort()

    @property
    def step(self) -> Optional[int]:
        return self._step

    @step.setter
    def step(self, v: Optional[int]) -> None:
        self._step = v
        for g in self._legs():
            g.step = v

    @property
    def timeout_s(self) -> float:
        return self._timeout_s

    @timeout_s.setter
    def timeout_s(self, v: float) -> None:
        self._timeout_s = float(v)
        for g in self._legs():
            g.timeout_s = float(v)

    # -- topology ----------------------------------------------------------

    def seg_bounds(self, total: int, seg: Optional[int] = None):
        """(lo, hi) of segment ``seg`` (default: this rank's) under the
        nested two-level split — see ``hier_seg_bounds``."""
        s = self.rank if seg is None else int(seg)
        return hier_seg_bounds(total, self.node_counts, s)

    def _node_base(self, total: int) -> int:
        return total * self.node // self.nnodes

    # -- error relay -------------------------------------------------------

    def _relay_inter(self, err: BaseException) -> None:
        """Inject ``err`` into the inter ring (leaders only): the other
        leaders' in-flight leg resolves to this agreed error in one
        header relay, and their own relays fan it out to their node
        members."""
        if self.inter is None:
            return
        try:
            self.inter.reduce_scatter(_PoisonValue(err))
        except BaseException:  # noqa: BLE001 — original error wins
            pass

    def _relay_bcast(self, err: BaseException) -> None:
        """Inject ``err`` into the intra broadcast this node's members
        are (or will be) blocked in. Leader-only by construction —
        members never hold an error their node leader hasn't seen."""
        if self.intra is None or self.local != 0:
            return
        try:
            self.intra.broadcast(_PoisonValue(err), root=0)
        except BaseException:  # noqa: BLE001 — original error wins
            pass

    # -- collectives -------------------------------------------------------

    def reduce_scatter(self, value, *, op: Optional[str] = None,
                       quantize=_UNSET):
        """Hierarchical reduce-scatter: returns this rank's owned flat
        shard (``seg_bounds(total)`` under the nested split, mean
        already divided). ``quantize`` applies to the cross-node leg
        only. The layout is cached for a following ``allgather``."""
        op = op or self.op
        if op not in ("sum", "mean", "max", "min"):
            raise ValueError(f"unknown op {op!r}")
        q = self.quantize if quantize is _UNSET else quantize
        leg_op = "sum" if op == "mean" else op
        # 0. flatten ONCE: the staged flat vector feeds every leg (the
        #    legs' own flatten of a 1-D contiguous array is zero-copy)
        #    and its metadata feeds the final layout — device leaves
        #    pay exactly one device->host copy per sync. A local
        #    flatten failure enters the legs as a poison value, so it
        #    ships as an error frame every peer agrees on in one
        #    header relay instead of stalling them to the ring timeout.
        leaves = rebuild = None
        try:
            leaves, rebuild, _ = _flatten(value)
            w0 = _wire_dtype([l.dtype for l in leaves], leg_op) \
                if leaves else np.dtype(np.float32)
            entry = np.empty(int(sum(l.size for l in leaves)), w0)
            off = 0
            for l in leaves:
                entry[off:off + l.size] = np.asarray(
                    l, dtype=w0).reshape(-1)
                off += l.size
        except BaseException as e:  # noqa: BLE001 — enters poisoned
            entry = _PoisonValue(e)
        # 1. intra combine: node members reduce into the node value,
        #    kept flat in the wide accumulation dtype (shm; no codec)
        if self.intra is not None:
            try:
                ishard = self.intra.reduce_scatter(entry, op=leg_op)
                node_flat = self.intra.allgather(ishard, rebuild=False)
            except BaseException as e:  # noqa: BLE001 — relay onward
                self._relay_inter(e)
                raise
        else:
            node_flat = entry
        # 2. inter leg (leaders): reduce-scatter node values across
        #    nodes — the only wire leg, and the only codec'd one
        lead = None
        if self.inter is not None:
            try:
                lead = self.inter.reduce_scatter(
                    node_flat, op=leg_op,
                    quantize=q if q is not None else None)
                if op == "mean":
                    # world mean, applied identically on every leader
                    # BEFORE the broadcast so members receive final
                    # bytes (bitwise identity by construction)
                    lead = lead / self.size
            except BaseException as e:  # noqa: BLE001 — relay onward
                self._relay_bcast(e)
                raise
        # 3. intra fan-out of the leader's owned node segment
        if self.intra is not None:
            full_seg = self.intra.broadcast(lead, root=0)
        else:
            full_seg = lead
        # 4. layout + owned slice from the step-0 metadata (a poisoned
        #    entry never reaches here — the legs raised)
        total = int(sum(l.size for l in leaves))
        wide = full_seg.dtype
        self._layout = {
            "rebuild": rebuild, "total": total, "wire": wide,
            "leaves": [(l.shape, l.size,
                        wide if _keeps_wide(l.dtype, op) else l.dtype)
                       for l in leaves]}
        lo, hi = self.seg_bounds(total)
        base = self._node_base(total)
        return np.ascontiguousarray(
            full_seg[lo - base:hi - base]).copy()

    def allgather(self, shard, *, wire_dtype=_UNSET, total_hint=None,
                  rebuild: bool = True):
        """Hierarchical allgather: member shards gather over the intra
        ring into the node segment, leaders allgather node segments
        across the inter ring (``wire_dtype`` codec applies HERE
        only), and the full vector broadcasts back down. Layout-cache
        semantics match ``RingReducer.allgather`` (``total_hint`` pins
        the match, ``rebuild=False`` skips it)."""
        shard = np.ascontiguousarray(np.asarray(shard)).reshape(-1)
        layout = self._layout if rebuild else None
        if layout is not None:
            lo, hi = self.seg_bounds(layout["total"])
            if (layout["total"] != total_hint
                    if total_hint is not None
                    else hi - lo != shard.size):
                layout = None
        wire = layout["wire"] if layout is not None else shard.dtype
        shard = np.ascontiguousarray(shard, dtype=wire)
        wdt = self.wire_dtype if wire_dtype is _UNSET else wire_dtype
        # 1. intra gather: member shards tile the node segment under
        #    the nested split, which IS the intra ring's own split
        if self.intra is not None:
            try:
                node_seg = self.intra.allgather(shard, rebuild=False)
            except BaseException as e:  # noqa: BLE001 — relay onward
                self._relay_inter(e)
                raise
        else:
            node_seg = shard
        # 2. inter leg (leaders): node segments -> full vector
        full = None
        if self.inter is not None:
            try:
                full = self.inter.allgather(
                    node_seg,
                    wire_dtype=wdt if wdt is not None else _UNSET,
                    rebuild=False)
            except BaseException as e:  # noqa: BLE001 — relay onward
                self._relay_bcast(e)
                raise
        # 3. intra fan-out
        if self.intra is not None:
            full = self.intra.broadcast(full, root=0)
        if layout is None or layout["total"] != full.size:
            return full
        return rebuild_from_layout(full, layout)

    def reduce(self, value, *, op: Optional[str] = None,
               quantize=_UNSET, wire_dtype=_UNSET):
        """Fused hierarchical allreduce: the two standalone phases back
        to back (reduce-scatter caches the layout; allgather rebuilds
        the pytree with the flat ring's cast-back policy). ``quantize``
        rides the inter reduce-scatter, ``wire_dtype`` the inter
        allgather — cross-node leg only, results bitwise identical on
        every rank."""
        shard = self.reduce_scatter(value, op=op, quantize=quantize)
        return self.allgather(shard, wire_dtype=wire_dtype,
                              total_hint=self._layout["total"])

    def round(self, kind: int, value, err_frame: Optional[bytes], *,
              op: Optional[str] = None,
              quantize=_UNSET, wire_dtype=_UNSET):
        """Dag-loop entrypoint: (DATA, reduced_value) or (ERROR,
        frame). An error entry (or a local failure) resolves to the
        same agreed error on every world rank via the per-leg error
        relay; a dead neighbor raises RingPeerDead as usual."""
        if kind != DATA and err_frame is None:
            err_frame = dumps_oob(RuntimeError(
                "hier participant entered an error round without a "
                "frame"))
        if err_frame is not None:
            err = loads_oob(err_frame)
            if not isinstance(err, BaseException):
                err = RuntimeError(str(err))
            value = _PoisonValue(err)
        try:
            out = self.reduce(value, op=op, quantize=quantize,
                              wire_dtype=wire_dtype)
            return DATA, out
        except RingPeerDead:
            raise
        except BaseException as e:  # noqa: BLE001 — agreed error
            try:
                frame = dumps_oob(e)
            except Exception:
                frame = dumps_oob(RuntimeError(
                    f"{type(e).__name__}: {e}"))
            return ERROR, frame
