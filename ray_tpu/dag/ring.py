"""Chunked ring allreduce over dag channels: the collective plane's
bandwidth-optimal path.

Replaces the star reduce for N>2 participants (reference for the shape:
NCCL's ring allreduce; the papers behind this PR: The Big Send-off,
arxiv 2504.18658 — chunked, pipelined collectives are what make
large-scale gradient exchange performant — and EQuARX, arxiv 2506.17615
— block-quantized allreduce recovers most of the interconnect bandwidth
with negligible quality loss). Topology: rank r owns one directed edge
to rank (r+1)%N — any mix of ShmRingChannel (same host) and TcpChannel
(cross host) works, the engine only needs write/read_with/slot_bytes.

A round has three phases:

1. **Header relay** (N-1 small frames): every participant sends a header
   carrying its layout signature — or the ERROR frame it entered the
   round with — and forwards whatever it received. After N-1 steps every
   rank holds every header, so an ERROR injected at ANY rank reaches ALL
   ranks in one round (no deadlock, channels stay aligned for the next
   round), and layout mismatches turn into the same deterministic error
   everywhere instead of a garbled reduce.
2. **Reduce-scatter** (N-1 steps): the flattened value is split into N
   segments, segments into chunks of ``chunk_bytes``; at step s rank r
   sends segment (r-s)%N chunk-by-chunk while receiving and accumulating
   segment (r-s-1)%N — the chunk pipelining: chunk k+1 is being copied
   into the ring while the consumer reduces chunk k. Accumulation is
   fused (np.add(src, incoming, out=buf)) and always happens in a
   float32-or-wider wire dtype, so low-precision inputs neither overflow
   nor drift across rounds. Per-participant traffic is O(S), independent
   of N — the star root's O(N*S) ingress+egress is gone.
3. **Allgather** (N-1 steps): each rank broadcasts the segment it now
   owns; received frames are forwarded VERBATIM (quantized payloads are
   not re-quantized hop by hop), so every rank reconstructs bitwise
   identical results — SPMD training state cannot diverge.

Opt-in int8 block quantization (``quantize="int8"``): each chunk ships
as [per-256-element float32 scales | int8 payload] — about 26% of the
fp32 wire bytes. The elementwise error of one quantization event is
bounded by scale/2 = max|block|/254; partial sums are requantized once
per reduce-scatter hop and the final value once, so a round's total
bound is (N*max_scale)/2 — exported per round as the
``allreduce_quant_error`` gauge. Accumulators stay float32/float64, so
the error does not compound across rounds.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.dag.channel import (DATA, ERROR, ChannelClosed, ChannelTimeout,
                                 attach_channel)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob

_UNSET = object()              # "use the constructor default" sentinel
DEFAULT_CHUNK_BYTES = 1 << 20
QUANT_BLOCK = 256           # elements per int8 quantization block
_QUANTIZE_MODES = (None, "int8")


class RingPeerDead(Exception):
    """A ring neighbor stopped responding (peer death / teardown):
    terminal for the group — bounded reads surfaced it within
    timeout_s on every surviving participant."""

    def __init__(self, cause: BaseException):
        super().__init__(str(cause))
        self.cause = cause


class RingProtocolError(Exception):
    """A frame kind the protocol cannot produce arrived mid-phase:
    the channels are desynced beyond repair for this group."""


def allreduce_metrics() -> dict:
    """Get-or-create the collective plane's series (shared process
    registry; worker processes push them to the head via
    util/metrics.push_loop, so the head /metrics serves cluster-wide
    allreduce telemetry like the other PR-2 aggregated series).

      allreduce_round_s      wall time of one full allreduce round
      allreduce_bytes_total  wire bytes this participant wrote
      allreduce_quant_error  elementwise error bound of the last
                             quantized round: (N * max_block_scale) / 2
                             where scale = max|block|/127 (0 when the
                             round was unquantized)
    """
    from ray_tpu.util import metrics as m
    return {
        "round": m.Histogram(
            "allreduce_round_s",
            "Wall time of one collective-plane allreduce round "
            "(header relay + reduce-scatter + allgather)"),
        "bytes": m.Counter(
            "allreduce_bytes_total",
            "Wire bytes written by this participant across allreduce "
            "rounds (headers + chunk frames)"),
        "quant_err": m.Gauge(
            "allreduce_quant_error",
            "Elementwise error bound of the last quantized round over "
            "the quantization events this participant OBSERVED (frames "
            "sent or received): (N*max_scale)/2, scale = "
            "max|block|/127. Exact when gradient magnitudes are "
            "comparable across ranks; partial sums quantized at "
            "non-adjacent hops can exceed it under cross-rank "
            "magnitude skew with cancellation. +inf when a non-finite "
            "gradient was NaN-poisoned through the wire; 0 for "
            "unquantized rounds"),
    }


# --- pytree flatten/unflatten (host plane: no jax import) ----------------


def _flatten(value) -> Tuple[List[np.ndarray], Any, tuple]:
    """(leaves, rebuild, sig): rebuild(iter_of_arrays) reconstructs the
    pytree; sig is a picklable, comparable structure descriptor —
    participants whose sigs differ cannot be reduced together."""
    leaves: List[np.ndarray] = []
    sig: List[tuple] = []

    def walk(v):
        if isinstance(v, dict):
            keys = list(v)
            sig.append(("dict", tuple(str(k) for k in keys)))
            fns = [walk(v[k]) for k in keys]
            t = type(v)

            def rb(it, keys=keys, fns=fns, t=t):
                out = {k: f(it) for k, f in zip(keys, fns)}
                return out if t is dict else t(out)
            return rb
        if isinstance(v, tuple) and hasattr(v, "_fields"):  # NamedTuple
            sig.append(("namedtuple", tuple(v._fields)))
            fns = [walk(x) for x in v]
            t = type(v)

            def rb(it, fns=fns, t=t):
                return t(*(f(it) for f in fns))
            return rb
        if isinstance(v, (list, tuple)):
            sig.append(("seq", type(v).__name__, len(v)))
            fns = [walk(x) for x in v]
            t = type(v)

            def rb(it, fns=fns, t=t):
                return t(f(it) for f in fns)
            return rb
        a = np.asarray(v)
        scalar = not isinstance(v, np.ndarray) and a.ndim == 0
        sig.append(("leaf", a.shape, a.dtype.str))
        leaves.append(a)

        def rb(it, scalar=scalar):
            out = next(it)
            return out.item() if scalar else out
        return rb

    rebuild = walk(value)
    return leaves, rebuild, tuple(sig)


def accumulation_dtype(dt: np.dtype, op: str) -> Optional[np.dtype]:
    """THE low-precision promotion policy, shared by the star's
    per-leaf reduce (runtime._tree_reduce) and the ring's wire dtype
    so the N<=2 fallback and the ring agree numerically. None = reduce
    in the input dtype. sum over sub-64-bit ints accumulates in int64;
    mean over integers accumulates in float64 (and the RESULT stays
    float64, matching numpy's int/len division — means of ints must
    not truncate); sub-32-bit floats (fp16, and bfloat16/fp8 which
    register as kind 'V') accumulate in float32."""
    if op not in ("sum", "mean"):
        return None              # max/min cannot overflow
    if dt.kind in "iub":
        if op == "mean":
            # int64/uint64 divisions already yield float64 stepwise
            return np.dtype(np.float64) if dt.itemsize < 8 else None
        return np.dtype(np.int64) if dt.itemsize < 8 else None
    if dt.kind == "f":
        return np.dtype(np.float32) if dt.itemsize < 4 else None
    if dt.kind == "V":           # ml_dtypes floats
        try:
            if np.finfo(dt).bits < 32:
                return np.dtype(np.float32)
        except ValueError:
            pass
    return None


def _keeps_wide(dt: np.dtype, op: str) -> bool:
    """True when the reduced result stays in the accumulation dtype
    instead of casting back: integer means are float64 results (the
    pre-ring star semantics; casting back would truncate)."""
    return op == "mean" and dt.kind in "iub"


def _wire_dtype(dtypes: List[np.dtype], op: str) -> np.dtype:
    rt = np.result_type(*dtypes) if dtypes else np.dtype(np.float32)
    p = accumulation_dtype(rt, op)
    if p is not None:
        return p
    if rt.kind in "iub":         # 64-bit ints
        return np.dtype(np.float64) if op == "mean" else rt
    if rt.kind in "cf":
        return rt
    try:                          # ml_dtypes floats >= 32 bits
        info = np.finfo(rt)
    except ValueError:
        raise TypeError(f"cannot ring-reduce dtype {rt}")
    return np.dtype(np.float32) if info.bits <= 32 else np.dtype(np.float64)


# --- int8 block quantization (EQuARX-style wire format) ------------------


def _quantize(x: np.ndarray) -> Tuple[bytearray, float]:
    """[nblocks float32 scales | n int8] — returns (frame, max_scale).
    Per-block scale = max|block|/127, so |q| <= 127 without clipping
    and the per-element dequantization error is bounded by scale/2.
    All-zero blocks ship scale 0 (exact). Blocks containing NaN/Inf
    ship scale NaN — dequantization NaN-poisons the whole block, so a
    diverged gradient SURFACES like it would unquantized instead of
    silently becoming finite garbage; max_scale reports +inf."""
    n = x.size
    nb = -(-n // QUANT_BLOCK)
    xb = np.zeros(nb * QUANT_BLOCK, np.float32)
    xb[:n] = x
    xb = xb.reshape(nb, QUANT_BLOCK)
    absmax = xb.__abs__().max(axis=1)
    finite = np.isfinite(absmax)
    div = np.where(finite & (absmax > 0.0), absmax / 127.0,
                   np.float32(1.0)).astype(np.float32)
    q = np.rint(np.where(finite[:, None], xb, np.float32(0.0))
                / div[:, None]).astype(np.int8)
    scales = np.where(finite,
                      np.where(absmax > 0.0, absmax / 127.0,
                               np.float32(0.0)),
                      np.float32(np.nan)).astype(np.float32)
    if not n:
        max_scale = 0.0
    elif finite.all():
        max_scale = float(absmax.max()) / 127.0
    else:
        max_scale = float("inf")
    frame = bytearray(4 * nb + n)
    frame[:4 * nb] = scales.tobytes()
    frame[4 * nb:] = q.reshape(-1)[:n].tobytes()
    return frame, max_scale


def _dequantize(frame, n: int) -> np.ndarray:
    nb = -(-n // QUANT_BLOCK)
    scales = np.frombuffer(frame, np.float32, nb)
    q = np.frombuffer(frame, np.int8, n, offset=4 * nb)
    out = np.zeros(nb * QUANT_BLOCK, np.float32)
    out[:n] = q
    out = out.reshape(nb, QUANT_BLOCK)
    out *= scales[:, None]
    # NaN scales must poison the ENTIRE block (q==0 elements included:
    # 0 * nan is already nan, so the multiply above covers every lane)
    return out.reshape(-1)[:n]


def _scales_max(frame, n: int) -> float:
    """Largest block scale carried by a received quantized frame —
    folded into the error-bound gauge so the bound reflects OTHER
    ranks' quantization events (their gradient magnitudes), not just
    this rank's own."""
    nb = -(-n // QUANT_BLOCK)
    if not nb:
        return 0.0
    m = float(np.frombuffer(frame, np.float32, nb).max())
    return m if np.isfinite(m) else float("inf")


# --- the ring ------------------------------------------------------------


class RingReducer:
    """One participant's endpoint pair in a ring allreduce group. Every
    participant must enter every round (with a value, or with the ERROR
    frame it would have shipped) and all per-round options (op,
    quantize) must match across the group — mismatches are detected in
    the header phase and surface as the same error on every rank."""

    def __init__(self, to_next, from_prev, *, rank: int, size: int,
                 op: str = "sum", timeout_s: float = 600.0,
                 quantize: Optional[str] = None,
                 chunk_bytes: Optional[int] = None):
        if size < 2:
            raise ValueError("ring allreduce needs at least 2 ranks")
        if quantize not in _QUANTIZE_MODES:
            raise ValueError(f"quantize must be one of {_QUANTIZE_MODES}")
        self.to_next = to_next
        self.from_prev = from_prev
        self.rank = int(rank)
        self.size = int(size)
        self.op = op
        self.timeout_s = float(timeout_s)
        self.quantize = quantize
        slot = min(to_next.slot_bytes, from_prev.slot_bytes)
        # floor at 4096 (tiny chunks drown in per-frame overhead) but
        # NEVER exceed the slot — a chunk that can't fit its channel
        # would desync the group mid-phase
        self.chunk_bytes = min(slot, max(
            4096, min(chunk_bytes or DEFAULT_CHUNK_BYTES, slot)))
        self._m = allreduce_metrics()
        self._wrote = 0           # wire bytes this round (batched inc)

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "RingReducer":
        """Attach both ring edges from a controller-built spec:
        {"rank", "size", "to_next", "from_prev", "op"?, "timeout_s"?,
        "quantize"?, "chunk_bytes"?} — channel specs are the same dicts
        the dag compiler produces (shm / lazy-shm / tcp).

        The consumer side attaches FIRST: lazy shm segments are created
        by their consumer, so when every rank attaches concurrently each
        must create its inbound edge before polling for its outbound
        one — the reverse order deadlocks the whole ring at attach.
        Attach waits honor the spec's timeout_s (participants may reach
        their first round arbitrarily skewed — compile, data load), and
        an attach that still times out surfaces as RingPeerDead like
        any other unresponsive-neighbor condition."""
        timeout_s = float(spec.get("timeout_s", 600.0))
        from_prev = None
        try:
            from_prev = attach_channel(spec["from_prev"], "consumer",
                                       timeout=timeout_s)
            to_next = attach_channel(spec["to_next"], "producer",
                                     timeout=timeout_s)
        except (ChannelTimeout, ChannelClosed) as e:
            if from_prev is not None:
                # we created the inbound (consumer-owned) segment;
                # don't leak it when the outbound attach fails
                try:
                    from_prev.close()
                    if getattr(from_prev, "_lazy_owner", False):
                        from_prev.unlink()
                except Exception:
                    pass
            raise RingPeerDead(RuntimeError(
                f"ring allreduce peer never attached within "
                f"{timeout_s}s (participant died before its first "
                f"round?): {e}"))
        return cls(to_next, from_prev,
                   rank=spec["rank"], size=spec["size"],
                   op=spec.get("op", "sum"),
                   timeout_s=timeout_s,
                   quantize=spec.get("quantize"),
                   chunk_bytes=spec.get("chunk_bytes"))

    def channels(self) -> list:
        return [self.to_next, self.from_prev]

    def close(self):
        for ch in self.channels():
            try:
                ch.close()
                if getattr(ch, "_lazy_owner", False):
                    ch.unlink()
            except Exception:  # noqa: BLE001 — teardown
                pass

    # --- wire helpers ---------------------------------------------------

    def _write(self, payload):
        mv = payload if isinstance(payload, memoryview) \
            else memoryview(payload)
        try:
            self.to_next.write(mv, DATA, timeout=self.timeout_s)
        except (ChannelTimeout, ChannelClosed) as e:
            raise RingPeerDead(RuntimeError(
                f"ring allreduce peer (rank {(self.rank + 1) % self.size})"
                f" unresponsive for {self.timeout_s}s "
                f"(participant died?): {e}"))
        self._wrote += mv.nbytes

    def _read_with(self, fn):
        try:
            return self.from_prev.read_with(fn, self.timeout_s)
        except (ChannelTimeout, ChannelClosed) as e:
            raise RingPeerDead(RuntimeError(
                f"ring allreduce peer (rank {(self.rank - 1) % self.size})"
                f" unresponsive for {self.timeout_s}s "
                f"(participant died?): {e}"))

    def _read_bytes(self):
        return self._read_with(lambda k, mv: (k, bytes(mv)))

    # --- phases ---------------------------------------------------------

    def _exchange_headers(self, hdr: dict) -> Dict[int, dict]:
        """N-1 relay steps: send own header, forward what arrives.
        Every rank ends holding every rank's header — the ordered,
        deadlock-free carrier for errors and layout validation."""
        headers = {self.rank: hdr}
        frame = dumps_oob(hdr)
        for _ in range(self.size - 1):
            self._write(frame)
            kind, data = self._read_bytes()
            if kind != DATA:
                raise RingProtocolError(
                    f"unexpected frame kind {kind} in ring header phase")
            got = loads_oob(data)
            headers[got["origin"]] = got
            frame = data
        return headers

    def _chunks(self, lo: int, hi: int, itemsize: int):
        step = max(1, self.chunk_bytes // itemsize)
        return [(p, min(p + step, hi)) for p in range(lo, hi, step)]

    def _send_chunk(self, arr: np.ndarray):
        if self._q == "int8":
            frame, smax = _quantize(arr)
            self._qmax = max(self._qmax, smax)
            self._write(frame)
        else:
            self._write(arr.data.cast("B"))

    def round(self, kind: int, value, err_frame: Optional[bytes], *,
              op: Optional[str] = None,
              quantize=_UNSET) -> Tuple[int, Any]:
        """One collective round. Returns (DATA, reduced_value) or
        (ERROR, frame) — the frame is an already-encoded exception every
        participant agrees on. Raises RingPeerDead when a neighbor stops
        responding (terminal for the group). ``op``/``quantize``
        override the constructor defaults for this round (all ranks
        must pass the same values — validated in the header phase)."""
        op = op or self.op
        if op not in ("sum", "mean", "max", "min"):
            # validate BEFORE any frame moves: a bad op discovered
            # mid-phase would waste a collective round on every rank
            raise ValueError(f"unknown op {op!r}")
        self._q = self.quantize if quantize is _UNSET else quantize
        if self._q not in _QUANTIZE_MODES:
            raise ValueError(f"quantize must be one of {_QUANTIZE_MODES}")
        t0 = time.monotonic()
        self._qmax = 0.0
        self._wrote = 0
        leaves = rebuild = wires = None
        hdr: Dict[str, Any] = {"origin": self.rank}
        if kind != DATA and err_frame is None:
            err_frame = dumps_oob(RuntimeError(
                "ring participant entered an error round without a "
                "frame"))
        if err_frame is None:
            try:
                leaves, rebuild, sig = _flatten(value)
                # PER-LEAF wire dtypes (star-path parity: an int64
                # counter next to float32 grads must neither widen the
                # grads to float64 nor round-trip the counter through
                # a float)
                wires = [_wire_dtype([l.dtype], op) for l in leaves]
                bad = next((w for w in wires if self._q
                            and w.kind != "f"), None)
                if bad is not None:
                    raise TypeError(
                        "int8 block quantization requires floating-"
                        f"point values (wire dtype would be {bad})")
                hdr["sig"] = (sig, tuple(w.str for w in wires), op,
                              self._q)
            except BaseException as e:  # noqa: BLE001 — enters as error
                try:
                    err_frame = dumps_oob(e)
                except Exception:
                    err_frame = dumps_oob(RuntimeError(
                        f"{type(e).__name__}: {e}"))
        if err_frame is not None:
            hdr["err"] = bytes(err_frame)
        try:
            headers = self._exchange_headers(hdr)
            err_origins = sorted(o for o, h in headers.items()
                                 if h.get("err") is not None)
            if err_origins:
                # everyone deterministically agrees on the same frame
                return ERROR, headers[err_origins[0]]["err"]
            sigs = {o: h["sig"] for o, h in headers.items()}
            if len(set(sigs.values())) != 1:
                lines = "; ".join(
                    f"rank {o}: {sigs[o]!r}" for o in sorted(sigs))
                return ERROR, dumps_oob(RuntimeError(
                    "ring allreduce value layouts differ across "
                    f"participants — {lines}"))
            out = self._data_phases(leaves, rebuild, wires, op)
            return DATA, out
        finally:
            self._m["bytes"].inc(self._wrote)
            self._m["quant_err"].set(
                0.5 * self._qmax * self.size if self._q else 0.0)
            self._m["round"].observe(time.monotonic() - t0)

    def reduce(self, value, *, op: Optional[str] = None,
               quantize=_UNSET):
        """Convenience wrapper: reduced value, or raises the group's
        agreed error (the train gradient-sync entrypoint)."""
        kind, out = self.round(DATA, value, None, op=op,
                               quantize=quantize)
        if kind == ERROR:
            err = loads_oob(out)
            raise err if isinstance(err, BaseException) \
                else RuntimeError(str(err))
        return out

    # --- data movement --------------------------------------------------

    def _data_phases(self, leaves, rebuild, wires, op):
        """Group leaves by wire dtype and run reduce-scatter+allgather
        once per group (deterministic first-appearance order, identical
        on every rank since the header phase validated leaf dtypes).
        Homogeneous pytrees — the common case — stay a single pass;
        mixed trees keep per-leaf accumulation exactness (an int64
        leaf never round-trips through float, a float32 leaf never
        pays float64 wire bytes)."""
        order: List[str] = []
        groups: Dict[str, List[int]] = {}
        for i, w in enumerate(wires):
            if w.str not in groups:
                order.append(w.str)
            groups.setdefault(w.str, []).append(i)
        outs: List[Optional[np.ndarray]] = [None] * len(leaves)
        for wstr in order:
            idxs = groups[wstr]
            reduced = self._reduce_group(
                [leaves[i] for i in idxs], np.dtype(wstr), op)
            for i, seg in zip(idxs, reduced):
                if not _keeps_wide(leaves[i].dtype, op):
                    seg = seg.astype(leaves[i].dtype, copy=False)
                outs[i] = seg
        return rebuild(iter(outs))

    def _reduce_group(self, leaves, wire, op) -> List[np.ndarray]:
        """One reduce-scatter + allgather pass over leaves sharing one
        wire dtype; returns the reduced leaves (wire dtype, original
        shapes)."""
        rank, n = self.rank, self.size
        sizes = [l.size for l in leaves]
        total = int(sum(sizes))
        if len(leaves) == 1 and leaves[0].dtype == wire \
                and leaves[0].flags.c_contiguous:
            src = leaves[0].reshape(-1)     # zero-copy fast path
        else:
            src = np.empty(total, wire)
            off = 0
            for l in leaves:
                src[off:off + l.size] = np.asarray(
                    l, dtype=wire).reshape(-1)
                off += l.size
        buf = np.empty(total, wire)         # filled by RS + AG below
        bounds = [(total * i // n, total * (i + 1) // n)
                  for i in range(n)]
        itemsize = wire.itemsize
        fuse = {"sum": np.add, "mean": np.add,
                "max": np.maximum, "min": np.minimum}[op]

        # reduce-scatter: after N-1 steps this rank owns the complete
        # reduction of segment (rank+1)%N in buf
        for s in range(n - 1):
            send_seg = (rank - s) % n
            recv_seg = (rank - s - 1) % n
            frm = src if s == 0 else buf    # step 0 ships pristine input
            send_chunks = self._chunks(*bounds[send_seg], itemsize)
            recv_chunks = self._chunks(*bounds[recv_seg], itemsize)
            for k in range(max(len(send_chunks), len(recv_chunks))):
                if k < len(send_chunks):
                    lo, hi = send_chunks[k]
                    self._send_chunk(frm[lo:hi])
                if k < len(recv_chunks):
                    lo, hi = recv_chunks[k]

                    def apply(kind, mv, lo=lo, hi=hi):
                        if kind != DATA:
                            raise RingProtocolError(
                                f"unexpected frame kind {kind} in ring "
                                f"reduce-scatter")
                        if self._q == "int8":
                            inc = _dequantize(mv, hi - lo)
                            self._qmax = max(self._qmax,
                                             _scales_max(mv, hi - lo))
                        else:
                            inc = np.frombuffer(mv, wire)
                        # fused init+accumulate: buf needs no pre-fill
                        fuse(src[lo:hi], inc, out=buf[lo:hi])
                    self._read_with(apply)

        own = (rank + 1) % n
        own_lo, own_hi = bounds[own]
        if op == "mean":
            buf[own_lo:own_hi] /= n

        # allgather: broadcast the owned segment; received frames are
        # forwarded VERBATIM so quantized payloads are encoded exactly
        # once and every rank reconstructs identical bytes
        outgoing: Optional[List[bytes]] = None
        if self._q == "int8":
            outgoing = []
            for lo, hi in self._chunks(own_lo, own_hi, itemsize):
                frame, smax = _quantize(buf[lo:hi])
                self._qmax = max(self._qmax, smax)
                # the owner applies its own quantization roundtrip so
                # its result matches what everyone else dequantizes
                buf[lo:hi] = _dequantize(frame, hi - lo)
                outgoing.append(bytes(frame))
        for s in range(n - 1):
            send_seg = (rank + 1 - s) % n
            recv_seg = (rank - s) % n
            send_chunks = self._chunks(*bounds[send_seg], itemsize)
            recv_chunks = self._chunks(*bounds[recv_seg], itemsize)
            incoming: List[bytes] = []
            for k in range(max(len(send_chunks), len(recv_chunks))):
                if k < len(send_chunks):
                    if outgoing is not None:
                        self._write(outgoing[k])
                    else:
                        lo, hi = send_chunks[k]
                        self._write(buf[lo:hi].data.cast("B"))
                if k < len(recv_chunks):
                    lo, hi = recv_chunks[k]
                    if outgoing is not None:
                        kind, frame = self._read_bytes()
                        if kind != DATA:
                            raise RingProtocolError(
                                f"unexpected frame kind {kind} in ring "
                                f"allgather")
                        buf[lo:hi] = _dequantize(frame, hi - lo)
                        self._qmax = max(self._qmax,
                                         _scales_max(frame, hi - lo))
                        incoming.append(frame)
                    else:
                        def apply(kind, mv, lo=lo, hi=hi):
                            if kind != DATA:
                                raise RingProtocolError(
                                    f"unexpected frame kind {kind} in "
                                    f"ring allgather")
                            buf[lo:hi] = np.frombuffer(mv, wire)
                        self._read_with(apply)
            if outgoing is not None:
                outgoing = incoming

        # split back into per-leaf views of buf (cast-back to input
        # dtype happens in _data_phases, which knows the leaf policy)
        outs = []
        off = 0
        for l in leaves:
            outs.append(buf[off:off + l.size].reshape(l.shape))
            off += l.size
        return outs
