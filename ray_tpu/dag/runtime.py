"""Worker-side pinned execution loop for compiled DAGs.

The analog of the reference's compiled-graph executor schedule (reference:
python/ray/dag/dag_node_operation.py:86 — each actor's node is compiled
into READ/COMPUTE/WRITE operations that overlap channel I/O with compute;
compiled_dag_node.py:805 _execute_until): each pinned actor runs an
operation schedule per item — a reader thread prefetches the NEXT item's
inputs (TCP receives hide under compute), the executor thread runs the
bound method, participates in any collective, and pushes downstream.
Per-item recv/compute windows are recorded (trace spans + a timing block
in the loop result) so overlap is measurable, not asserted.

jax.Array results are staged to host (np.asarray) before entering the
channel — the seed of the tensor-transport path (reference:
experimental/rdt/tensor_transport_manager.py:37); device-to-device over
ICI belongs to jit'd collectives, not the object plane.
"""

from __future__ import annotations

import os
import queue as _queue
import sys
import threading
import time
from typing import List, Optional

import numpy as np

from ray_tpu.dag.channel import (DATA, ERROR, STOP, ChannelClosed,
                                 ChannelTimeout, ShmRingChannel,
                                 attach_channel)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob, serialize
from ray_tpu.util import events

_MAX_TIMED_ITEMS = 512   # per-item windows kept for overlap analysis


_JAX_ARRAY_T = None     # cached jax.Array type (import hoisted: resolved
                        # once per process instead of per staged value)


def _stage_to_host(value):
    """jax.Array leaves — bare or inside dict/list/tuple/NamedTuple
    results — are host-staged into the channel; a method that returns
    TensorRefs (runtime/device_store.py put_device) opts into the
    device transport instead — only the small handle rides the channel
    and the tensor moves on first resolution (zero-copy within a
    process)."""
    global _JAX_ARRAY_T
    if _JAX_ARRAY_T is None:
        if "jax" not in sys.modules:
            return value     # no jax in-process: nothing to stage
        import jax
        _JAX_ARRAY_T = jax.Array
    return _stage_tree(value)


def _stage_tree(value):
    if isinstance(value, _JAX_ARRAY_T):
        return np.asarray(value)
    if isinstance(value, dict):
        staged = {k: _stage_tree(v) for k, v in value.items()}
        if any(staged[k] is not value[k] for k in staged):
            if type(value) is dict:
                return staged
            try:
                return type(value)(staged)
            except TypeError:    # subclass ctor isn't mapping-shaped
                return value     # (defaultdict etc.): leave unstaged
        return value
    if isinstance(value, (list, tuple)):
        staged = [_stage_tree(v) for v in value]
        if any(s is not v for s, v in zip(staged, value)):
            try:
                if isinstance(value, tuple) and hasattr(value, "_fields"):
                    return type(value)(*staged)     # NamedTuple
                return type(value)(staged)
            except TypeError:
                return value     # exotic sequence ctor: leave unstaged
        return value
    return value


class _Stop(Exception):
    pass


class _Upstream(Exception):
    """An ERROR frame arrived; carry it downstream unchanged."""

    def __init__(self, frame: bytes):
        self.frame = frame


class _ReaderDead(Exception):
    """The prefetch reader hit a channel error (peer death/teardown):
    terminal for the loop — nobody will produce another round."""

    def __init__(self, cause: BaseException):
        self.cause = cause


# --- collective (host-plane star reduce) --------------------------------


def _tree_reduce(op: str, vals: list):
    """Elementwise reduce over matching pytrees of arrays/scalars. Host
    plane: numpy, no jax import (reference lowers collective nodes to
    NCCL allreduce, dag/collective_node.py:252; within one process
    holding a mesh, jit'd psum over ICI is the right tool instead).
    Low-precision leaves accumulate wide (the policy shared with the
    ring path: dag/ring.py accumulation_dtype) and cast back to the
    input dtype at the end — except integer means, which stay float64
    like a stepwise numpy division would."""
    from ray_tpu.dag.ring import _keeps_wide, accumulation_dtype
    v0 = vals[0]
    if isinstance(v0, dict):
        return {k: _tree_reduce(op, [v[k] for v in vals]) for k in v0}
    if isinstance(v0, tuple) and hasattr(v0, "_fields"):   # NamedTuple
        return type(v0)(*(
            _tree_reduce(op, [v[i] for v in vals])
            for i in range(len(v0))))
    if isinstance(v0, (list, tuple)):
        return type(v0)(
            _tree_reduce(op, [v[i] for v in vals])
            for i in range(len(v0)))
    arrs = [np.asarray(v) for v in vals]
    acc = accumulation_dtype(arrs[0].dtype, op)
    out = arrs[0] if acc is None else arrs[0].astype(acc)
    for a in arrs[1:]:
        if op in ("sum", "mean"):
            out = out + (a if acc is None else a.astype(acc))
        elif op == "max":
            out = np.maximum(out, a)
        else:
            out = np.minimum(out, a)
    if op == "mean":
        out = out / len(arrs)
    if acc is not None and not _keeps_wide(arrs[0].dtype, op):
        out = out.astype(arrs[0].dtype)
    return out


class _Collective:
    """One participant's view of a dag allreduce group. Every data round
    EVERY participant enters the round (with its value, or with the
    ERROR frame it would have shipped) — peers must never be left
    blocking in a reduce because one participant failed. Reads are
    bounded by `timeout_s` (shm rings carry no peer-death signal): a
    dead/killed peer surfaces as a terminal stall instead of pinning
    this actor's executor thread forever.

    Two wire topologies share these semantics: the chunked ring
    (role "ring", N>2 and all quantized groups — per-participant
    bandwidth O(S), see dag/ring.py) and the star (roles "root"/"leaf",
    the N<=2 fallback — root ingress+egress O(N*S)).

    Ring specs may carry ``trace_level`` ("off"/"round"/"chunk") and
    ``group`` (a lane label): collective spans + the flight recorder
    (dag/ring.py _RingTrace) ride through unchanged, and a ring that
    dies mid-round stitches its flight-dump path into the cause that
    _ReaderDead ships downstream."""

    def __init__(self, spec: dict):
        self.role = spec["role"]
        self.op = spec["op"]
        self.timeout_s = float(spec.get("timeout_s", 600.0))
        self._ring = None
        if self.role == "ring":
            from ray_tpu.dag.ring import RingReducer
            self._ring = RingReducer.from_spec(spec)
        elif self.role == "hier":
            # ring-of-rings: same collective surface as the flat ring
            # (round / reduce_scatter / allgather), so every path
            # below treats it identically
            from ray_tpu.dag.ring import HierarchicalReducer
            self._ring = HierarchicalReducer.from_spec(spec)
        elif self.role == "root":
            self.up = [attach_channel(s, "consumer") for s in spec["up"]]
            self.down = [attach_channel(s, "producer")
                         for s in spec["down"]]
        else:
            self.up = [attach_channel(spec["up"], "producer")]
            self.down = [attach_channel(spec["down"], "consumer")]

    def channels(self) -> list:
        if self._ring is not None:
            return self._ring.channels()
        return self.up + self.down

    def _require_ring(self, what: str):
        if self._ring is None:
            raise RuntimeError(
                f"{what} needs a ring collective group (role "
                f"{self.role!r} is the N<=2 star topology — compile "
                f"the group with impl='ring' or grow it past 2 "
                f"participants)")
        return self._ring

    def reduce_scatter(self, value, *, op: Optional[str] = None,
                       quantize=None):
        """Standalone reduce-scatter over the group's ring: returns
        this rank's owned flat shard of the elementwise reduction (see
        dag/ring.py RingReducer.reduce_scatter — the ZeRO-1 gradient
        sync). Raises the group's agreed error; a dead neighbor
        surfaces as _ReaderDead like any other collective stall."""
        from ray_tpu.dag.ring import RingPeerDead, _UNSET
        ring = self._require_ring("reduce_scatter")
        try:
            return ring.reduce_scatter(
                value, op=op,
                quantize=_UNSET if quantize is None else quantize)
        except RingPeerDead as e:
            raise _ReaderDead(e.cause)

    def allgather(self, shard, *, wire_dtype=None,
                  total_hint: Optional[int] = None,
                  rebuild: bool = True):
        """Standalone allgather over the group's ring: every rank
        contributes its owned flat shard, every rank receives the
        reassembled value (the cached reduce_scatter pytree layout when
        one matches — pin the match with ``total_hint``, or skip the
        cache entirely with ``rebuild=False`` — else the flat vector).
        ``wire_dtype="bfloat16"`` halves the wire bytes (see
        RingReducer.allgather)."""
        from ray_tpu.dag.ring import RingPeerDead, _UNSET
        ring = self._require_ring("allgather")
        try:
            return ring.allgather(
                shard,
                wire_dtype=_UNSET if wire_dtype is None else wire_dtype,
                total_hint=total_hint, rebuild=rebuild)
        except RingPeerDead as e:
            raise _ReaderDead(e.cause)

    def round(self, kind: int, value, err_frame: Optional[bytes]):
        """Returns (DATA, reduced_frame) or (ERROR, frame). The reduced
        value travels onward as the already-encoded frame — participants
        forward it downstream without a second serialize/deserialize."""
        from ray_tpu.dag.channel import ChannelClosed, ChannelTimeout
        if self._ring is not None:
            from ray_tpu.dag.ring import RingPeerDead
            try:
                k, out = self._ring.round(kind, value, err_frame)
            except RingPeerDead as e:
                raise _ReaderDead(e.cause)
            if k == ERROR:
                return (ERROR, out)
            return (DATA, serialize(out))
        try:
            if self.role == "leaf":
                if kind == DATA:
                    self.up[0].write(serialize(value), DATA,
                                     timeout=self.timeout_s)
                else:
                    self.up[0].write(err_frame, ERROR,
                                     timeout=self.timeout_s)
                return self.down[0].read_bytes(self.timeout_s)
        except (ChannelTimeout, ChannelClosed) as e:
            raise _ReaderDead(RuntimeError(
                f"allreduce peer unresponsive for {self.timeout_s}s "
                f"(participant died?): {e}"))
        # root: gather every leaf's contribution, reduce, broadcast
        contribs = []
        err = err_frame if kind == ERROR else None
        for ch in self.up:
            try:
                k, p = ch.read_bytes(self.timeout_s)
            except (ChannelTimeout, ChannelClosed) as e:
                raise _ReaderDead(RuntimeError(
                    f"allreduce peer unresponsive for {self.timeout_s}s "
                    f"(participant died?): {e}"))
            if k == ERROR:
                err = err or p
            else:
                contribs.append(loads_oob(p))
        if err is not None:
            for ch in self.down:
                ch.write(err, ERROR)
            return (ERROR, err)
        try:
            red = _tree_reduce(self.op, [value] + contribs)
            ser = serialize(red)
        except BaseException as e:  # noqa: BLE001 — reduce failed
            # e.g. mismatched pytree keys: the leaves are all parked on
            # their down channels — broadcast the failure so they raise
            # it this round instead of blocking for collective timeout_s
            # with the group desynced
            try:
                frame = dumps_oob(e)
            except Exception:   # unpicklable exception payload
                frame = dumps_oob(RuntimeError(
                    f"{type(e).__name__}: {e}"))
            for ch in self.down:
                ch.write(frame, ERROR)
            return (ERROR, frame)
        for ch in self.down:
            ch.write(ser, DATA)
        return (DATA, ser)


# --- the loop -----------------------------------------------------------


def exec_loop(instance, spec: dict) -> dict:
    """Runs inside the actor's executor thread until a STOP frame.

    spec:
      method: attribute name on the actor instance
      in_channels: list of channel specs (one per bound upstream arg)
      arg_template: list where each element is ("chan", idx) or
        ("const", frame) — positional args in order
      out_channels: list of channel specs (broadcast to every consumer)
      overlap: prefetch next item's inputs on a reader thread
      collective: optional allreduce role spec (see _Collective)
    """
    method = getattr(instance, spec["method"])
    # shm rings attach by name (same host); tcp edges bind/connect per
    # role — this stage CONSUMES its in-edges, PRODUCES its out-edges
    ins: List[ShmRingChannel] = [
        attach_channel(s, "consumer") for s in spec["in_channels"]]
    outs: List[ShmRingChannel] = [
        attach_channel(s, "producer") for s in spec["out_channels"]]
    coll = _Collective(spec["collective"]) if spec.get("collective") \
        else None
    template = [loads_oob(frame) if k == "const" else None
                for k, frame in spec["arg_template"]]
    chan_pos = [i for i, (k, _) in enumerate(spec["arg_template"])
                if k == "chan"]
    # Zero-copy is opt-in (compile(zero_copy=True)): args alias the ring
    # slot, which is only safe when the method does not retain them —
    # and incompatible with both prefetch (the window would escape) and
    # collectives (the value must outlive the slot for the reduce).
    single = len(ins) == 1 and spec.get("zero_copy") and coll is None
    overlap = bool(spec.get("overlap")) and not single and ins

    from ray_tpu.util import tracing
    items: List[dict] = []          # first N per-item timing windows
    # recv windows span WAIT + transfer (channels expose no first-byte
    # mark): overlapped_recv_s is the receive-side blocking hidden under
    # compute — the overlap the schedule creates — not pure wire time;
    # an upstream-starved stage shows long recv spans by design.
    stats = {"recv_s": 0.0, "compute_s": 0.0, "overlapped_recv_s": 0.0}

    def _run_in_window(kind, mv):
        """Zero-copy fast path: the method consumes the frame AND the
        result is serialized downstream INSIDE the slot window, so
        deserialization is zero-copy (arrays alias the ring slot —
        even a method returning a view of its input stays safe, since
        the slot is released only after the downstream copy)."""
        if kind != DATA:
            raise _Stop() if kind == STOP else _Upstream(bytes(mv))
        args = list(template)
        args[chan_pos[0]] = loads_oob(mv)
        ser = serialize(_stage_to_host(method(*args)))
        for out in outs:
            out.write(ser, DATA)

    # --- overlapped reader: prefetches whole input rounds ---------------
    rounds_q: Optional[_queue.Queue] = None
    reader: Optional[threading.Thread] = None
    if overlap:
        rounds_q = _queue.Queue(maxsize=2)

        def _read_rounds():
            while True:
                t0 = time.time()
                frames = []
                for ch in ins:
                    try:
                        frames.append(ch.read_bytes())
                    except BaseException as e:  # noqa: BLE001
                        rounds_q.put(("fail", e, (t0, time.time())))
                        return
                rounds_q.put(("round", frames, (t0, time.time())))
                if any(k == STOP for k, _ in frames):
                    return   # lockstep: STOP reaches every edge together

        reader = threading.Thread(target=_read_rounds, daemon=True,
                                  name="dag-prefetch")
        reader.start()

    def _next_round():
        """One input round: [(kind, payload)] per in-channel + the recv
        window. Raises what a direct read would raise."""
        if overlap:
            tag, payload, win = rounds_q.get()
            if tag == "fail":
                raise _ReaderDead(payload)
            return payload, win
        t0 = time.time()
        try:
            frames = [ch.read_bytes() for ch in ins]
        except BaseException as e:  # channel death: terminal, like the
            raise _ReaderDead(e)    # prefetch reader's fail path
        return frames, (t0, time.time())

    processed = 0
    compute_until = 0.0             # wall time the last compute ended
    try:
        while True:
            try:
                if single:
                    try:
                        ins[0].read_with(_run_in_window)
                    except ChannelClosed as e:
                        raise _ReaderDead(e)   # peer died: terminal
                    processed += 1
                    continue
                frames, (r0, r1) = _next_round()
                stats["recv_s"] += r1 - r0
                if compute_until > r0:
                    # the part of this receive that hid under the
                    # previous item's compute — the overlap win itself
                    stats["overlapped_recv_s"] += \
                        min(r1, compute_until) - r0
                if any(k == STOP for k, _ in frames):
                    raise _Stop()
                err_frame = next(
                    (p for k, p in frames if k == ERROR), None)
                value = None
                c0 = c1 = r1
                if err_frame is None:
                    args = list(template)
                    for pos, (_, payload) in zip(chan_pos, frames):
                        args[pos] = loads_oob(payload)
                    c0 = time.time()
                    try:
                        value = _stage_to_host(method(*args))
                    except BaseException as e:  # noqa: BLE001
                        try:
                            err_frame = dumps_oob(e)
                        except Exception:   # unpicklable payload
                            err_frame = dumps_oob(RuntimeError(
                                f"{type(e).__name__}: {e}"))
                    c1 = time.time()
                    stats["compute_s"] += c1 - c0
                    compute_until = c1
                out_frame = None      # pre-encoded downstream payload
                if coll is not None:
                    kind = ERROR if err_frame is not None else DATA
                    kind, frame = coll.round(kind, value, err_frame)
                    if kind == ERROR:
                        err_frame = frame
                    else:
                        out_frame, err_frame = frame, None
                if len(items) < _MAX_TIMED_ITEMS:
                    items.append({"recv": (r0, r1), "compute": (c0, c1)})
                # no enabled() pre-check: record_exec gates itself, and
                # the task-events flag must reach dag rows even when
                # span tracing is off (state.list_tasks)
                tracing.record_exec("", "dag",
                                    f"{spec['method']}:recv", r0, r1)
                tracing.record_exec("", "dag",
                                    f"{spec['method']}", c0, c1,
                                    error=err_frame is not None)
                if err_frame is not None:
                    for out in outs:
                        out.write(err_frame, ERROR)
                else:
                    ser = out_frame if out_frame is not None \
                        else serialize(value)
                    for out in outs:
                        out.write(ser, DATA)
                    processed += 1
            except _Stop:
                for out in outs:
                    out.write(b"", STOP)
                break
            except _ReaderDead as e:
                # TERMINAL: the reader exited, no further round will
                # arrive — resuming the loop would block on an empty
                # queue forever and pin the executor thread through
                # teardown. Ship the error and leave.
                try:
                    frame = dumps_oob(e.cause)
                except Exception:
                    frame = dumps_oob(RuntimeError(
                        f"{type(e.cause).__name__}: {e.cause}"))
                for out in outs:
                    try:
                        out.write(frame, ERROR, timeout=5.0)
                        # STOP too: downstream stages must terminate —
                        # shm rings carry no peer-death signal, so an
                        # un-terminated consumer would block forever.
                        out.write(b"", STOP, timeout=5.0)
                    except Exception:  # noqa: BLE001 — tearing down
                        pass
                break
            except _Upstream as e:   # zero-copy path only
                for out in outs:
                    out.write(e.frame, ERROR)
            except BaseException as e:  # noqa: BLE001 — ship downstream
                try:
                    frame = dumps_oob(e)
                except Exception:  # unpicklable exception payload
                    frame = dumps_oob(RuntimeError(
                        f"{type(e).__name__}: {e}"))
                for out in outs:
                    out.write(frame, ERROR)
    finally:
        coll_chans = coll.channels() if coll is not None else []
        for ch in ins + outs + coll_chans:
            ch.close()
            if getattr(ch, "_lazy_owner", False):
                ch.unlink()   # consumer created this same-node segment
    return {"method": spec["method"], "processed": processed,
            "timing": stats, "items": items}


# --- pipeline-parallel stage loop ----------------------------------------
#
# The MPMD sibling of exec_loop (reference: arxiv 2412.14374 — per-stage
# compiled programs driven by a microbatch schedule): instead of one
# method applied per streamed item, the actor executes a COMPILED OP
# SCHEDULE per training step (train/pipeline.py compile_schedule —
# GPipe fill/drain or 1F1B), alternating forward receives from the
# previous stage and backward-gradient receives from the next one over
# the same placement-aware channels. The prefetch reader walks the
# identical schedule one window ahead, so stage p's recv of microbatch
# i+1 hides under its compute of microbatch i — the same overlap window
# exec_loop gives streamed items, measured the same way.


class _UnwalkableTree(TypeError):
    """A container whose ctor isn't shape-compatible (defaultdict, a
    NamedTuple with a custom __new__, ...) sits in the tree — strict
    walkers raise this so EFFECTFUL mappings can undo their side
    effects instead of silently dropping a mapped subtree."""


def _map_tree_leaves(fn, value, strict: bool = False):
    """ONE container walk (dict / NamedTuple / list-tuple) shared by
    the device-transport helpers below — the same shapes _stage_tree
    handles, with the same exotic-constructor guard: non-strict
    walkers pass an unmappable container through unmapped (the
    _stage_tree behavior); strict walkers raise _UnwalkableTree.
    (_stage_tree deliberately keeps its own walk: it preserves
    container IDENTITY when no leaf changed — a no-copy optimization
    the always-rebuilding mappers here don't want to inherit.)"""
    def bail(v):
        if strict:
            raise _UnwalkableTree(type(v).__name__)
        return v
    if isinstance(value, dict):
        out = {k: _map_tree_leaves(fn, v, strict)
               for k, v in value.items()}
        if type(value) is dict:
            return out
        try:
            return type(value)(out)
        except _UnwalkableTree:
            raise
        except TypeError:       # defaultdict etc.
            return bail(value)
    if isinstance(value, tuple) and hasattr(value, "_fields"):
        try:
            return type(value)(*(_map_tree_leaves(fn, v, strict)
                                 for v in value))
        except _UnwalkableTree:
            raise
        except TypeError:       # NamedTuple-like with custom __new__
            return bail(value)
    if isinstance(value, (list, tuple)):
        try:
            return type(value)(_map_tree_leaves(fn, v, strict)
                               for v in value)
        except _UnwalkableTree:
            raise
        except TypeError:       # exotic sequence ctor
            return bail(value)
    return fn(value)


def _ship_device_tree(value, ttl_s: Optional[float]):
    """jax.Array leaves -> parked TensorRefs (runtime/device_store.py):
    only the handle rides the channel; the tensor moves at most once,
    on the consumer's resolve. Returns (wrapped, tensor_bytes). An
    unwalkable container anywhere in the tree falls the WHOLE payload
    back to host staging, freeing any already-parked refs — a partial
    ship would strand parked tensors with no consumer to free them."""
    import numpy as np

    from ray_tpu.runtime.device_store import _store
    global _JAX_ARRAY_T
    if _JAX_ARRAY_T is None:
        if "jax" not in sys.modules:
            return value, 0
        import jax
        _JAX_ARRAY_T = jax.Array
    nbytes = [0]
    shipped: list = []

    def ship(v):
        if isinstance(v, _JAX_ARRAY_T):
            ref = _store().put(v, ttl_s=ttl_s)
            shipped.append(ref)
            nbytes[0] += int(np.dtype(ref.dtype).itemsize
                             * int(np.prod(ref.shape or (1,))))
            return ref
        return v
    try:
        return _map_tree_leaves(ship, value, strict=True), nbytes[0]
    except _UnwalkableTree:
        for ref in shipped:
            ref.free()
        return value, 0


def _resolve_device_tree(value):
    """TensorRef leaves -> materialized arrays, freeing each ref the
    moment it resolves: the schedule owns activation lifetime, so
    steady-state device/store memory is O(in-flight microbatches) —
    never O(steps) (tested via device_store accounting)."""
    from ray_tpu.runtime.device_store import TensorRef

    def resolve(v):
        if isinstance(v, TensorRef):
            try:
                return v.resolve()
            finally:
                v.free()
        return v
    return _map_tree_leaves(resolve, value)


class _PipeFlight:
    """Flight recorder for one stage loop: the last K op timing records,
    dumped to JSON on a terminal channel death so the raised
    PeerLostError names a post-mortem file — the ring flight-recorder
    contract (dag/ring.py _RingTrace) for the pipeline plane."""

    def __init__(self, stage: int, chain: int, group: str, keep: int = 64):
        import collections
        self.stage, self.chain, self.group = stage, chain, group
        self.ops = collections.deque(maxlen=keep)
        self.path: Optional[str] = None

    def add(self, **rec) -> None:
        self.ops.append(rec)

    def dump(self, err: BaseException) -> Optional[str]:
        import json
        import os
        import tempfile
        try:
            from ray_tpu.config import get_config
            d = getattr(get_config(), "collective_flight_dir", "") or \
                os.path.join(tempfile.gettempdir(), "ray_tpu_flight")
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"pipe-{self.group}-s{self.stage}-c{self.chain}-"
                   f"{os.getpid()}-{int(time.time() * 1000)}.json")
            with open(path, "w") as f:
                json.dump({"error": repr(err), "stage": self.stage,
                           "chain": self.chain, "group": self.group,
                           "ts": time.time(),
                           "ops": list(self.ops)}, f, default=str)
            self.path = path
            return path
        except Exception:   # noqa: BLE001 — post-mortem must not mask
            return None


def _pipe_peer_lost(cause: BaseException, flight: _PipeFlight):
    """Terminal channel death -> the typed error elastic train_fns
    catch (train.PeerLostError), flight-dump path stitched in like the
    ring plane does."""
    from ray_tpu.train.collective import PeerLostError
    path = flight.dump(cause)
    note = f" [collective flight recorder: {path}]" if path else ""
    err = PeerLostError(
        f"pipeline stage {flight.stage} lost a channel peer "
        f"(stage actor died mid-schedule?): {cause}{note}")
    err.flight_recorder_path = path
    return err


def pipe_exec_loop(instance, spec: dict) -> dict:
    """Pinned pipeline-stage loop: runs one op schedule per step until
    a STOP frame arrives at a step boundary.

    spec (built by train/pipeline.py build_pipe_specs):
      stage/num_stages/chain: this actor's position
      schedule: ordered [kind, mb] op list for ONE step
      fwd_in/fwd_out/bwd_in/bwd_out: channel specs (None at the ends)
      res_out: per-step report channel back to the driver
      zero_spec: per-stage ZeRO ring (handed to pipe_configure)
      device: ship activations/gradients as TensorRefs
      ttl_s: activation-ref TTL backstop (leak bound for dead consumers)
      group/step_base/timeout_s: trace tags + recv bound
    """
    from ray_tpu.util import tracing
    stage = int(spec["stage"])
    chain = int(spec.get("chain", 0))
    group = str(spec.get("group", ""))[:12]
    timeout_s = float(spec.get("timeout_s", 300.0))
    sched = [tuple(op) for op in spec["schedule"]]
    device = bool(spec.get("device"))
    ttl_s = spec.get("ttl_s")
    step_base = int(spec.get("step_base", 0))
    fwd_in = attach_channel(spec["fwd_in"], "consumer") \
        if spec.get("fwd_in") else None
    fwd_out = attach_channel(spec["fwd_out"], "producer") \
        if spec.get("fwd_out") else None
    bwd_in = attach_channel(spec["bwd_in"], "consumer") \
        if spec.get("bwd_in") else None
    bwd_out = attach_channel(spec["bwd_out"], "producer") \
        if spec.get("bwd_out") else None
    res_out = attach_channel(spec["res_out"], "producer")
    chans = [c for c in (fwd_in, fwd_out, bwd_in, bwd_out, res_out)
             if c is not None]
    cfg = getattr(instance, "pipe_configure", None)
    if cfg is not None:
        cfg(spec)
    flight = _PipeFlight(stage, chain, group)
    try:
        from ray_tpu.train.pipeline import pipeline_metrics
        metrics = pipeline_metrics()
    except Exception:   # noqa: BLE001 — metrics must never break the loop
        metrics = None

    def recv_chan(kind: str):
        return fwd_in if kind == "F" else bwd_in

    def send_chan(kind: str):
        return fwd_out if kind == "F" else bwd_out

    recv_ops = [(j, op) for j, op in enumerate(sched)
                if recv_chan(op[0]) is not None]

    # -- prefetch reader: walks the same schedule one window ahead -------
    rounds_q: _queue.Queue = _queue.Queue(maxsize=2)
    done_evt = threading.Event()        # loop exiting: reader must too

    def _qput(item) -> bool:
        """Bounded put that can never strand the reader: once the
        executor has exited (done_evt), the frame is dropped instead
        of blocking forever on a full queue — a failed run must not
        leak the reader thread for the worker's lifetime."""
        while not done_evt.is_set():
            try:
                rounds_q.put(item, timeout=0.2)
                return True
            except _queue.Full:
                continue
        return False

    def _read_schedule():
        while not done_evt.is_set():
            for n, (j, (kind, mb, *_v)) in enumerate(recv_ops):
                t0 = time.time()
                while True:
                    try:
                        # the STEP BOUNDARY recv (n == 0) arrives at
                        # the driver's cadence (eval/checkpoint pauses
                        # between steps are healthy) — park in short
                        # slices, resetting the recv window each
                        # slice so driver idle doesn't masquerade as
                        # transfer time in recv_s/overlap stats.
                        # timeout_s bounds MID-step waits only; a
                        # peer dead at a boundary is detected by the
                        # driver's report read and unwound by
                        # STOP/teardown.
                        frame = recv_chan(kind).read_bytes(
                            min(1.0, timeout_s) if n == 0
                            else timeout_s)
                        break
                    except ChannelTimeout as e:
                        if n == 0 and not done_evt.is_set():
                            t0 = time.time()
                            continue
                        _qput(("fail", e, (t0, time.time())))
                        return
                    except BaseException as e:  # noqa: BLE001
                        _qput(("fail", e, (t0, time.time())))
                        return
                if not _qput((j, frame, (t0, time.time()))):
                    return
                if frame[0] == STOP:
                    return

    reader = threading.Thread(target=_read_schedule, daemon=True,
                              name=f"pipe-prefetch-s{stage}")
    reader.start()

    def _broadcast(frame: bytes, kind: int) -> None:
        for out in (fwd_out, bwd_out, res_out):
            if out is None:
                continue
            try:
                out.write(frame, kind, timeout=5.0)
            except Exception:   # noqa: BLE001 — tearing down
                pass

    def _terminal(err: BaseException) -> None:
        """Ship the failure everywhere a peer could be parked, then
        STOP every edge so downstream/upstream loops terminate (shm
        rings carry no peer-death signal)."""
        try:
            frame = dumps_oob(err)
        except Exception:   # noqa: BLE001 — unpicklable payload
            frame = dumps_oob(RuntimeError(f"{type(err).__name__}: {err}"))
        _broadcast(frame, ERROR)
        _broadcast(b"", STOP)

    stats = {"recv_s": 0.0, "compute_s": 0.0, "overlapped_recv_s": 0.0,
             "bubble_s": 0.0, "steps": 0}
    first_recv_j = recv_ops[0][0] if recv_ops else None
    step_no = 0
    # persists ACROSS steps (the exec_loop pattern): a frame the
    # reader prefetched during the previous step's compute tail must
    # still earn its overlapped_recv_s credit when consumed early in
    # the next step
    compute_until = 0.0
    try:
        while True:     # one iteration == one schedule step
            step_tag = step_base + step_no
            step_t0 = None
            bubble = 0.0
            recv0 = stats["recv_s"]
            ov0 = stats["overlapped_recv_s"]
            comp0 = stats["compute_s"]
            try:
                for j, op in enumerate(sched):
                    kind, mb = op[0], int(op[1])
                    payload = None
                    wait_s = 0.0
                    if recv_chan(kind) is not None:
                        q0 = time.time()
                        tag = rounds_q.get()
                        q1 = time.time()
                        if tag[0] == "fail":
                            raise _ReaderDead(tag[1])
                        rj, (fkind, fpayload), (r0, r1) = tag
                        if fkind == STOP:
                            raise _Stop()
                        if fkind == ERROR:
                            raise _Upstream(bytes(fpayload))
                        if rj != j:
                            raise RuntimeError(
                                f"pipeline schedule desync at stage "
                                f"{stage}: expected op {j}, reader "
                                f"delivered {rj}")
                        wait_s = q1 - q0
                        # bubble counts IN-step stalls only: the wait
                        # for the step's FIRST payload is driver
                        # cadence + fill (and in steady state the
                        # prefetch reader hides it under the previous
                        # step's tail), and the step window below
                        # opens after it — numerator and denominator
                        # cover the same window, so the fraction is
                        # always <= 1
                        if j != first_recv_j:
                            bubble += wait_s
                        stats["recv_s"] += r1 - r0
                        if compute_until > r0:
                            stats["overlapped_recv_s"] += \
                                min(r1, compute_until) - r0
                        payload = loads_oob(fpayload)
                        if device:
                            payload = _resolve_device_tree(payload)
                    if step_t0 is None:
                        step_t0 = time.time()
                    c0 = time.time()
                    if kind == "F":
                        out_val = instance.pipe_forward(mb, payload)
                    else:
                        out_val = instance.pipe_backward(mb, payload)
                    c1 = time.time()
                    stats["compute_s"] += c1 - c0
                    compute_until = c1
                    out_ch = send_chan(kind)
                    if out_ch is not None:
                        nbytes = 0
                        if device:
                            out_val, nbytes = _ship_device_tree(
                                out_val, ttl_s)
                        ser = serialize(_stage_to_host(out_val))
                        nbytes = nbytes or ser.total_bytes
                        out_ch.write(ser, DATA, timeout=timeout_s)
                        if metrics is not None:
                            try:
                                metrics["activation_bytes"].inc(nbytes)
                            except Exception:   # noqa: BLE001
                                pass
                    flight.add(op=j, kind=kind, mb=mb, ts=c0,
                               wait_s=round(wait_s, 6),
                               compute_s=round(c1 - c0, 6))
                    events.record(
                        "pipeline", "op", ph="X", ts=c0, dur=c1 - c0,
                        stage=stage, chain=chain, mb=mb, kind=kind,
                        step=step_tag, group=group,
                        wait_s=round(wait_s, 6), pid=os.getpid())
                    # stage/microbatch-tagged dag exec span: `ray-tpu
                    # list tasks` / the dag timeline see pipeline ops
                    # like any other dag compute
                    tracing.record_exec(
                        "", "dag", f"pipe{stage}:{kind}{mb}", c0, c1)
                # end of schedule: optimizer step + report to driver
                u0 = time.time()
                result = instance.pipe_step()
                u1 = time.time()
                stats["compute_s"] += u1 - u0
                step_dur = u1 - (step_t0 if step_t0 is not None else u0)
                stats["bubble_s"] += bubble
                stats["steps"] += 1
                if metrics is not None:
                    try:
                        metrics["stage_step"].observe(
                            step_dur, tags={"stage": str(stage)})
                        metrics["bubble"].observe(
                            bubble, tags={"stage": str(stage)})
                    except Exception:   # noqa: BLE001
                        pass
                events.record(
                    "pipeline", "step", ph="X",
                    ts=step_t0 if step_t0 is not None else u0,
                    dur=step_dur, stage=stage, chain=chain,
                    step=step_tag, group=group,
                    bubble_s=round(bubble, 6),
                    update_s=round(u1 - u0, 6), pid=os.getpid())
                try:
                    # this stage's step anatomy, pre-aggregated (the
                    # exec loop measures compute/bubble itself — no
                    # interval stamping). "rank" is the STAGE index:
                    # stage processes have no train rank, and per-stage
                    # rows are what the bubble-fraction cross-check in
                    # scripts/goodput_bench.py reads
                    from ray_tpu.util import goodput
                    goodput.record_step(
                        step_tag, step_dur, rank=stage,
                        compute=stats["compute_s"] - comp0,
                        bubble=bubble)
                except Exception:   # noqa: BLE001
                    pass
                res_out.write(serialize({
                    "result": result,
                    # per-step values only (THIS step's deltas); the
                    # loop's return value carries the cumulative totals
                    "stats": {"step_s": step_dur,
                              "bubble_s": bubble,
                              "update_s": u1 - u0,
                              "recv_s": stats["recv_s"] - recv0,
                              "overlapped_recv_s":
                                  stats["overlapped_recv_s"] - ov0}}),
                    DATA, timeout=timeout_s)
                step_no += 1
            except _Stop:
                _broadcast(b"", STOP)
                break
            except _Upstream as e:
                # a peer already failed: relay ITS error (driver raises
                # the original), terminate every edge, leave
                _broadcast(e.frame, ERROR)
                _broadcast(b"", STOP)
                break
            except _ReaderDead as e:
                cause = e.cause
                if isinstance(cause, (ChannelClosed, ChannelTimeout)):
                    cause = _pipe_peer_lost(cause, flight)
                _terminal(cause)
                break
            except BaseException as e:  # noqa: BLE001 — user/compute error
                if isinstance(e, (ChannelClosed, ChannelTimeout)):
                    # SEND-side channel death (peer gone, edge full
                    # forever): the same typed contract as a recv-side
                    # death — elastic train_fns catch PeerLostError,
                    # and the flight dump names the stalled op
                    e = _pipe_peer_lost(e, flight)
                _terminal(e)
                break
    finally:
        # unstick the reader BEFORE closing channels: _qput drops
        # frames once done_evt is set, so a reader blocked on the full
        # queue (or parked at a step boundary) exits instead of
        # leaking for the worker's lifetime
        done_evt.set()
        try:
            while True:
                rounds_q.get_nowait()
        except _queue.Empty:
            pass
        closer = getattr(instance, "pipe_close", None)
        if closer is not None:
            try:
                closer()    # releases the stage's ZeRO ring channels
            except Exception:   # noqa: BLE001 — teardown
                pass
        for ch in chans:
            ch.close()
            if getattr(ch, "_lazy_owner", False):
                ch.unlink()
        reader.join(timeout=2.0)
    return {"stage": stage, "chain": chain, "steps": stats["steps"],
            "timing": stats,
            "flight": flight.path}
