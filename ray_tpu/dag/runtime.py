"""Worker-side pinned execution loop for compiled DAGs.

The analog of the reference's compiled-graph executor loop (reference:
python/ray/dag/compiled_dag_node.py:805 _execute_until / the per-actor
do_exec_tasks loop): each pinned actor blocks on its input channels,
runs its bound method, and pushes the result downstream — no RPC, no
scheduler, no driver round-trip per item.

jax.Array results are staged to host (np.asarray) before entering the
channel — the seed of the tensor-transport path (reference:
experimental/rdt/tensor_transport_manager.py:37); device-to-device over
ICI belongs to jit'd collectives, not the object plane.
"""

from __future__ import annotations

import sys
from typing import List, Optional

import numpy as np

from ray_tpu.dag.channel import (DATA, ERROR, STOP, ShmRingChannel,
                                 attach_channel)
from ray_tpu.runtime.serialization import dumps_oob, loads_oob, serialize


def _stage_to_host(value):
    """Bare jax.Arrays are host-staged into the channel; a method that
    returns TensorRefs (runtime/device_store.py put_device) opts into
    the device transport instead — only the small handle rides the
    channel and the tensor moves on first resolution (zero-copy within
    a process)."""
    if "jax" in sys.modules:
        import jax
        if isinstance(value, jax.Array):
            return np.asarray(value)
    return value


class _Stop(Exception):
    pass


class _Upstream(Exception):
    """An ERROR frame arrived; carry it downstream unchanged."""

    def __init__(self, frame: bytes):
        self.frame = frame


def exec_loop(instance, spec: dict) -> dict:
    """Runs inside the actor's executor thread until a STOP frame.

    spec:
      method: attribute name on the actor instance
      in_channels: list of channel specs (one per bound upstream arg)
      arg_template: list where each element is ("chan", idx) or
        ("const", frame) — positional args in order
      out_channels: list of channel specs (broadcast to every consumer)
    """
    method = getattr(instance, spec["method"])
    # shm rings attach by name (same host); tcp edges bind/connect per
    # role — this stage CONSUMES its in-edges, PRODUCES its out-edges
    ins: List[ShmRingChannel] = [
        attach_channel(s, "consumer") for s in spec["in_channels"]]
    outs: List[ShmRingChannel] = [
        attach_channel(s, "producer") for s in spec["out_channels"]]
    template = [loads_oob(frame) if k == "const" else None
                for k, frame in spec["arg_template"]]
    chan_pos = [i for i, (k, _) in enumerate(spec["arg_template"])
                if k == "chan"]
    # Zero-copy is opt-in (compile(zero_copy=True)): args alias the ring
    # slot, which is only safe when the method does not retain them.
    single = len(ins) == 1 and spec.get("zero_copy")

    def _take_copy(kind, mv):
        """Deserialize from a copy — the slot is released on return, so
        zero-copy views must not escape this window."""
        if kind == DATA:
            return loads_oob(bytes(mv))
        raise _Stop() if kind == STOP else _Upstream(bytes(mv))

    def _run_in_window(kind, mv):
        """Zero-copy fast path: the method consumes the frame AND the
        result is serialized downstream INSIDE the slot window, so
        deserialization is zero-copy (arrays alias the ring slot —
        even a method returning a view of its input stays safe, since
        the slot is released only after the downstream copy)."""
        if kind != DATA:
            raise _Stop() if kind == STOP else _Upstream(bytes(mv))
        args = list(template)
        args[chan_pos[0]] = loads_oob(mv)
        ser = serialize(_stage_to_host(method(*args)))
        for out in outs:
            out.write(ser, DATA)

    processed = 0
    try:
        while True:
            try:
                if single:
                    ins[0].read_with(_run_in_window)
                else:
                    args = list(template)
                    pending: Optional[BaseException] = None
                    for pos, ch in zip(chan_pos, ins):
                        # Drain every input each round even after a
                        # stop/error so the channels stay in lockstep.
                        try:
                            args[pos] = ch.read_with(_take_copy)
                        except (_Stop, _Upstream) as e:
                            pending = pending or e
                    if pending is not None:
                        raise pending
                    ser = serialize(_stage_to_host(method(*args)))
                    for out in outs:
                        out.write(ser, DATA)
            except _Stop:
                for out in outs:
                    out.write(b"", STOP)
                break
            except _Upstream as e:
                for out in outs:
                    out.write(e.frame, ERROR)
            except BaseException as e:  # noqa: BLE001 — ship downstream
                try:
                    frame = dumps_oob(e)
                except Exception:  # unpicklable exception payload
                    frame = dumps_oob(RuntimeError(
                        f"{type(e).__name__}: {e}"))
                for out in outs:
                    out.write(frame, ERROR)
            else:
                processed += 1
    finally:
        for ch in ins + outs:
            ch.close()
            if getattr(ch, "_lazy_owner", False):
                ch.unlink()   # consumer created this same-node segment
    return {"processed": processed}
