"""In-situ auto-tuner for the collective plane: impl + chunk size per
payload band, from a one-shot micro-bench on the LIVE ring.

Replaces the static ``Config.allreduce_star_max_bytes`` crossover with
a measured one ("The Big Send-off", arxiv 2504.18658: the winning
collective regime switches with payload size, and the switch point is
a property of the deployment — hop latency and link bandwidth — not a
constant). The first collective op on a tuning-enabled ring runs two
tiny fused probe rounds (probes ARE collectives, so every rank reaches
them in lockstep and the group stays aligned), fits the classic
latency/bandwidth model ``t(S) = alpha + beta * S`` to the ring round,
and derives:

  * the star/ring crossover — the star pays ~4 hop latencies against
    the ring's 3(N-1), but its root moves O(N*S) bytes against the
    ring's O(S) per rank; equate and solve for S;
  * the hierarchical band — when the group spans nodes, cross-node
    bytes dominate large payloads and the ring-of-rings moves
    ~1/ranks-per-node of them, so payloads above a multiple of the
    star crossover go hierarchical;
  * a chunk size per payload — large enough that per-chunk framing
    costs less than the hop latency it hides, small enough that
    (4*(N-1)) chunks still pipeline around the ring.

Results are cached PER RING GENERATION: the cache key is the ring's
group id, which the train controller regenerates for every group
incarnation — a rewired (elastic) group re-probes instead of trusting
a dead topology's numbers. ``invalidate()`` drops entries explicitly.

The last probed profile doubles as the process default that
``dag.allreduce(impl="auto")`` consults at compile time (with the
static 4 MB knob as the fallback when nothing was ever probed), and
every decision lands in the ``collective_tuner_regime`` gauge
(0 = star, 1 = flat ring, 2 = hierarchical).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

REGIMES = {"star": 0, "ring": 1, "hier": 2}

_LOCK = threading.Lock()
_CACHE: Dict[str, dict] = {}     # group id -> profile entry
_DEFAULT: Optional[str] = None   # last probed group (compile-time table)
_MAX_ENTRIES = 64                # rings come and go with incarnations

# The codec band rides beside the impl/chunk profile: per ring
# generation, per wire codec, the probed round time and the observed
# quant-error bound. Separate cache because codec probes are optional
# (``allreduce_gradients(codec="auto")`` triggers them lazily) and a
# generation bump must drop BOTH — invalidate() clears this too.
_CODEC_CACHE: Dict[str, dict] = {}   # group id -> codec band entry

# Probe preference order, cheapest wire first: auto selection walks
# this list and takes the first codec whose probed error bound clears
# Config.collective_codec_error_bound (lossy codecs additionally
# require error-feedback to be on).
CODEC_ORDER = ("int4", "int8", "bf16", "fp32")
_LOSSY = ("int4", "int8")


def _cfg():
    from ray_tpu.config import get_config
    return get_config()


def profile_for(group: str, size: int) -> Optional[dict]:
    """The cached profile for a ring generation, or None (the signal
    to probe). A same-named group with a different world size is a
    different ring — never reuse its numbers."""
    with _LOCK:
        e = _CACHE.get(group or "")
        return e if e is not None and e["size"] == int(size) else None


def register_profile(group: str, size: int, alpha_s: float,
                     beta_s_per_b: float, *,
                     hierarchical: bool = False) -> dict:
    """Install a profile (the probe path, and the hook benches/tests
    use to inject known numbers). Becomes the process default table."""
    global _DEFAULT
    entry = {"group": group or "", "size": int(size),
             "alpha_s": max(1e-7, float(alpha_s)),
             "beta_s_per_b": max(1e-12, float(beta_s_per_b)),
             "hierarchical": bool(hierarchical),
             "probed_at": time.time()}
    with _LOCK:
        if len(_CACHE) >= _MAX_ENTRIES:
            oldest = min(_CACHE, key=lambda k: _CACHE[k]["probed_at"])
            del _CACHE[oldest]
        _CACHE[entry["group"]] = entry
        _DEFAULT = entry["group"]
    return entry


def invalidate(group: Optional[str] = None) -> None:
    """Drop one ring generation's profile (or all of them): the next
    collective on a tuning ring re-probes. Clears the codec band for
    the same generation too — an elastic reshape changes the wire
    (different size, possibly different hosts), so a cached codec
    choice from the dead topology must not survive the bump."""
    global _DEFAULT
    with _LOCK:
        if group is None:
            _CACHE.clear()
            _CODEC_CACHE.clear()
            _DEFAULT = None
        else:
            _CACHE.pop(group, None)
            _CODEC_CACHE.pop(group, None)
            if _DEFAULT == group:
                _DEFAULT = None


def probe_ring(ring) -> dict:
    """The one-shot in-situ micro-bench: two fused sum rounds on the
    live ring (small + large payload, min of 2 reps each), linear fit,
    cache per the ring's group id. The caller (RingReducer) guards
    reentrancy — the probe rounds themselves must not re-probe."""
    import numpy as np
    big = max(64 * 1024,
              int(getattr(_cfg(), "collective_tuner_probe_bytes",
                          1 << 20)))
    small = max(16 * 1024, big // 8)
    times: List[float] = []
    for nbytes in (small, big):
        best = None
        v = np.zeros(max(1, nbytes // 4), np.float32)
        for _ in range(2):
            t0 = time.perf_counter()
            ring.reduce(v, op="sum")
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        times.append(best)
    ts, tb = times
    if tb > ts:
        beta = (tb - ts) / float(big - small)
        alpha = max(ts - beta * small, 0.05 * ts)
    else:
        # noise inverted the slope: split the big round half fixed
        # cost, half wire — keeps the derived crossover finite
        beta = tb / (2.0 * big)
        alpha = tb / 2.0
    # AGREE on the profile: each rank measured its own wall clock, and
    # the derived chunk size is part of the ring's wire contract (the
    # sender chunks by it, the receiver expects it) — one more tiny
    # collective makes every rank register the bitwise-identical mean
    # profile instead of its private one
    agreed = ring.reduce(np.array([alpha, beta], np.float64), op="mean")
    alpha, beta = float(agreed[0]), float(agreed[1])
    hier = bool(getattr(ring, "level", None) == "inter"
                or getattr(ring, "nnodes", 1) > 1)
    return register_profile(getattr(ring, "group", ""), ring.size,
                            alpha, beta, hierarchical=hier)


# --- the decision surface -------------------------------------------------


def star_crossover(size: int, alpha_s: float,
                   beta_s_per_b: float) -> int:
    """Payload at/below which the star beats the flat ring. From the
    alpha/beta decomposition of a ring round (3(N-1) hops, 2S(N-1)/N
    wire per rank) vs a star round (~4 hops, 2(N-1)S at the root):
    S* = N(3N-7)h / (2(N-1)^2 w) with h the per-hop latency and w the
    per-byte cost. N <= 2 keeps the static knob (the two topologies
    move the same bytes and the model degenerates)."""
    n = int(size)
    static = int(getattr(_cfg(), "allreduce_star_max_bytes",
                         4 * 1024 * 1024))
    if n <= 2 or (3 * n - 7) <= 0:
        return static
    h = alpha_s / (3.0 * (n - 1))
    w = beta_s_per_b * n / (2.0 * (n - 1))
    s = n * (3 * n - 7) * h / (2.0 * (n - 1) ** 2 * w)
    return int(min(max(s, 64 * 1024), 64 << 20))


def hier_crossover(size: int, alpha_s: float,
                   beta_s_per_b: float) -> int:
    """Payload at/above which the hierarchical path wins, when a
    two-level topology exists: the ring-of-rings pays ~2 extra rounds
    of (cheap, shm) hops but moves ~1/ranks-per-node of the cross-node
    bytes — so it takes over once wire bytes dominate, a few multiples
    of the star crossover, floored at 8 MB."""
    s = star_crossover(size, alpha_s, beta_s_per_b)
    return int(min(max(4 * s, 8 << 20), 256 << 20))


def _entry(key: Optional[str], size: int) -> Optional[dict]:
    with _LOCK:
        if key:
            e = _CACHE.get(key)
        elif _DEFAULT is not None:      # "" is a legal default key
            e = _CACHE.get(_DEFAULT)
        else:
            e = None
    return e if e is not None and e["size"] == int(size) else None


def _gauge(regime: str) -> None:
    try:
        from ray_tpu.dag.ring import allreduce_metrics
        allreduce_metrics()["tuner_regime"].set(REGIMES[regime])
    except Exception:   # noqa: BLE001 — telemetry must never break
        pass


def choose_impl(payload_bytes: Optional[int], size: int, *,
                hierarchical: bool = False,
                key: Optional[str] = None) -> Optional[str]:
    """The tuned impl for one payload band, or None when no profile
    exists for ``key`` (nor a process default) — the caller falls back
    to the static crossover. ``hierarchical`` gates the "hier" regime
    (the topology must actually span nodes)."""
    e = _entry(key, size)
    if e is None or payload_bytes is None:
        return None
    a, b = e["alpha_s"], e["beta_s_per_b"]
    if payload_bytes <= star_crossover(size, a, b):
        impl = "star"
    elif hierarchical and payload_bytes >= hier_crossover(size, a, b):
        impl = "hier"
    else:
        impl = "ring"
    _gauge(impl)
    return impl


def tuned_chunk(group: str, size: int, payload_bytes: int,
                slot_bytes: int) -> Optional[int]:
    """Chunk size for one round from the ring's profile: at least the
    configured floor AND the bytes whose wire time equals one hop
    latency (smaller chunks pay more framing than they hide), at most
    the channel slot, aiming for ~4 in-flight chunks per ring step.
    None when this ring generation has no profile yet."""
    e = _entry(group, size)
    if e is None:
        return None
    n = max(2, int(size))
    h = e["alpha_s"] / (3.0 * (n - 1))
    w = e["beta_s_per_b"] * n / (2.0 * (n - 1))
    floor_b = int(h / w)
    target = int(payload_bytes) // (4 * (n - 1))
    lo = int(getattr(_cfg(), "collective_tuner_min_chunk_bytes",
                     64 * 1024))
    chunk = max(lo, floor_b, target)
    return int(max(4096, min(chunk, int(slot_bytes))))


def table(key: Optional[str], size: int,
          hierarchical: bool = False) -> Optional[List[dict]]:
    """The tuned payload-band table for reporting (benches, the CLI):
    [{"max_bytes": upper-bound-or-None, "impl": ...}, ...]."""
    e = _entry(key, size)
    if e is None:
        return None
    a, b = e["alpha_s"], e["beta_s_per_b"]
    s_star = star_crossover(size, a, b)
    rows = [{"max_bytes": s_star, "impl": "star"}]
    if hierarchical or e["hierarchical"]:
        s_h = hier_crossover(size, a, b)
        rows.append({"max_bytes": s_h, "impl": "ring"})
        rows.append({"max_bytes": None, "impl": "hier"})
    else:
        rows.append({"max_bytes": None, "impl": "ring"})
    return rows


# --- the codec band -------------------------------------------------------


def codec_profile_for(group: str, size: int) -> Optional[dict]:
    """The cached codec band for a ring generation, or None (the
    signal to probe): {"size": N, "codecs": {tag: {"round_s", "err"}}}.
    Same generation discipline as the impl profile — a same-named
    group at a different world size never reuses the band."""
    with _LOCK:
        e = _CODEC_CACHE.get(group or "")
        return e if e is not None and e["size"] == int(size) else None


def register_codec_profile(group: str, size: int, codec: str,
                           round_s: float, err: float) -> dict:
    """Record one codec's probed round time + observed quant-error
    bound for a ring generation (the probe path, and the injection
    hook benches/tests use). Eviction here is per-process and may
    leave RANKS disagreeing about what is cached — safe only because
    the probe decision is AGREED on the ring (_resolve_codec
    max-reduces the cache-miss bit, so one rank's eviction re-probes
    on all ranks in lockstep, never a lone collective)."""
    with _LOCK:
        if len(_CODEC_CACHE) >= _MAX_ENTRIES:
            oldest = min(_CODEC_CACHE,
                         key=lambda k: _CODEC_CACHE[k]["probed_at"])
            del _CODEC_CACHE[oldest]
        e = _CODEC_CACHE.setdefault(
            group or "", {"group": group or "", "size": int(size),
                          "codecs": {}, "probed_at": time.time()})
        if e["size"] != int(size):      # stale generation — replace
            e = {"group": group or "", "size": int(size),
                 "codecs": {}, "probed_at": time.time()}
            _CODEC_CACHE[group or ""] = e
        e["codecs"][codec] = {"round_s": float(round_s),
                              "err": float(err)}
        e["probed_at"] = time.time()
        return e


_CODEC_KW = {"int4": {"quantize": "int4"},
             "int8": {"quantize": "int8"},
             "bf16": {"wire_dtype": "bfloat16"},
             "fp32": {}}


def codec_wire_available(tag: str) -> bool:
    """LOCAL availability of one wire codec's prerequisites (bf16
    needs ml_dtypes; the lossy codecs need their frame cutters). No
    collectives here — a per-rank availability check must never be a
    round some peers skip."""
    import numpy as np
    from ray_tpu.dag import ring as ring_mod
    try:
        if tag == "bf16":
            ring_mod.resolve_wire_dtype("bfloat16")
        elif tag in _LOSSY:
            ring_mod.codec_roundtrip(np.ones(2, np.float32), tag)
        return True
    except Exception:   # noqa: BLE001 — "missing" is the answer
        return False


def probe_codecs(ring) -> Optional[dict]:
    """One timed small round per wire codec on the live ring,
    recording wall time and the ``allreduce_quant_error`` bound the
    round observed. Probes are collectives, so the probe LIST must be
    identical on every rank: availability is checked locally first
    (``codec_wire_available`` — no collective can fail on a subset of
    hosts without stranding the rest), then min-agreed on the ring so
    a codec probes only where EVERY rank has its prerequisites. A
    genuine collective failure mid-probe (peer death, timeout) is
    terminal for the group and PROPAGATES — swallowing it would leave
    peers blocked in a round this rank skipped. The payload is
    rank-seeded noise (rank-skewed values exercise the error bound the
    way real gradients do), and the recorded band is itself max-agreed
    — per-rank clocks and quant errors differ, but every rank must
    register the bitwise-identical band for ``choose_codec`` to
    resolve the same tag everywhere."""
    import numpy as np
    from ray_tpu.dag import ring as ring_mod
    avail = np.array([1.0 if codec_wire_available(t) else 0.0
                      for t in CODEC_ORDER], np.float64)
    agreed_avail = ring.reduce(avail, op="min")
    tags = [t for t, a in zip(CODEC_ORDER, agreed_avail) if a > 0]
    n = max(1, int(getattr(_cfg(), "collective_tuner_probe_bytes",
                           1 << 20)) // 32)
    v = np.random.default_rng(1 + getattr(ring, "rank", 0)) \
        .standard_normal(n).astype(np.float32)
    stats: List[float] = []
    for tag in tags:
        t0 = time.perf_counter()
        ring.reduce(v, op="mean", **_CODEC_KW[tag])
        stats.append(time.perf_counter() - t0)
        err = ring_mod.last_quant_error(tag)
        stats.append(0.0 if err is None else float(err))
    # max over ranks: the ring is as slow as its slowest rank, and the
    # error bound must cover every rank's frames
    agreed = ring.reduce(np.array(stats, np.float64), op="max")
    out = None
    for i, tag in enumerate(tags):
        out = register_codec_profile(getattr(ring, "group", ""),
                                     ring.size, tag,
                                     float(agreed[2 * i]),
                                     float(agreed[2 * i + 1]))
    return out


def choose_codec(payload_bytes: Optional[int], size: int, *,
                 key: Optional[str] = None,
                 ef_enabled: bool = True,
                 live_err: Optional[Dict[str, float]] = None) -> str:
    """Resolve ``codec="auto"`` for one payload: the cheapest wire
    codec that is SAFE for this round. Small payloads (below
    Config.collective_codec_min_bytes) stay fp32 — framing overhead
    and quant error buy nothing on a wire that cheap. Lossy codecs
    (int4/int8) require error-feedback; with EF off they are never
    chosen (bf16 is the floor). A codec is rejected when its probed
    error bound OR its live ``allreduce_quant_error`` reading (pass
    ``live_err={tag: bound}``) exceeds
    Config.collective_codec_error_bound. No codec band probed yet →
    bf16 when that state is transient (EF on, the tuner enabled to
    probe on the next round, ml_dtypes importable), fp32 otherwise —
    with the tuner off nothing will ever probe, so "auto" must not
    park forever on a codec whose prerequisites may not even import."""
    cfg = _cfg()
    bound = float(getattr(cfg, "collective_codec_error_bound", 1e-2))
    min_b = int(getattr(cfg, "collective_codec_min_bytes", 64 * 1024))
    if payload_bytes is not None and int(payload_bytes) < min_b:
        return "fp32"
    band = codec_profile_for(key or "", size)
    if band is None:
        if not ef_enabled \
                or not getattr(cfg, "collective_tuner", True) \
                or not codec_wire_available("bf16"):
            return "fp32"
        return "bf16"
    codecs = band["codecs"]
    for tag in CODEC_ORDER:
        if tag == "fp32":
            break               # the unconditional floor
        if tag in _LOSSY and not ef_enabled:
            continue
        if tag not in codecs:
            continue            # not probed (or probe failed) here
        err = codecs[tag]["err"]
        if live_err and tag in live_err:
            err = max(err, live_err[tag])
        if tag in _LOSSY and err > bound:
            continue            # the bound tripped — back off
        return tag
    return "fp32"
