"""Streaming datasets (reference capability: python/ray/data — Dataset at
data/dataset.py:189, read_api.py, streaming executor). Lazy plans over
columnar numpy blocks, generator-streamed with optional task fan-out;
iter_jax_batches stages batches to TPU with prefetch."""

from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (from_blocks, from_items, from_numpy,
                                     from_pandas, range,
                                     read_binary_files, read_csv,
                                     read_images, read_json, read_numpy, read_sql,
                                     read_parquet, read_text,
                                     read_tfrecord, read_webdataset,
                                     write_csv,
                                     write_json, write_parquet,
                                     write_tfrecord)
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "Dataset", "DataIterator", "from_blocks", "from_items", "from_numpy",
    "from_pandas", "range", "read_binary_files", "read_csv",
    "read_images", "read_json", "read_numpy", "read_sql",
    "read_parquet", "read_text", "read_tfrecord", "read_webdataset",
    "write_csv",
    "write_json", "write_parquet", "write_tfrecord",
]
