"""Blocks: the unit of data movement — columnar numpy tables.

Reference: python/ray/data/block.py (blocks are Arrow tables there). Here a
block is a dict[str, np.ndarray] — numpy-native so batches flow zero-copy
into jax.device_put / torch.from_numpy; Arrow interop at the parquet
boundary only.

Column dtype contract:
- uniform scalars / equal-shape sequences -> dense numeric arrays (2D+
  for tensor columns): the ZERO-COPY tensor path into device_put.
- strings -> native numpy 'U' arrays (vectorized sort/compare).
- RAGGED sequences (per-row variable shape: token lists, boxes, dicts)
  -> an explicit 1-D object array holding the Python values. Row
  identity is preserved through slice/take/concat — shuffle, sort,
  groupby and join all work — but the column rides the OBJECT path:
  no vectorized kernels, no zero-copy into jax. Pad/truncate to a
  fixed shape (e.g. map_batches) before feeding device code.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional

import numpy as np

Block = Dict[str, np.ndarray]


def block_from_rows(rows: List[dict]) -> Block:
    if not rows:
        return {}
    cols: Dict[str, list] = {k: [] for k in rows[0]}
    for r in rows:
        for k in cols:
            cols[k].append(r.get(k))
    return {k: _to_array(v) for k, v in cols.items()}


def _to_array(values: list) -> np.ndarray:
    try:
        return np.asarray(values)
    except ValueError:
        # Ragged rows (inhomogeneous shapes raise under numpy>=1.24):
        # keep the column honest as a 1-D object array of the original
        # Python values instead of crashing the pipeline — see the
        # module docstring's dtype contract.
        return object_array(values)


def object_array(values: list) -> np.ndarray:
    """1-D object array with one slot per ROW (np.empty + per-row
    assignment: a plain fill can still trip numpy's broadcasting when
    rows happen to share a length)."""
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def block_from_items(items: List[Any]) -> Block:
    """Non-dict items get the reference's implicit 'item' column
    (reference: from_items wraps scalars the same way)."""
    if items and isinstance(items[0], dict):
        return block_from_rows(items)
    return {"item": _to_array(items)}


def block_num_rows(b: Block) -> int:
    for v in b.values():
        return len(v)
    return 0


def block_slice(b: Block, start: int, end: int) -> Block:
    return {k: v[start:end] for k, v in b.items()}


def block_take(b: Block, idx: np.ndarray) -> Block:
    return {k: v[idx] for k, v in b.items()}


def block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b)]
    if not blocks:
        return {}
    out: Block = {}
    for k in blocks[0].keys():
        parts = [b[k] for b in blocks]
        try:
            out[k] = np.concatenate(parts)
        except ValueError:
            # a column ragged ACROSS blocks (dense [n,3] in one part,
            # [m,4] or object in another): fall back to one object row
            # per element. Dense parts convert via tolist() so the
            # column holds plain Python values THROUGHOUT — mixing
            # ndarray rows with list rows would make `row == [...]`
            # comparisons blow up for some rows only.
            rows = []
            for p in parts:
                rows.extend(list(p) if p.dtype == object else p.tolist())
            out[k] = object_array(rows)
    return out


def block_rows(b: Block) -> Iterable[dict]:
    n = block_num_rows(b)
    keys = list(b.keys())
    for i in range(n):
        yield {k: b[k][i] for k in keys}


def block_to_pandas(b: Block):
    import pandas as pd
    return pd.DataFrame({k: list(v) if v.ndim > 1 else v
                         for k, v in b.items()})


def block_from_arrow(table) -> Block:
    out = {}
    for name in table.column_names:
        col = table.column(name)
        try:
            out[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            out[name] = np.asarray(col.to_pylist(), dtype=object)
    return out


def block_to_arrow(b: Block):
    import pyarrow as pa
    return pa.table({k: pa.array(list(v) if v.ndim > 1 else v)
                     for k, v in b.items()})
