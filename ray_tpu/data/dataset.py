"""Dataset: lazy logical plan → streaming block execution.

Reference: python/ray/data/dataset.py:189 (Dataset), the logical plan +
rule-based optimizer (data/_internal/logical/), physical operators
(data/_internal/execution/operators/) and the StreamingExecutor
(streaming_executor.py:76). Here the plan is a chain of operators executed
as a generator pipeline — block-at-a-time streaming with implicit
backpressure (a consumer pulls, producers run) — with per-stage fan-out to
runtime tasks for CPU-heavy map_batches (reference: ActorPoolMapOperator /
TaskPoolMapOperator).

Shuffle-like ops (sort/groupby/random_shuffle/repartition) are pipeline
breakers that materialize, matching the reference's all-to-all operators.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple, Union)

import numpy as np

from ray_tpu.data.block import (Block, block_concat, block_from_items,
                                block_from_rows, block_num_rows, block_rows,
                                block_slice, block_take, block_to_pandas)

BatchFormat = str  # "numpy" (dict of arrays) | "pandas" | "rows"


# --- logical operators -------------------------------------------------------

@dataclass
class _Op:
    name: str
    kind: str                      # source|map|filter|flat|all2all|...
    fn: Optional[Callable] = None
    args: dict = field(default_factory=dict)


class Dataset:
    """Lazy, immutable; every transform returns a new Dataset with one more
    operator on the plan (reference: dataset.py Dataset._plan)."""

    def __init__(self, ops: List[_Op]):
        self._ops = ops

    # ---- plan construction ----
    def _with(self, op: _Op) -> "Dataset":
        return Dataset(self._ops + [op])

    def map(self, fn: Callable[[dict], dict]) -> "Dataset":
        return self._with(_Op("map", "map_rows", fn))

    def map_batches(self, fn: Callable, *,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None,
                    num_cpus: Optional[float] = None,
                    max_in_flight_bytes: Optional[int] = None,
                    batch_size: Optional[int] = 4096,
                    batch_format: BatchFormat = "numpy",
                    concurrency: Optional[int] = None) -> "Dataset":
        """``fn`` may be a FUNCTION (stateless: runs inline, or as a
        task pool with `concurrency`) or a callable CLASS (stateful —
        e.g. a model loaded once per worker: runs on an ACTOR POOL of
        `concurrency` actors, constructed with fn_constructor_args;
        reference: ActorPoolMapOperator / ActorPoolStrategy).
        ``max_in_flight_bytes`` bounds the bytes of input batches
        concurrently in flight — fan-out stages otherwise have no
        memory ceiling (reference:
        data/_internal/execution/backpressure_policy/)."""
        return self._with(_Op("map_batches", "map_batches", fn,
                              {"batch_size": batch_size,
                               "batch_format": batch_format,
                               "concurrency": concurrency,
                               "fn_constructor_args": fn_constructor_args,
                               "fn_constructor_kwargs":
                                   fn_constructor_kwargs or {},
                               "num_cpus": num_cpus,
                               "max_in_flight_bytes":
                                   max_in_flight_bytes}))

    def flat_map(self, fn: Callable[[dict], List[dict]]) -> "Dataset":
        return self._with(_Op("flat_map", "flat_map", fn))

    def filter(self, fn: Callable[[dict], bool]) -> "Dataset":
        return self._with(_Op("filter", "filter", fn))

    def add_column(self, name: str, fn: Callable[[Block], np.ndarray]
                   ) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[name] = fn(batch)
            return batch
        return self.map_batches(add, batch_size=None)

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(batch):
            return {k: v for k, v in batch.items() if k not in cols}
        return self.map_batches(drop, batch_size=None)

    def select_columns(self, cols: List[str]) -> "Dataset":
        # A declarative op (not a map_batches closure) so the optimizer
        # can push the projection into a parquet scan.
        return self._with(_Op("select", "select", None,
                              {"cols": list(cols)}))

    def limit(self, n: int) -> "Dataset":
        return self._with(_Op("limit", "limit", None, {"n": n}))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with(_Op("repartition", "all2all", None,
                              {"mode": "repartition", "n": num_blocks}))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(_Op("random_shuffle", "all2all", None,
                              {"mode": "shuffle", "seed": seed}))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(_Op("sort", "all2all", None,
                              {"mode": "sort", "key": key,
                               "descending": descending}))

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(_Op("union", "union", None,
                              {"others": [o._ops for o in others]}))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(_Op("zip", "zip", None, {"other": other._ops}))

    def join(self, other: "Dataset", on: str, *,
             join_type: str = "inner", suffix: str = "_r") -> "Dataset":
        """Hash join on a key column (reference:
        data/_internal/execution/operators/join.py). ``join_type`` is
        "inner" or "left"; colliding right columns get ``suffix``. Runs
        distributed when the runtime is up (both sides hash-partitioned
        by key, one join task per partition)."""
        if join_type not in ("inner", "left"):
            raise ValueError("join_type must be 'inner' or 'left'")
        return self._with(_Op("join", "join", None,
                              {"other": other._ops, "on": on,
                               "join_type": join_type, "suffix": suffix}))

    # ---- execution ----
    def iter_blocks(self) -> Iterator[Block]:
        yield from _execute(self._ops)

    def optimized_plan(self) -> List[_Op]:
        """The plan after the rewrite rules run (introspection/tests)."""
        return _optimize(self._ops)

    def materialize(self) -> "Dataset":
        blocks = [b for b in self.iter_blocks() if block_num_rows(b)]
        return Dataset([_Op("from_blocks", "source", None,
                            {"blocks": blocks})])

    # ---- consumption ----
    def count(self) -> int:
        return sum(block_num_rows(b) for b in self.iter_blocks())

    def take(self, n: int = 20) -> List[dict]:
        out: List[dict] = []
        for b in self.iter_blocks():
            for r in block_rows(b):
                out.append(r)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[dict]:
        return [r for b in self.iter_blocks() for r in block_rows(b)]

    def take_batch(self, batch_size: int = 20,
                   batch_format: BatchFormat = "numpy"):
        it = self.iterator().iter_batches(batch_size=batch_size,
                                          batch_format=batch_format)
        return next(iter(it))

    def show(self, n: int = 20) -> None:
        for r in self.take(n):
            print(r)

    def schema(self) -> Dict[str, str]:
        for b in self.iter_blocks():
            if block_num_rows(b):
                return {k: str(v.dtype) for k, v in b.items()}
        return {}

    def columns(self) -> List[str]:
        return list(self.schema().keys())

    def to_pandas(self):
        blocks = list(self.iter_blocks())
        return block_to_pandas(block_concat(blocks) if blocks else {})

    def sum(self, on: str) -> float:
        return float(sum(float(np.sum(b[on]))
                         for b in self.iter_blocks() if block_num_rows(b)))

    def min(self, on: str):
        vals = [np.min(b[on]) for b in self.iter_blocks()
                if block_num_rows(b)]
        return np.min(vals) if vals else None

    def max(self, on: str):
        vals = [np.max(b[on]) for b in self.iter_blocks()
                if block_num_rows(b)]
        return np.max(vals) if vals else None

    def mean(self, on: str) -> Optional[float]:
        total, count = 0.0, 0
        for b in self.iter_blocks():
            n = block_num_rows(b)
            if n:
                total += float(np.sum(b[on]))
                count += n
        return total / count if count else None

    def iter_rows(self) -> Iterator[dict]:
        for b in self.iter_blocks():
            yield from block_rows(b)

    def iterator(self) -> "DataIterator":
        from ray_tpu.data.iterator import DataIterator
        return DataIterator(self.iter_blocks)

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    # ---- split for distributed training ----
    def streaming_split(self, n: int, *, equal: bool = True
                        ) -> List["DataIterator"]:
        """n per-worker iterators (reference: dataset.py:2037
        streaming_split + _internal/iterator/stream_split_iterator.py).
        Each shard iterator opens a push-based streaming TASK
        (num_returns="streaming") at iteration time: the producer runs
        the plan on a worker and yields only that shard's row-slices,
        so blocks flow producer -> consumer as they are produced with
        stream-window-bounded memory — no upfront materialization.
        Iterators are picklable (plan payload + shard index), open
        their stream in the CONSUMING process (each train worker owns
        its own stream), and are re-iterable: every epoch submits a
        fresh producer task.

        Unlike the reference's coordinator-actor design, shards execute
        the plan independently (n plan runs instead of one) — the
        tradeoff buys re-iterability and zero idle-actor footprint."""
        from ray_tpu.data.iterator import DataIterator
        import cloudpickle
        payload = cloudpickle.dumps(self._ops, protocol=5)

        def make_iter(idx):
            def gen():
                import ray_tpu as rt
                g = rt.remote(_produce_shard).options(
                    num_returns="streaming").remote(payload, idx, n,
                                                    equal)
                try:
                    for ref in g:
                        b = rt.get(ref)
                        # consumed: free now — multi-epoch re-iteration
                        # mints fresh oids each pass, so unfreed blocks
                        # would accumulate in this worker's store
                        rt.free([ref])
                        yield b
                finally:
                    g.close()  # early exit stops this shard's stream
            return DataIterator(gen)
        return [make_iter(i) for i in range(n)]

    def split(self, n: int) -> List["Dataset"]:
        blocks = list(self.iter_blocks())
        rows = block_concat(blocks) if blocks else {}
        total = block_num_rows(rows)
        per = total // n
        out = []
        for i in range(n):
            start = i * per
            end = total if i == n - 1 else (i + 1) * per
            out.append(Dataset([_Op("from_blocks", "source", None,
                                    {"blocks": [block_slice(rows, start,
                                                            end)]})]))
        return out

    # ---- writes ----
    def write_parquet(self, path: str) -> None:
        from ray_tpu.data.datasource import write_parquet
        write_parquet(self, path)

    def write_csv(self, path: str) -> None:
        from ray_tpu.data.datasource import write_csv
        write_csv(self, path)

    def write_json(self, path: str) -> None:
        from ray_tpu.data.datasource import write_json
        write_json(self, path)

    def write_tfrecord(self, path: str) -> None:
        from ray_tpu.data.datasource import write_tfrecord
        write_tfrecord(self, path)

    def __repr__(self):
        names = "->".join(op.name for op in self._ops)
        return f"Dataset({names})"


def _produce_shard(ops_payload: bytes, shard: int, n: int, equal: bool):
    """Streaming-split producer task (sync generator; runs under
    num_returns="streaming"): executes the plan and yields shard
    `shard`'s blocks. equal=True row-slices every block across all
    shards (rotating the remainder rows) so shards stay row-balanced
    without knowing the total row count up front; equal=False deals
    whole blocks round-robin."""
    import cloudpickle
    ops = cloudpickle.loads(ops_payload)
    rr = 0
    for b in Dataset(ops).iter_blocks():
        rows = block_num_rows(b)
        if not rows:
            continue
        if equal:
            per, extra = divmod(rows, n)
            start = 0
            for j in range(n):
                cnt = per + (1 if (j - rr) % n < extra else 0)
                if cnt and j == shard:
                    yield block_slice(b, start, start + cnt)
                start += cnt
            rr = (rr + extra) % n
        else:
            if rr % n == shard:
                yield b
            rr += 1


class GroupedData:
    """Hash aggregation (reference: grouped_data.py + hash-aggregate
    physical operator)."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _agg(self, cols: Dict[str, Tuple[str, Callable]]) -> Dataset:
        if _runtime_up():
            # Hash-partitioned distributed aggregation: every row of a key
            # lands in one partition, aggregated there by a task
            # (reference: hash-aggregate over hash_shuffle.py).
            from ray_tpu.data.shuffle import distributed_groupby
            blocks = list(distributed_groupby(
                self._ds.iter_blocks(), self._key, cols))
            return Dataset([_Op("from_blocks", "source", None,
                                {"blocks": blocks})])
        groups: Dict[Any, List[dict]] = {}
        for row in self._ds.iter_rows():
            groups.setdefault(row[self._key], []).append(row)
        out_rows = []
        for k, rows in groups.items():
            out = {self._key: k}
            for out_name, (col, fn) in cols.items():
                out[out_name] = fn(np.asarray([r[col] for r in rows]))
            out_rows.append(out)
        return Dataset([_Op("from_blocks", "source", None,
                            {"blocks": [block_from_rows(out_rows)]})])

    def count(self) -> Dataset:
        return self._agg({"count()": (self._key, len)})

    def sum(self, on: str) -> Dataset:
        return self._agg({f"sum({on})": (on, lambda v: float(np.sum(v)))})

    def mean(self, on: str) -> Dataset:
        return self._agg({f"mean({on})": (on, lambda v: float(np.mean(v)))})

    def min(self, on: str) -> Dataset:
        return self._agg({f"min({on})": (on, lambda v: np.min(v))})

    def max(self, on: str) -> Dataset:
        return self._agg({f"max({on})": (on, lambda v: np.max(v))})

    def std(self, on: str) -> Dataset:
        return self._agg({f"std({on})": (on, lambda v: float(np.std(v)))})


# --- plan optimizer ----------------------------------------------------------

def _optimize(ops: List[_Op]) -> List[_Op]:
    """Rule-based logical rewrite (reference:
    data/_internal/logical/optimizers.py — there a visitor framework;
    here two high-value rules over the op list):

    1. projection pushdown — select_columns directly after read_parquet
       narrows the scan itself, so parquet reads only those columns
       off disk;
    2. stage fusion — consecutive row-wise ops (map/filter/flat_map)
       collapse into ONE operator that makes a single pass over each
       block instead of materializing an intermediate block per stage.
    """
    ops = list(ops)
    # rule 1: fold consecutive selects into a parquet scan — only when
    # the select NARROWS the current projection (folding a widening
    # select would silently resurrect dropped columns; left unfolded it
    # raises KeyError at execution, the pre-optimizer behavior)
    if ops and ops[0].name == "read_parquet":
        while len(ops) > 1 and ops[1].kind == "select":
            cols = ops[1].args["cols"]
            cur = ops[0].args.get("columns")
            if cur is not None and not set(cols) <= set(cur):
                break
            src_args = dict(ops[0].args)
            src_args["columns"] = list(cols)
            ops[0] = _Op("read_parquet", "source", None, src_args)
            del ops[1]
    # rule 2: fuse adjacent row-wise stages
    fused: List[_Op] = []
    for op in ops:
        if op.kind in ("map_rows", "filter", "flat_map"):
            if fused and fused[-1].kind == "fused_rows":
                prev = fused[-1]
                fused[-1] = _Op(f"{prev.name}+{op.name}", "fused_rows",
                                None, {"stages": prev.args["stages"]
                                       + [(op.kind, op.fn)]})
            else:
                fused.append(_Op(op.name, "fused_rows", None,
                                 {"stages": [(op.kind, op.fn)]}))
        else:
            fused.append(op)
    return fused


# --- execution engine --------------------------------------------------------

def _execute(ops: List[_Op]) -> Iterator[Block]:
    """Build the generator pipeline bottom-up. Each stage pulls from the
    previous — streaming with inherent backpressure (the reference gets the
    same property from StreamingExecutor's bounded buffers)."""
    stream: Iterator[Block] = iter(())
    for op in _optimize(ops):
        stream = _apply(stream, op)
    return stream


def _apply(stream: Iterator[Block], op: _Op) -> Iterator[Block]:
    if op.kind == "source":
        return _source(op)
    if op.kind == "map_rows":
        return (_map_rows(b, op.fn) for b in stream)
    if op.kind == "select":
        cols = op.args["cols"]
        return ({k: b[k] for k in cols} for b in stream)
    if op.kind == "fused_rows":
        stages = op.args["stages"]
        return (_fused_rows_block(b, stages) for b in stream)
    if op.kind == "flat_map":
        return (_flat_map_rows(b, op.fn) for b in stream)
    if op.kind == "filter":
        return (_filter_rows(b, op.fn) for b in stream)
    if op.kind == "map_batches":
        return _map_batches_stream(stream, op)
    if op.kind == "limit":
        return _limit_stream(stream, op.args["n"])
    if op.kind == "all2all":
        return _all2all(stream, op)
    if op.kind == "union":
        def union_gen():
            yield from stream
            for other_ops in op.args["others"]:
                yield from _execute(other_ops)
        return union_gen()
    if op.kind == "zip":
        return _zip_stream(stream, _execute(op.args["other"]))
    if op.kind == "join":
        return _join_exec(stream, op)
    raise ValueError(f"unknown op kind {op.kind}")


def _fused_rows_block(b: Block, stages) -> Block:
    """One pass over a block through a fused chain of row-wise stages
    (map/filter/flat_map) — no intermediate block per stage."""
    out: List[dict] = []
    samples: Dict[int, dict] = {}   # stage idx -> one observed output row
    for r in block_rows(b):
        items = [r]
        for si, (kind, fn) in enumerate(stages):
            if kind == "map_rows":
                items = [fn(x) for x in items]
            elif kind == "filter":
                items = [x for x in items if fn(x)]
            else:  # flat_map
                items = [y for x in items for y in fn(x)]
            if items and si not in samples:
                samples[si] = items[0]
            if not items:
                break
        out.extend(items)
    if not out:
        # No surviving rows: reconstruct the (empty) output SCHEMA from
        # rows the fused pass already observed — downstream ops (left
        # joins) rely on it, and re-running the UDFs would double work
        # and side effects. Semantics match per-stage execution: a
        # map/flat_map stage that never saw a row yields a schemaless
        # block (block_from_rows([]) == {}); filters pass schema
        # through.
        sample: Optional[dict] = "input"  # sentinel: input schema
        for si, (kind, _fn) in enumerate(stages):
            if kind == "filter":
                continue
            sample = samples.get(si)
            if sample is None:
                return {}
        if sample == "input":
            return {c: np.asarray(v)[:0] for c, v in b.items()}
        one = block_from_rows([sample])
        return {c: np.asarray(v)[:0] for c, v in one.items()}
    return block_from_rows(out)


def _source(op: _Op) -> Iterator[Block]:
    args = op.args
    if "parquet_paths" in args:
        # declarative parquet scan (kept lazy so the optimizer can
        # narrow `columns` before any file is opened)
        import pyarrow.parquet as pq

        from ray_tpu.data.block import block_from_arrow
        for path in args["parquet_paths"]:
            yield block_from_arrow(
                pq.read_table(path, columns=args.get("columns")))
        return
    if "blocks" in args:
        yield from args["blocks"]
        return
    if "block_fns" in args:
        for fn in args["block_fns"]:
            out = fn()
            if isinstance(out, dict):
                yield out
            else:
                yield from out
        return
    raise ValueError("source op missing blocks")


def _map_rows(b: Block, fn) -> Block:
    return block_from_rows([fn(r) for r in block_rows(b)])


def _flat_map_rows(b: Block, fn) -> Block:
    out: List[dict] = []
    for r in block_rows(b):
        out.extend(fn(r))
    return block_from_rows(out)


def _filter_rows(b: Block, fn) -> Block:
    keep = np.asarray([bool(fn(r)) for r in block_rows(b)])
    if not keep.any():
        # zero rows but KEEP the columns: schema must survive an
        # all-filtered block (left joins emit right columns as nulls
        # based on it)
        return {c: np.asarray(v)[:0] for c, v in b.items()}
    return block_take(b, np.nonzero(keep)[0])


def _rebatch(stream: Iterator[Block],
             batch_size: Optional[int]) -> Iterator[Block]:
    if batch_size is None:
        yield from stream
        return
    buf: List[Block] = []
    rows = 0
    for b in stream:
        n = block_num_rows(b)
        if not n:
            continue
        buf.append(b)
        rows += n
        while rows >= batch_size:
            merged = block_concat(buf)
            yield block_slice(merged, 0, batch_size)
            rest = block_slice(merged, batch_size, block_num_rows(merged))
            buf = [rest] if block_num_rows(rest) else []
            rows = block_num_rows(rest)
    if rows:
        yield block_concat(buf)


def _convert_in(b: Block, fmt: str):
    if fmt == "pandas":
        return block_to_pandas(b)
    if fmt == "rows":
        return list(block_rows(b))
    return b


def _convert_out(out) -> Block:
    if isinstance(out, dict):
        return {k: np.asarray(v) for k, v in out.items()}
    if isinstance(out, list):
        return block_from_rows(out)
    try:
        import pandas as pd
        if isinstance(out, pd.DataFrame):
            return {c: out[c].to_numpy() for c in out.columns}
    except ImportError:
        pass
    raise TypeError(f"map_batches fn returned {type(out)}")


def _map_batches_stream(stream: Iterator[Block], op: _Op) -> Iterator[Block]:
    args = op.args
    fmt = args.get("batch_format", "numpy")
    concurrency = args.get("concurrency")
    batches = _rebatch(stream, args.get("batch_size"))
    fn = op.fn

    if isinstance(fn, type):
        # stateful UDF: one instance per pool worker
        if concurrency and _runtime_up():
            yield from _actor_pool_map(batches, fn, fmt, args)
            return
        inst = fn(*args.get("fn_constructor_args", ()),
                  **args.get("fn_constructor_kwargs", {}))
        for b in batches:
            yield _convert_out(inst(_convert_in(b, fmt)))
        return
    if concurrency and concurrency > 1 and _runtime_up():
        yield from _parallel_map(batches, fn, fmt, concurrency,
                                 args.get("num_cpus"),
                                 args.get("max_in_flight_bytes"))
        return
    for b in batches:
        yield _convert_out(fn(_convert_in(b, fmt)))


def _runtime_up() -> bool:
    try:
        import ray_tpu
        return ray_tpu.is_initialized()
    except Exception:
        return False


def _block_nbytes(b: Block) -> int:
    return sum(np.asarray(v).nbytes for v in b.values())


def _windowed(batches: Iterator[Block], submit, cap: int,
              max_bytes: Optional[int],
              on_done=None) -> Iterator[Block]:
    """THE in-order fan-out scheduler shared by the task-pool and
    actor-pool map operators: at most `cap` submissions (and, when
    set, `max_bytes` of input bytes) in flight; results yield in
    submission order (reference: TaskPoolMapOperator /
    ActorPoolMapOperator + the execution backpressure policies that
    bound per-op memory). `submit(block) -> (ref, meta)`;
    `on_done(meta)` runs when that submission's result is yielded."""
    import ray_tpu

    window: List = []        # (ref, meta, input_nbytes) in order
    in_bytes = 0

    def drain_one():
        nonlocal in_bytes
        ref, meta, nb = window.pop(0)
        in_bytes -= nb
        out = ray_tpu.get(ref, timeout=600)
        if on_done is not None:
            on_done(meta)
        return out

    for b in batches:
        nb = _block_nbytes(b)
        while window and (
                len(window) >= cap
                or (max_bytes is not None
                    and in_bytes + nb > max_bytes)):
            yield drain_one()
        ref, meta = submit(b)
        window.append((ref, meta, nb))
        in_bytes += nb
    while window:
        yield drain_one()


def _parallel_map(batches: Iterator[Block], fn, fmt: str,
                  concurrency: int, num_cpus: Optional[float] = None,
                  max_in_flight_bytes: Optional[int] = None
                  ) -> Iterator[Block]:
    """Stateless fan-out: one runtime task per batch."""
    import ray_tpu

    @ray_tpu.remote
    def _run_batch(fn_, b, fmt_):
        return _convert_out(fn_(_convert_in(b, fmt_)))

    task = _run_batch.options(num_cpus=num_cpus) \
        if num_cpus is not None else _run_batch
    yield from _windowed(batches,
                         lambda b: (task.remote(fn, b, fmt), None),
                         concurrency, max_in_flight_bytes)


class _MapWorker:
    """Actor-pool worker hosting ONE instance of a stateful map UDF
    (reference: ActorPoolMapOperator's _MapWorker)."""

    def __init__(self, cls_payload: bytes, ctor_args, ctor_kwargs):
        import cloudpickle
        cls = cloudpickle.loads(cls_payload)
        self.fn = cls(*ctor_args, **(ctor_kwargs or {}))

    def run(self, b, fmt: str):
        return _convert_out(self.fn(_convert_in(b, fmt)))


def _actor_pool_map(batches: Iterator[Block], cls, fmt: str,
                    args: dict) -> Iterator[Block]:
    """Stateful map over an actor pool: `concurrency` actors each
    construct the UDF once (model load amortized across every batch),
    batches go to the least-loaded actor, results yield in input
    order. In-flight work is bounded by 2 batches per actor plus the
    optional byte budget."""
    import cloudpickle

    import ray_tpu
    concurrency = int(args.get("concurrency") or 1)
    num_cpus = args.get("num_cpus")
    max_bytes = args.get("max_in_flight_bytes")
    payload = cloudpickle.dumps(cls, protocol=5)
    opts = {"num_cpus": num_cpus} if num_cpus is not None else {}
    Worker = ray_tpu.remote(_MapWorker).options(**opts) \
        if opts else ray_tpu.remote(_MapWorker)
    actors = [Worker.remote(payload, args.get("fn_constructor_args", ()),
                            args.get("fn_constructor_kwargs", {}))
              for _ in range(concurrency)]
    try:
        loads = [0] * concurrency

        def submit(b):
            ai = min(range(concurrency), key=lambda i: loads[i])
            loads[ai] += 1
            return actors[ai].run.remote(b, fmt), ai

        def done(ai):
            loads[ai] -= 1

        # cap = 2 per actor: every actor busy + one queued
        yield from _windowed(batches, submit, concurrency * 2,
                             max_bytes, on_done=done)
    finally:
        for a in actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass


def _limit_stream(stream: Iterator[Block], n: int) -> Iterator[Block]:
    left = n
    for b in stream:
        rows = block_num_rows(b)
        if rows <= left:
            yield b
            left -= rows
        else:
            yield block_slice(b, 0, left)
            left = 0
        if left <= 0:
            return


def _all2all(stream: Iterator[Block], op: _Op) -> Iterator[Block]:
    if _runtime_up():
        # Distributed path: map/reduce over runtime tasks + object plane;
        # the driver streams refs, never the whole dataset (reference:
        # hash_shuffle.py / planner/exchange).
        from ray_tpu.data.shuffle import distributed_all2all
        mode = op.args["mode"]
        if mode == "shuffle":
            spec = {"mode": "shuffle", "seed": op.args.get("seed")}
            yield from distributed_all2all(stream, spec)
            return
        if mode == "sort":
            spec = {"mode": "range", "key": op.args["key"],
                    "descending": op.args.get("descending", False)}
            yield from distributed_all2all(stream, spec)
            return
        if mode == "repartition":
            spec = {"mode": "split"}
            yield from distributed_all2all(stream, spec,
                                           n_out=op.args["n"])
            return
    yield from _all2all_local(stream, op)


def _all2all_local(stream: Iterator[Block], op: _Op) -> Iterator[Block]:
    mode = op.args["mode"]
    blocks = [b for b in stream if block_num_rows(b)]
    if not blocks:
        return
    merged = block_concat(blocks)
    total = block_num_rows(merged)
    if mode == "shuffle":
        rng = np.random.default_rng(op.args.get("seed"))
        idx = rng.permutation(total)
        merged = block_take(merged, idx)
        n_out = max(1, len(blocks))
    elif mode == "sort":
        key = op.args["key"]
        idx = np.argsort(merged[key], kind="stable")
        if op.args.get("descending"):
            idx = idx[::-1]
        merged = block_take(merged, idx)
        n_out = max(1, len(blocks))
    elif mode == "repartition":
        n_out = op.args["n"]
    else:
        raise ValueError(mode)
    per = max(1, total // n_out)
    for i in range(n_out):
        start = i * per
        end = total if i == n_out - 1 else (i + 1) * per
        if start >= total:
            break
        yield block_slice(merged, start, end)


def _join_exec(stream: Iterator[Block], op: _Op) -> Iterator[Block]:
    other = _execute(op.args["other"])
    key = op.args["on"]
    jt, suffix = op.args["join_type"], op.args["suffix"]
    if _runtime_up():
        from ray_tpu.data.shuffle import distributed_join
        yield from distributed_join(stream, other, key, jt, suffix)
        return
    # local fallback (no cluster): concat both sides, one in-driver join
    from ray_tpu.data.shuffle import join_blocks
    lblocks = [b for b in stream if block_num_rows(b)]
    rall = list(other)
    rblocks = [b for b in rall if block_num_rows(b)]
    # a zero-row right side still carries SCHEMA: left joins must emit
    # its columns as nulls rather than silently change shape
    rb = block_concat(rblocks) if rblocks else \
        next((b for b in rall if len(b) > 0), None)
    out = join_blocks(block_concat(lblocks) if lblocks else None,
                      rb, key, jt, suffix)
    if block_num_rows(out):
        yield out


def _zip_stream(a: Iterator[Block], b: Iterator[Block]) -> Iterator[Block]:
    abuf: List[Block] = []
    bbuf: List[Block] = []

    def pull(it, buf, need):
        have = sum(block_num_rows(x) for x in buf)
        while have < need:
            try:
                blk = next(it)
            except StopIteration:
                break
            buf.append(blk)
            have += block_num_rows(blk)

    while True:
        pull(a, abuf, 1)
        pull(b, bbuf, 1)
        na = sum(block_num_rows(x) for x in abuf)
        nb = sum(block_num_rows(x) for x in bbuf)
        n = min(na, nb)
        if n == 0:
            return
        ma, mb = block_concat(abuf), block_concat(bbuf)
        out = {}
        out.update(block_slice(ma, 0, n))
        for k, v in block_slice(mb, 0, n).items():
            out[k if k not in out else f"{k}_1"] = v
        yield out
        abuf = [block_slice(ma, n, na)] if na > n else []
        bbuf = [block_slice(mb, n, nb)] if nb > n else []
