"""Datasources: creation + IO (reference: python/ray/data/read_api.py,
data/datasource/). Files become one source block-fn per file/fragment so
reads stream lazily into the pipeline."""

from __future__ import annotations

import glob as _glob
import json as _json
import os
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ray_tpu.data.block import (Block, block_from_arrow, block_from_items,
                                block_from_rows, block_num_rows,
                                block_slice, block_to_arrow)
from ray_tpu.data.dataset import Dataset, _Op


def _source_ds(name: str, **args) -> Dataset:
    return Dataset([_Op(name, "source", None, args)])


def from_blocks(blocks: List[Block]) -> Dataset:
    return _source_ds("from_blocks", blocks=blocks)


def from_items(items: Sequence[Any], *,
               block_size: int = 4096) -> Dataset:
    import builtins
    items = list(items)
    blocks = [block_from_items(items[i:i + block_size])
              for i in builtins.range(0, max(len(items), 1), block_size)]
    return _source_ds("from_items", blocks=blocks)


def range(n: int, *, block_size: int = 65536) -> Dataset:  # noqa: A001
    import builtins
    fns = []
    for start in builtins.range(0, n, block_size):
        end = min(start + block_size, n)
        fns.append(lambda s=start, e=end: {"id": np.arange(s, e)})
    return _source_ds("range", block_fns=fns)


def from_numpy(arr, column: str = "data") -> Dataset:
    """A single ndarray (one column) or a dict of same-length ndarrays."""
    if isinstance(arr, dict):
        return _source_ds("from_numpy",
                          blocks=[{k: np.asarray(v)
                                   for k, v in arr.items()}])
    return _source_ds("from_numpy", blocks=[{column: np.asarray(arr)}])


def from_pandas(df) -> Dataset:
    return _source_ds("from_pandas",
                      blocks=[{c: df[c].to_numpy() for c in df.columns}])


def _expand(paths) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if not f.startswith(".")))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(_glob.glob(p)))
        else:
            out.append(p)
    return out


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    """(reference: read_api.py:943 read_parquet). The source op is
    DECLARATIVE (paths + columns, files opened at execution) so the
    plan optimizer can push a later select_columns into the scan —
    parquet then reads only the projected columns off disk."""
    return _source_ds("read_parquet", parquet_paths=_expand(paths),
                      columns=list(columns) if columns is not None
                      else None)


def read_csv(paths, **read_kwargs) -> Dataset:
    import pyarrow.csv as pacsv
    files = _expand(paths)

    def make(path):
        def fn():
            return block_from_arrow(pacsv.read_csv(path))
        return fn
    return _source_ds("read_csv", block_fns=[make(p) for p in files])


def read_json(paths) -> Dataset:
    files = _expand(paths)

    def make(path):
        def fn():
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(_json.loads(line))
            return block_from_rows(rows)
        return fn
    return _source_ds("read_json", block_fns=[make(p) for p in files])


def read_text(paths) -> Dataset:
    files = _expand(paths)

    def make(path):
        def fn():
            with open(path) as f:
                lines = [ln.rstrip("\n") for ln in f]
            return {"text": np.asarray(lines, dtype=object)}
        return fn
    return _source_ds("read_text", block_fns=[make(p) for p in files])


def read_tfrecord(paths, *, verify_crc: bool = True) -> Dataset:
    """TFRecord files of tf.train.Example protos, one block per file —
    WITHOUT TensorFlow (native framing + proto codec, data/tfrecord.py;
    reference capability: data/read_api.py read_tfrecords)."""
    from ray_tpu.data import tfrecord as tfr
    files = _expand(paths)

    def make(path):
        def fn():
            rows = [tfr.decode_example(rec)
                    for rec in tfr.read_records(
                        path, verify_crc=verify_crc)]
            return tfr.rows_to_block(rows)
        return fn
    return _source_ds("read_tfrecord",
                      block_fns=[make(p) for p in files])


def _expand_files(paths) -> List[str]:
    """Like _expand but RECURSES into directories (class-subfolder
    image layouts: data/cat/x.png) and never returns a directory."""
    out: List[str] = []
    for p in _expand(paths):
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                # prune hidden dirs (.git, .ipynb_checkpoints) like the
                # top-level dot filter
                dirs[:] = [d for d in dirs if not d.startswith(".")]
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if not f.startswith("."))
        else:
            out.append(p)
    return sorted(out)


def read_binary_files(paths, *, include_paths: bool = False) -> Dataset:
    """One row per file: {"bytes": ...} (+ "path") — the multimodal
    ingest workhorse (reference: read_api.py:2375 read_binary_files).
    Directories are walked recursively."""
    files = _expand_files(paths)

    def make(path):
        def fn():
            with open(path, "rb") as f:
                data = f.read()
            b: Block = {"bytes": np.asarray([data], dtype=object)}
            if include_paths:
                b["path"] = np.asarray([path], dtype=object)
            return b
        return fn
    return _source_ds("read_binary_files",
                      block_fns=[make(p) for p in files])


def read_images(paths, *, size: Optional[tuple] = None,
                mode: Optional[str] = None,
                include_paths: bool = False) -> Dataset:
    """Image files -> {"image": (1, H, W, C) uint8} rows (reference:
    read_api.py:1134 read_images; PIL decodes — optional dependency,
    gated with a clear error). Pass ``size=(H, W)`` to resize on read
    (required if downstream batching concatenates across images of
    different shapes), ``mode`` (e.g. "RGB"/"L") to convert.
    Directories are walked recursively (class-subfolder layouts)."""
    files = _expand_files(paths)

    def make(path):
        def fn():
            try:
                from PIL import Image
            except ImportError as e:  # pragma: no cover - env-specific
                raise RuntimeError(
                    "read_images needs pillow (PIL); install it or use "
                    "read_binary_files + your own decoder") from e
            img = Image.open(path)
            if mode is not None:
                img = img.convert(mode)
            if size is not None:
                img = img.resize((size[1], size[0]))  # PIL takes (W, H)
            arr = np.asarray(img)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            b: Block = {"image": arr[None, ...]}
            if include_paths:
                b["path"] = np.asarray([path], dtype=object)
            return b
        return fn
    return _source_ds("read_images", block_fns=[make(p) for p in files])


def read_webdataset(paths, *, include_keys: bool = False,
                    columns: Optional[List[str]] = None) -> Dataset:
    """WebDataset-style tar shards: members grouped by their path minus
    extension (WebDataset semantics: ``a/0001.jpg`` + ``a/0001.cls``
    form sample ``a/0001``), one ROW per sample with one column per
    extension (reference: read_api.py read_webdataset — there via the
    webdataset package; here a stdlib tarfile codec). Decode with
    map/map_batches (e.g. PIL for images, int(...) for labels).

    One block per shard; directories walk recursively but only
    ``.tar``/``.tar.gz``/``.tgz`` members are read (published sets ship
    index/README sidecars). Shards with DIFFERING extension sets yield
    ragged schemas — pass ``columns`` to pin the schema (missing
    payloads become None) when shards are heterogeneous."""
    import tarfile
    files = [p for p in _expand_files(paths)
             if p.endswith((".tar", ".tar.gz", ".tgz"))]

    def make(path):
        def fn():
            rows = []
            cur_key, cur = None, {}
            with tarfile.open(path) as tf:
                for m in tf:
                    if not m.isfile():
                        continue
                    dirpart, base = os.path.split(m.name)
                    if "." not in base:
                        continue
                    stem, ext = base.split(".", 1)
                    key = os.path.join(dirpart, stem) if dirpart else stem
                    if key != cur_key:
                        if cur:
                            rows.append(cur)
                        cur_key, cur = key, {}
                        if include_keys:
                            cur["__key__"] = key
                    cur[ext] = tf.extractfile(m).read()
                if cur:
                    rows.append(cur)
            keys = (list(columns) + (["__key__"] if include_keys else [])
                    if columns is not None
                    else sorted({k for r in rows for k in r}))
            # object-dtype columns: numpy's S dtype silently strips
            # trailing NUL bytes from binary payloads
            return {k: np.asarray([r.get(k) for r in rows],
                                  dtype=object)
                    for k in keys}
        return fn
    return _source_ds("read_webdataset",
                      block_fns=[make(p) for p in files])


def read_sql(sql: str, connection_factory: Callable[[], Any], *,
             block_size: int = 4096) -> Dataset:
    """Rows of a SQL query as blocks (reference: read_api.py read_sql —
    there over any DBAPI connection; same contract here:
    ``connection_factory`` returns a DBAPI2 connection, e.g.
    ``lambda: sqlite3.connect(path)``). The query runs lazily at
    execution; results stream in ``block_size``-row blocks."""
    def gen():
        conn = connection_factory()
        try:
            cur = conn.cursor()
            cur.execute(sql)
            cols = [d[0] for d in cur.description]
            while True:
                rows = cur.fetchmany(block_size)
                if not rows:
                    break
                yield {c: np.asarray([r[i] for r in rows])
                       for i, c in enumerate(cols)}
        finally:
            conn.close()

    # the source executor accepts callables returning block iterators
    return _source_ds("read_sql", block_fns=[gen])


def read_numpy(paths) -> Dataset:
    files = _expand(paths)

    def make(path):
        def fn():
            return {"data": np.load(path)}
        return fn
    return _source_ds("read_numpy", block_fns=[make(p) for p in files])


# --- writes -----------------------------------------------------------------

def write_parquet(ds: Dataset, path: str) -> None:
    import pyarrow.parquet as pq
    os.makedirs(path, exist_ok=True)
    for i, b in enumerate(ds.iter_blocks()):
        if block_num_rows(b):
            pq.write_table(block_to_arrow(b),
                           os.path.join(path, f"part-{i:05d}.parquet"))


def write_csv(ds: Dataset, path: str) -> None:
    import pyarrow.csv as pacsv
    os.makedirs(path, exist_ok=True)
    for i, b in enumerate(ds.iter_blocks()):
        if block_num_rows(b):
            pacsv.write_csv(block_to_arrow(b),
                            os.path.join(path, f"part-{i:05d}.csv"))


def write_tfrecord(ds: Dataset, path: str) -> None:
    """One TFRecord file of tf.train.Example protos per block —
    readable by TF input pipelines (masked-crc32c framing)."""
    from ray_tpu.data import tfrecord as tfr
    from ray_tpu.data.block import block_rows
    os.makedirs(path, exist_ok=True)
    for i, b in enumerate(ds.iter_blocks()):
        if block_num_rows(b):
            tfr.write_records(
                os.path.join(path, f"part-{i:05d}.tfrecord"),
                (tfr.encode_example(r) for r in block_rows(b)))


def write_json(ds: Dataset, path: str) -> None:
    os.makedirs(path, exist_ok=True)
    from ray_tpu.data.block import block_rows
    for i, b in enumerate(ds.iter_blocks()):
        if block_num_rows(b):
            with open(os.path.join(path, f"part-{i:05d}.jsonl"), "w") as f:
                for r in block_rows(b):
                    f.write(_json.dumps(
                        {k: (v.tolist() if isinstance(v, np.ndarray)
                             else v.item() if isinstance(v, np.generic)
                             else v) for k, v in r.items()}) + "\n")
