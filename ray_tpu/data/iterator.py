"""DataIterator: batch iteration with framework conversion + device staging.

Reference: python/ray/data/iterator.py (iter_batches, iter_torch_batches at
:309). TPU-first addition: iter_jax_batches stages host numpy batches onto
devices with jax.device_put — optionally double-buffered so host→HBM copy
overlaps the previous step's compute (the usual input-pipeline trick the
scaling book prescribes).
"""

from __future__ import annotations

from typing import Callable, Dict, Iterator, Optional

import numpy as np

from ray_tpu.data.block import Block, block_num_rows, block_to_pandas
from ray_tpu.data.dataset import _rebatch


class DataIterator:
    def __init__(self, block_gen: Callable[[], Iterator[Block]]):
        self._block_gen = block_gen

    def iter_blocks(self) -> Iterator[Block]:
        return self._block_gen()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     drop_last: bool = False):
        for b in _rebatch(self._block_gen(), batch_size):
            if drop_last and block_num_rows(b) < batch_size:
                continue
            if batch_format == "pandas":
                yield block_to_pandas(b)
            elif batch_format == "rows":
                from ray_tpu.data.block import block_rows
                yield list(block_rows(b))
            else:
                yield b

    def iter_torch_batches(self, *, batch_size: int = 256,
                           drop_last: bool = False,
                           dtypes: Optional[dict] = None):
        import torch
        for b in self.iter_batches(batch_size=batch_size,
                                   drop_last=drop_last):
            out = {}
            for k, v in b.items():
                t = torch.from_numpy(np.ascontiguousarray(v))
                if dtypes and k in dtypes:
                    t = t.to(dtypes[k])
                out[k] = t
            yield out

    def iter_jax_batches(self, *, batch_size: int = 256,
                         drop_last: bool = True,
                         sharding=None,
                         prefetch: int = 1):
        """Device-resident batches. With prefetch>=1, the NEXT batch's
        device_put is issued before the current one is yielded, so the
        host->device copy overlaps downstream compute."""
        import jax

        def put(b):
            if sharding is not None:
                return {k: jax.device_put(v, sharding)
                        for k, v in b.items()}
            return {k: jax.device_put(v) for k, v in b.items()}

        it = self.iter_batches(batch_size=batch_size, drop_last=drop_last)
        buf = []
        for b in it:
            buf.append(put(b))
            if len(buf) > max(prefetch, 0):
                yield buf.pop(0)
        yield from buf

    def materialize(self):
        from ray_tpu.data.dataset import Dataset, _Op
        blocks = [b for b in self._block_gen() if block_num_rows(b)]
        return Dataset([_Op("from_blocks", "source", None,
                            {"blocks": blocks})])

    def count(self) -> int:
        return sum(block_num_rows(b) for b in self._block_gen())
