"""data.llm: batch LLM inference over Datasets.

Analog of the reference's `ray.data.llm` (reference:
python/ray/llm/_internal/batch/processor/* build_llm_processor — a
vLLM-backed stage in a data pipeline): prompts stream through shared
continuous-batching engine actors (ray_tpu.llm), so a Dataset map stage
gets the same token-level batching the online path has. Engines are
long-lived actors shared across all map tasks — model weights load once
per replica, not once per block.

    from ray_tpu.serve.llm import LLMConfig
    from ray_tpu.data.llm import build_llm_processor
    proc = build_llm_processor(LLMConfig(model="tiny"), concurrency=2,
                               max_new_tokens=32)
    out_ds = proc(ds)   # adds a "generated_tokens" column
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


class _EngineActor:
    """One LLM engine behind an actor; map tasks call generate_many."""

    def __init__(self, cfg):
        from ray_tpu.serve.llm import _LLMServer
        self._server = _LLMServer(cfg)

    async def generate_many(self, prompts, max_new_tokens: int,
                            temperature: float, eos_id):
        import asyncio
        outs = await asyncio.gather(*[
            self._server.engine.generate(
                list(map(int, p)), max_new_tokens=max_new_tokens,
                temperature=temperature, eos_id=eos_id)
            for p in prompts])
        return [o["tokens"] for o in outs]


def build_llm_processor(cfg, *, input_column: str = "tokens",
                        output_column: str = "generated_tokens",
                        max_new_tokens: int = 64,
                        temperature: float = 0.0,
                        eos_id: Optional[int] = None,
                        concurrency: int = 1,
                        batch_size: Optional[int] = 64,
                        engine_options: Optional[dict] = None
                        ) -> Callable:
    """Returns Dataset -> Dataset adding `output_column` (object array of
    token-id lists). `concurrency` = engine replicas (model copies)."""
    import ray_tpu

    engines = [
        ray_tpu.remote(_EngineActor).options(
            max_concurrency=64, **(engine_options or {})).remote(cfg)
        for _ in range(concurrency)]

    def infer(batch: dict) -> dict:
        prompts = [list(map(int, np.asarray(p).tolist()))
                   for p in batch[input_column]]
        # Shard the batch's prompts ACROSS all engine replicas so they
        # run concurrently (the map stage itself is sequential per
        # batch; intra-batch sharding is where replica parallelism
        # comes from), then reassemble in order.
        shards = np.array_split(np.arange(len(prompts)), len(engines))
        refs, order = [], []
        for eng, idx in zip(engines, shards):
            if len(idx) == 0:
                continue
            refs.append(eng.generate_many.remote(
                [prompts[i] for i in idx], max_new_tokens,
                temperature, eos_id))
            order.append(idx)
        toks = [None] * len(prompts)
        for idx, part in zip(order, ray_tpu.get(refs, timeout=3600)):
            for i, t in zip(idx, part):
                toks[i] = t
        out = dict(batch)
        out[output_column] = np.array([np.array(t, np.int32)
                                       for t in toks], dtype=object)
        return out

    def apply(ds):
        return ds.map_batches(infer, batch_size=batch_size)

    apply.engines = engines  # exposed so callers can kill them
    return apply
