"""Distributed all-to-all: hash/range/random shuffle over runtime tasks.

The analog of the reference's all-to-all execution
(python/ray/data/_internal/execution/operators/hash_shuffle.py and
planner/exchange/*: map tasks partition each input block, reduce tasks
merge one partition each). Blocks move through the shared-memory object
plane — the driver only ever holds block *refs* plus the single block it
is currently streaming to the consumer, never the whole dataset.

Phases:
  1. collect: stream input blocks into the object store (one at a time).
  2. (sort only) sample: each block contributes a key sample; the driver
     computes range boundaries from the union of samples.
  3. map: one `_partition` task per input block -> n_out sub-blocks.
  4. reduce: one `_merge` task per output partition; intermediate refs are
     freed as soon as their partition is reduced.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import (Block, block_concat, block_num_rows,
                                block_slice, block_take, object_array)

# Partitions per shuffle: bounded so n_in x n_out ref fan-out stays sane.
MAX_PARTITIONS = 64


def _spec_partition(block: Block, n_out: int, spec: dict) -> List[Block]:
    """Split one block into n_out sub-blocks per the shuffle spec. Runs
    inside a worker task."""
    total = block_num_rows(block)
    mode = spec["mode"]
    if mode == "shuffle":
        rng = np.random.default_rng(spec.get("seed"))
        part = rng.integers(0, n_out, size=total)
    elif mode == "hash":
        key = np.asarray(block[spec["key"]])
        if key.dtype.kind in "OUS":
            # Stable cross-process hash: Python's hash() is salted per
            # process, which would scatter one key across partitions.
            import zlib
            part = np.asarray(
                [zlib.crc32(str(k).encode()) % n_out for k in key],
                dtype=np.int64)
        else:
            part = (key.astype(np.int64, copy=False) % n_out + n_out) % n_out
    elif mode == "range":
        key = np.asarray(block[spec["key"]])
        part = np.searchsorted(spec["bounds"], key, side="right")
    elif mode == "split":
        per = max(1, -(-total // n_out))
        part = np.minimum(np.arange(total) // per, n_out - 1)
    else:
        raise ValueError(mode)
    out = []
    for j in range(n_out):
        idx = np.nonzero(part == j)[0]
        # empty partitions keep their COLUMNS (zero-row block): the join
        # needs the right-side schema in right-empty partitions
        out.append(block_take(block, idx))
    # num_returns=1 stores the return value as ONE object — return the
    # bare block so the merge task doesn't see a single-element list.
    return out[0] if n_out == 1 else out


def _spec_merge(spec: dict, *parts: Block) -> Block:
    """Merge one partition's sub-blocks into a final block. Runs inside a
    worker task."""
    parts = [p for p in parts if block_num_rows(p)]
    if not parts:
        return {}
    merged = block_concat(list(parts))
    mode = spec["mode"]
    if mode == "shuffle":
        rng = np.random.default_rng(spec.get("seed"))
        return block_take(merged, rng.permutation(block_num_rows(merged)))
    if mode == "range":
        idx = np.argsort(merged[spec["key"]], kind="stable")
        return block_take(merged, idx)
    if mode == "hash" and spec.get("aggs"):
        return _aggregate(merged, spec["key"], spec["aggs"])
    return merged


def _aggregate(block: Block, key: str,
               aggs: Dict[str, Tuple[str, Callable]]) -> Block:
    keys = np.asarray(block[key])
    order = np.argsort(keys, kind="stable")
    keys_sorted = keys[order]
    uniq, starts = np.unique(keys_sorted, return_index=True)
    out_rows: Dict[str, list] = {key: list(uniq)}
    for out_name in aggs:
        out_rows[out_name] = []
    bounds = list(starts) + [len(keys_sorted)]
    for g in range(len(uniq)):
        sel = order[bounds[g]:bounds[g + 1]]
        for out_name, (col, fn) in aggs.items():
            out_rows[out_name].append(fn(np.asarray(block[col])[sel]))
    return {k: np.asarray(v) for k, v in out_rows.items()}


def distributed_all2all(stream: Iterator[Block],
                        spec: dict,
                        n_out: Optional[int] = None) -> Iterator[Block]:
    """Run the shuffle across the cluster; yields output blocks one at a
    time (cites reference shape: hash_shuffle.py HashShuffleOperator)."""
    import ray_tpu

    in_refs = []
    for b in stream:
        if block_num_rows(b):
            in_refs.append(ray_tpu.put(b))
    if not in_refs:
        return
    if n_out is None:
        n_out = min(max(1, len(in_refs)), MAX_PARTITIONS)

    if spec["mode"] == "range":
        spec = dict(spec)
        spec["bounds"] = _sample_bounds(in_refs, spec, n_out)

    cols = _fan_cols(in_refs, n_out, spec)
    merge_fn = ray_tpu.remote(_spec_merge)
    out_refs = [merge_fn.remote(spec, *col) for col in cols]
    # Stream the reduced partitions; free inputs after the first merge
    # lands (all maps have resolved their args by then) and each
    # partition's intermediates as soon as it is consumed.
    first = True
    descending = spec.get("descending", False)
    order = range(n_out - 1, -1, -1) if descending else range(n_out)
    for j in order:
        out = ray_tpu.get(out_refs[j], timeout=600)
        # get() returns zero-copy views into the shared store; copy before
        # freeing or the arena range gets recycled under the caller.
        out = {k: np.array(v) for k, v in out.items()}
        if first:
            ray_tpu.free(in_refs)
            first = False
        ray_tpu.free(cols[j] + [out_refs[j]])
        if block_num_rows(out):
            if descending:
                out = block_take(
                    out, np.arange(block_num_rows(out) - 1, -1, -1))
            yield out


def _fan_cols(in_refs, n_out: int, spec: dict):
    """Map phase: one _spec_partition task per input block; returns the
    transposed [partition][input] ref grid (shared by shuffle and join —
    the free/zero-copy protocol must stay identical in both)."""
    import ray_tpu
    part_fn = ray_tpu.remote(_spec_partition).options(num_returns=n_out)
    rows = []
    for ref in in_refs:
        r = part_fn.remote(ref, n_out, spec)
        rows.append([r] if n_out == 1 else r)  # bare ref when 1 return
    return [[rows[i][j] for i in range(len(rows))] for j in range(n_out)]


def _sample_bounds(in_refs, spec: dict, n_out: int) -> np.ndarray:
    """Range-partition boundaries from per-block samples (reference:
    planner/exchange/sort_task_spec.py SortTaskSpec.sample_boundaries)."""
    import ray_tpu

    key = spec["key"]

    def _sample(block, k=64):
        vals = np.asarray(block[key])
        if len(vals) > k:
            idx = np.random.default_rng(0).choice(
                len(vals), size=k, replace=False)
            vals = vals[idx]
        return vals

    sample_fn = ray_tpu.remote(_sample)
    samples = ray_tpu.get([sample_fn.remote(r) for r in in_refs],
                          timeout=300)
    allv = np.sort(np.concatenate([s for s in samples if len(s)]))
    # n_out == 1 needs NO boundaries — np.clip([]) yields a FLOAT empty
    # array that then faults as an index
    if n_out <= 1:
        return allv[:0]
    qs = np.asarray(
        [int(len(allv) * (j + 1) / n_out) for j in range(n_out - 1)],
        dtype=np.int64)
    return allv[np.clip(qs, 0, len(allv) - 1)]


def distributed_groupby(stream: Iterator[Block], key: str,
                        aggs: Dict[str, Tuple[str, Callable]]
                        ) -> Iterator[Block]:
    """Hash-partition by key, aggregate per partition (all rows of one key
    land in one partition, so per-partition aggregation is exact)."""
    spec = {"mode": "hash", "key": key, "aggs": aggs}
    yield from distributed_all2all(stream, spec)


# --- join ------------------------------------------------------------------
# Reference: python/ray/data/_internal/execution/operators/join.py (hash
# join: both sides hash-partitioned by key, each output partition joined
# independently — all rows of one key land in the same partition pair).

def join_blocks(lb: Optional[Block], rb: Optional[Block], key: str,
                join_type: str, suffix: str) -> Block:
    """Join two (already co-partitioned) blocks on `key`. inner / left;
    left-join fills missing right numerics with NaN and everything else
    with None (object dtype). NOTE: when any left row is unmatched, right
    int/uint/bool columns are promoted to float64 so NaN can represent
    the nulls (numpy has no nullable ints) — same promotion pandas
    applies on a left merge."""
    if lb is None or not block_num_rows(lb):
        return {}
    # a right block with columns but zero rows still contributes SCHEMA:
    # a left join must emit its columns (as nulls) in every partition
    have_right = rb is not None and len(rb) > 0
    r_rows = block_num_rows(rb) if have_right else 0
    keys_l = np.asarray(lb[key])
    ridx: Dict[Any, List[int]] = {}
    if r_rows:
        for i, k in enumerate(np.asarray(rb[key]).tolist()):
            ridx.setdefault(k, []).append(i)
    li: List[int] = []
    ri: List[int] = []
    for i, k in enumerate(keys_l.tolist()):
        matches = ridx.get(k)
        if matches:
            for j in matches:
                li.append(i)
                ri.append(j)
        elif join_type == "left":
            li.append(i)
            ri.append(-1)           # null marker
    if not li:
        return {}
    out = dict(block_take(lb, np.asarray(li, np.int64)))
    if have_right:
        rtake = np.asarray([j if j >= 0 else 0 for j in ri], np.int64)
        nulls = np.asarray([j < 0 for j in ri])
        for col, vals in rb.items():
            if col == key:
                continue
            name = col
            while name in out:   # keep suffixing until unique — a right
                name += suffix   # column named f"{col}{suffix}" must not
                                 # be silently overwritten
            if r_rows:
                v = np.asarray(block_take({col: vals}, rtake)[col])
                if nulls.any():
                    if v.dtype.kind in "fiub" and v.ndim == 1:
                        v = v.astype(np.float64)
                        v[nulls] = np.nan
                    else:
                        # strings, object/ragged AND multi-dim tensor
                        # columns: numpy cannot represent a missing
                        # row densely — demote to object rows with
                        # None (np.resize would silently FLATTEN a
                        # [n,d] tensor column across rows)
                        v = object_array(list(v))
                        v[nulls] = None
            else:  # zero-row right partition: every match is null
                proto = np.asarray(vals)
                if proto.dtype.kind in "fiub" and proto.ndim == 1:
                    v = np.full(len(li), np.nan)
                else:
                    v = np.empty(len(li), dtype=object)   # all None
            out[name] = v
    return out


def _join_partition(key: str, join_type: str, suffix: str, n_left: int,
                    r_schema: Optional[Dict[str, Any]],
                    *parts: Block) -> Block:
    """One output partition: concat this partition's left and right
    sub-blocks, join them. Runs inside a worker task. `r_schema`
    ({col: (dtype, ndim)}) is the right side's schema, threaded through
    so a left join emits the right columns (as nulls) even in
    partitions — or whole joins — where the right side has no rows at
    all. ndim matters: a 2-D tensor column's nulls must be None (object
    rows), not NaN, and a zero-row 1-D reconstruction would lose
    that."""
    left = [p for p in parts[:n_left] if block_num_rows(p)]
    # keep zero-row right parts: they carry the right-side SCHEMA, which
    # a left join needs to emit null columns in right-empty partitions
    right = [p for p in parts[n_left:] if len(p) > 0]
    nonempty_r = [p for p in right if block_num_rows(p)]
    lb = block_concat(left) if left else None
    rb = block_concat(nonempty_r) if nonempty_r else (
        right[0] if right else None)
    if rb is None and r_schema:
        rb = {c: np.empty((0,) * max(nd, 1), dtype=dt)
              for c, (dt, nd) in r_schema.items()}
    return join_blocks(lb, rb, key, join_type, suffix)


def distributed_join(left: Iterator[Block], right: Iterator[Block],
                     key: str, join_type: str = "inner",
                     suffix: str = "_r") -> Iterator[Block]:
    """Hash join across the cluster: both sides partitioned by key, one
    join task per partition, outputs streamed."""
    import ray_tpu

    l_refs = [ray_tpu.put(b) for b in left if block_num_rows(b)]
    r_refs = []
    r_schema = None   # first right block's {col: (dtype, ndim)}
    for b in right:
        if r_schema is None and len(b) > 0:
            r_schema = {c: (np.asarray(v).dtype, np.asarray(v).ndim)
                        for c, v in b.items()}
        if block_num_rows(b):
            r_refs.append(ray_tpu.put(b))
    if not l_refs:
        ray_tpu.free(r_refs)   # nothing to join; don't pin the right side
        return
    n_out = min(max(1, len(l_refs) + len(r_refs)), MAX_PARTITIONS)
    spec = {"mode": "hash", "key": key}
    l_cols = _fan_cols(l_refs, n_out, spec)
    r_cols = _fan_cols(r_refs, n_out, spec) if r_refs \
        else [[] for _ in range(n_out)]
    join_fn = ray_tpu.remote(_join_partition)
    out_refs = []
    cols = []
    for j in range(n_out):
        cols.append(l_cols[j] + r_cols[j])
        out_refs.append(join_fn.remote(key, join_type, suffix,
                                       len(l_cols[j]), r_schema,
                                       *cols[-1]))
    first = True
    for j in range(n_out):
        out = ray_tpu.get(out_refs[j], timeout=600)
        out = {k: np.array(v) for k, v in out.items()}
        if first:
            ray_tpu.free(l_refs + r_refs)
            first = False
        ray_tpu.free(cols[j] + [out_refs[j]])
        if block_num_rows(out):
            yield out
