"""TFRecord IO without TensorFlow.

Reference capability: python/ray/data/read_api.py read_tfrecord /
datasource/tfrecords_datasource.py (which imports TF or pyarrow's
codec). Neither ships in this image, and neither is needed: a TFRecord
file is length-prefixed framing (u64 length + masked-crc32c of the
length + payload + masked-crc32c of the payload), and the payloads are
``tf.train.Example`` protos — three nested messages over five wire
types. Both are implemented here directly, so TFRecord datasets written
by TF pipelines read straight into Dataset blocks and vice versa.

Feature mapping per Example (column-oriented on the block side):
int64_list -> np.int64, float_list -> np.float32, bytes_list -> object
(bytes). Single-element lists flatten to scalars; multi-element lists
stay as per-row arrays (object column).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, Iterator, List

import numpy as np

# --- crc32c (Castagnoli), table-driven; masked per the TFRecord spec --

_POLY = 0x82F63B78
_T = [[0] * 256 for _ in range(8)]
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ _POLY if _c & 1 else _c >> 1
    _T[0][_i] = _c
for _i in range(256):
    _c = _T[0][_i]
    for _k in range(1, 8):
        _c = _T[0][_c & 0xFF] ^ (_c >> 8)
        _T[_k][_i] = _c

try:                      # native wheel when the environment has one
    import crc32c as _crc32c_native
except ImportError:
    _crc32c_native = None


def _crc32c(data: bytes) -> int:
    if _crc32c_native is not None:
        return _crc32c_native.crc32c(data)
    # slice-by-8: one loop iteration per 8 bytes instead of per byte —
    # a per-byte pure-python CRC otherwise dominates TFRecord IO
    crc = 0xFFFFFFFF
    t0, t1, t2, t3, t4, t5, t6, t7 = _T
    n8 = len(data) - (len(data) % 8)
    i = 0
    while i < n8:
        crc ^= int.from_bytes(data[i:i + 4], "little")
        hi = int.from_bytes(data[i + 4:i + 8], "little")
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF]
               ^ t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF]
               ^ t3[hi & 0xFF] ^ t2[(hi >> 8) & 0xFF]
               ^ t1[(hi >> 16) & 0xFF] ^ t0[(hi >> 24) & 0xFF])
        i += 8
    for b in data[n8:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# --- protobuf wire helpers -------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: memoryview, off: int):
    shift = n = 0
    while True:
        b = buf[off]
        off += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, off
        shift += 7


def _ld(field: int, payload: bytes) -> bytes:
    """length-delimited field"""
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


def _fields(buf: memoryview) -> Iterator[tuple]:
    """(field_number, wire_type, value) over one message."""
    off = 0
    while off < len(buf):
        key, off = _read_varint(buf, off)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, off = _read_varint(buf, off)
        elif wt == 2:
            ln, off = _read_varint(buf, off)
            v = buf[off:off + ln]
            off += ln
        elif wt == 5:
            v = bytes(buf[off:off + 4])
            off += 4
        elif wt == 1:
            v = bytes(buf[off:off + 8])
            off += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


# --- tf.train.Example ------------------------------------------------

def encode_example(row: Dict[str, Any]) -> bytes:
    feats = bytearray()
    for name, value in row.items():
        if isinstance(value, np.ndarray) and value.ndim == 0:
            value = value.item()
        values = value if isinstance(value, (list, tuple, np.ndarray)) \
            else [value]
        if len(values):
            first = values[0]
        elif isinstance(value, np.ndarray):
            # EMPTY array: keep the feature KIND from the dtype so a
            # fixed-schema TF parser downstream doesn't see a kind flip
            first = (b"" if value.dtype.kind in "SUO"
                     else 0.0 if value.dtype.kind == "f" else 0)
        else:
            first = 0    # empty plain list: int64_list by convention
        if isinstance(first, (bytes, str)):
            payload = b"".join(
                _ld(1, v.encode() if isinstance(v, str) else bytes(v))
                for v in values)
            feature = _ld(1, payload)                 # bytes_list
        elif isinstance(first, (float, np.floating)):
            packed = struct.pack(f"<{len(values)}f",
                                 *[float(v) for v in values])
            feature = _ld(2, _ld(1, packed))          # float_list
        else:
            packed = b"".join(_varint(int(v) & 0xFFFFFFFFFFFFFFFF)
                              for v in values)
            feature = _ld(3, _ld(1, packed))          # int64_list
        entry = _ld(1, name.encode()) + _ld(2, feature)
        feats += _ld(1, entry)                        # map entry
    return _ld(1, bytes(feats))                       # Example.features


def decode_example(data) -> Dict[str, Any]:
    row: Dict[str, Any] = {}
    for f, _wt, features in _fields(memoryview(data)):
        if f != 1:
            continue
        for f2, _w2, entry in _fields(features):
            if f2 != 1:
                continue
            name, feature = None, None
            for f3, _w3, v3 in _fields(entry):
                if f3 == 1:
                    name = bytes(v3).decode()
                elif f3 == 2:
                    feature = v3
            if name is None or feature is None:
                continue
            row[name] = _decode_feature(feature)
    return row


def _decode_feature(feature: memoryview):
    for kind, _wt, body in _fields(feature):
        if kind == 1:      # bytes_list
            return [bytes(v) for f, _w, v in _fields(body) if f == 1]
        if kind == 2:      # float_list (packed or repeated)
            vals: List[float] = []
            for f, wt, v in _fields(body):
                if f != 1:
                    continue
                if wt == 2:
                    vals += list(np.frombuffer(v, "<f4"))
                else:
                    vals.append(struct.unpack("<f", v)[0])
            return vals
        if kind == 3:      # int64_list (packed or repeated)
            vals = []
            for f, wt, v in _fields(body):
                if f != 1:
                    continue
                if wt == 2:
                    off = 0
                    while off < len(v):
                        n, off = _read_varint(v, off)
                        if n >= 1 << 63:
                            n -= 1 << 64
                        vals.append(n)
                else:
                    if v >= 1 << 63:
                        v -= 1 << 64
                    vals.append(v)
            return vals
    return []


# --- record framing ---------------------------------------------------

def read_records(path: str, *, verify_crc: bool = True
                 ) -> Iterator[bytes]:
    with open(path, "rb") as f:
        while True:
            hdr = f.read(12)
            if len(hdr) < 12:
                return
            (n,) = struct.unpack("<Q", hdr[:8])
            if verify_crc:
                (crc,) = struct.unpack("<I", hdr[8:])
                if _masked_crc(hdr[:8]) != crc:
                    raise ValueError(f"{path}: corrupt length crc")
            data = f.read(n)
            if len(data) < n:
                raise ValueError(f"{path}: truncated record")
            trailer = f.read(4)
            if len(trailer) < 4:
                raise ValueError(f"{path}: truncated record trailer")
            (dcrc,) = struct.unpack("<I", trailer)
            if verify_crc and _masked_crc(data) != dcrc:
                raise ValueError(f"{path}: corrupt data crc")
            yield data


def write_records(path: str, records: Iterator[bytes]) -> int:
    n = 0
    with open(path, "wb") as f:
        for rec in records:
            hdr = struct.pack("<Q", len(rec))
            f.write(hdr)
            f.write(struct.pack("<I", _masked_crc(hdr)))
            f.write(rec)
            f.write(struct.pack("<I", _masked_crc(rec)))
            n += 1
    return n


# --- row <-> column glue ----------------------------------------------

def rows_to_block(rows: List[Dict[str, Any]]) -> Dict[str, np.ndarray]:
    """Decoded example rows -> a column block. A column whose every
    row has EXACTLY one value flattens to a typed scalar column;
    variable-length (or partially-missing) features — the normal case
    in TF datasets — stay an object column of per-row typed arrays."""
    cols: Dict[str, list] = {}
    for r in rows:
        for k in r:
            cols.setdefault(k, [])
    for r in rows:
        for k, vals in cols.items():
            vals.append(list(r.get(k, [])))
    out = {}
    for k, vals in cols.items():
        sample = next((v[0] for v in vals if v), None)
        if sample is None:
            out[k] = np.array([None] * len(vals), dtype=object)
            continue
        if isinstance(sample, (float, np.floating)):
            dt = np.float32
        elif isinstance(sample, bytes):
            dt = None
        else:
            dt = np.int64
        if all(len(v) == 1 for v in vals):
            flat = [v[0] for v in vals]
            out[k] = np.array(flat, dtype=object) if dt is None \
                else np.asarray(flat, dtype=dt)
        elif dt is None:
            out[k] = np.array(vals, dtype=object)
        else:
            col = np.empty(len(vals), dtype=object)
            for i, v in enumerate(vals):
                col[i] = np.asarray(v, dtype=dt)
            out[k] = col
    return out
