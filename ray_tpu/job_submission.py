"""Job submission SDK: run driver scripts on the cluster head.

Analog of the reference's job submission client (reference:
python/ray/dashboard/modules/job/sdk.py JobSubmissionClient,
job_manager.py:62) over the RPC plane instead of REST: submit a shell
entrypoint, poll status, fetch logs, stop.

    client = JobSubmissionClient("127.0.0.1:6379")
    sid = client.submit_job(entrypoint="python train.py",
                            runtime_env={"env_vars": {"MODE": "prod"}})
    client.wait_until_finish(sid)
    print(client.get_job_logs(sid))
"""

from __future__ import annotations

import time
from typing import Optional

from ray_tpu.runtime import rpc


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"
    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class JobSubmissionClient:
    def __init__(self, address: str):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._elt = rpc.EventLoopThread("ray_tpu_jobclient")
        self._pool = None

    def _call(self, method: str, **kw):
        async def go():
            global_pool = self._pool
            if global_pool is None:
                self._pool = global_pool = rpc.ConnectionPool()
            return await global_pool.call(self._addr, method,
                                          timeout=30.0, **kw)
        return self._elt.run(go())

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None) -> str:
        rt = None
        if runtime_env:
            from ray_tpu.runtime.runtime_env import validate
            rt = validate(runtime_env)
        r = self._call("submit_job", entrypoint=entrypoint,
                       submission_id=submission_id, runtime_env=rt)
        if not r.get("ok"):
            raise RuntimeError(r.get("error", "job submission failed"))
        return r["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        info = self._call("get_submitted_job", submission_id=submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info["status"]

    def get_job_info(self, submission_id: str) -> dict:
        info = self._call("get_submitted_job", submission_id=submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        return info

    def list_jobs(self) -> list:
        return self._call("list_submitted_jobs")

    def get_job_logs(self, submission_id: str) -> str:
        logs = self._call("submitted_job_logs",
                          submission_id=submission_id)
        if logs is None:
            raise ValueError(f"no job {submission_id!r}")
        return logs

    def stop_job(self, submission_id: str) -> bool:
        r = self._call("stop_submitted_job", submission_id=submission_id)
        return bool(r.get("ok"))

    def wait_until_finish(self, submission_id: str,
                          timeout: float = 300.0,
                          poll_s: float = 0.5) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            st = self.get_job_status(submission_id)
            if st in JobStatus.TERMINAL:
                return st
            time.sleep(poll_s)
        raise TimeoutError(
            f"job {submission_id!r} not finished after {timeout}s")

    def close(self):
        if self._pool is not None:
            pool = self._pool
            self._pool = None
            self._elt.run(pool.close())
        self._elt.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
