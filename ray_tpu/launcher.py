"""One-command cluster bring-up: ``ray-tpu up cluster.yaml``.

The analog of the reference's ``ray up`` (reference:
python/ray/autoscaler/_private/commands.py create_or_update_cluster):
one YAML describes the head, optional extra LOCAL nodes (dev boxes,
simulation), and optional CLOUD TPU slices; ``up`` boots the head,
joins the local nodes, and creates the slices with join startup
scripts; ``down`` deletes the slices and stops the local processes.

YAML shape::

    cluster_name: demo
    head:
      port: 6379            # optional (0 = ephemeral)
      num_cpus: 8           # optional resource overrides
      resources: {widget: 2}
      labels: {role: head}
    workers:                # optional local nodes joined to the head
      - num_cpus: 4
        labels: {zone: a}
    provider:               # optional TPU slices via queued resources
      type: gcp
      project: my-proj
      zone: us-central2-b
      pod_type: v5e-16
      slices: 2
      runtime_version: v2-alpha-tpuv5-lite

Cluster state (head address, node pids, slice handles) persists in the
session dir so ``down`` can find everything without the cloud being
queried first.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional


def _session_dir() -> str:
    from ray_tpu.scripts import session_dir
    return session_dir()


def _state_path(name: str) -> str:
    return os.path.join(_session_dir(), f"cluster-{name}.json")


def load_config(path: str) -> dict:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if not isinstance(cfg, dict):
        raise ValueError(f"{path}: cluster config must be a mapping")
    cfg.setdefault("cluster_name", "default")
    return cfg




def _slice_provider(cfg: dict, head_address: str, gcp_client=None):
    from ray_tpu.providers.gcp import GCPClient, TPUQueuedResourceProvider
    prov = cfg.get("provider") or {}
    if prov.get("type") != "gcp":
        raise ValueError(f"unknown provider type {prov.get('type')!r}")
    client = gcp_client or GCPClient(prov["project"], prov["zone"])
    return TPUQueuedResourceProvider(
        client, head_address,
        runtime_version=prov.get("runtime_version",
                                 "v2-alpha-tpuv5-lite"),
        default_pod_type=prov.get("pod_type", "v5e-8"),
        name_prefix=cfg.get("cluster_name", "ray-tpu"))


def up(cfg: dict, *, gcp_client=None) -> dict:
    """Boot the cluster described by ``cfg``; idempotent-ish: an
    existing state file for the name is an error (run ``down`` first).
    Returns the recorded state."""
    name = cfg["cluster_name"]
    sp = _state_path(name)
    if os.path.exists(sp):
        raise RuntimeError(
            f"cluster {name!r} already has state at {sp}; "
            "run `ray-tpu down` first")
    from ray_tpu.scripts import start_node
    head_cfg = cfg.get("head") or {}
    # Cloud slices must reach the head over the network: with a
    # provider section, loopback can't be the bind host.
    host = head_cfg.get("host", "127.0.0.1")
    if cfg.get("provider") and not head_cfg.get("host"):
        host = _routable_host()
    head = start_node(
        head=True, host=host, port=int(head_cfg.get("port", 0)),
        num_cpus=head_cfg.get("num_cpus"),
        resources=head_cfg.get("resources"),
        labels=head_cfg.get("labels"))
    state = {"cluster_name": name, "address": head["address"],
             "nodes": [head], "slice_handles": []}
    try:
        for w in cfg.get("workers") or []:
            state["nodes"].append(start_node(
                head=False, address=head["address"],
                num_cpus=w.get("num_cpus"),
                resources=w.get("resources"),
                labels=w.get("labels")))
        if cfg.get("provider"):
            provider = _slice_provider(cfg, head["address"], gcp_client)
            n_slices = int((cfg.get("provider") or {}).get("slices", 1))
            import asyncio
            for i in range(n_slices):
                handle = asyncio.run(provider.launch(
                    {}, {"slice_index": str(i)}))
                state["slice_handles"].append(handle)
    except BaseException as boot_err:
        # partial bring-up must not leak processes/slices; anything the
        # rollback could NOT clean (a slice whose delete failed) is
        # persisted so a later `down` can retry with its handle
        errors = _teardown(state, cfg, gcp_client=gcp_client)
        if state.get("slice_handles"):
            state["nodes"] = []
            os.makedirs(_session_dir(), exist_ok=True)
            with open(sp, "w") as f:
                json.dump(state, f, indent=2)
        if errors:
            raise RuntimeError(
                f"cluster bring-up failed ({boot_err}); rollback left: "
                + "; ".join(errors)) from boot_err
        raise
    os.makedirs(_session_dir(), exist_ok=True)
    with open(sp, "w") as f:
        json.dump(state, f, indent=2)
    return state


def _routable_host() -> str:
    """A non-loopback address cloud slices can dial. gethostname
    resolution is NOT enough (Debian maps it to 127.0.1.1); the
    UDP-connect trick reads the address of the default route. No
    routable address at all is a hard error — slices joining loopback
    would silently never form a cluster."""
    import socket
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("8.8.8.8", 80))   # no packets sent (UDP)
            addr = s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        addr = ""
    if not addr or addr.startswith("127."):
        raise ValueError(
            "cannot auto-detect a routable head address for cloud "
            "slices to join; set head.host in the cluster YAML")
    return addr


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except OSError:
        return False
    # our reaped-or-not children: a zombie counts as dead
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except OSError:
        return True


def _teardown(state: dict, cfg: Optional[dict],
              gcp_client=None) -> List[str]:
    errors: List[str] = []
    if state.get("slice_handles"):
        if not (cfg and cfg.get("provider")):
            # wiping handles we cannot terminate would orphan
            # still-billing slices — keep them and surface it
            errors.append(
                "state records cloud slices but the config has no "
                "provider section; restore it and re-run down")
        else:
            import asyncio
            provider = _slice_provider(cfg, state.get("address", ""),
                                       gcp_client)
            remaining: List[str] = []
            for h in state["slice_handles"]:
                try:
                    asyncio.run(provider.terminate(h))
                except Exception as e:  # noqa: BLE001 — keep going
                    errors.append(f"slice {h}: {e}")
                    remaining.append(h)
            state["slice_handles"] = remaining
    import signal
    nodes = list(reversed(state.get("nodes") or []))  # workers first
    for n in nodes:
        try:
            os.killpg(os.getpgid(n["pid"]), signal.SIGTERM)
        except (OSError, ProcessLookupError):
            pass  # already gone
    # Grace window: poll liveness (works whether or not the nodes are
    # OUR children — `down` usually runs in a different process than
    # `up`); reap children opportunistically so zombies don't read as
    # alive; escalate to SIGKILL past the window.
    deadline = time.monotonic() + 10.0
    pending = {n["pid"] for n in nodes}
    while pending and time.monotonic() < deadline:
        for pid in list(pending):
            try:
                os.waitpid(pid, os.WNOHANG)
            except ChildProcessError:
                pass                # not our child: liveness poll only
            if not _pid_alive(pid):
                pending.discard(pid)
        if pending:
            time.sleep(0.1)
    for pid in pending:
        try:
            os.killpg(os.getpgid(pid), signal.SIGKILL)
            errors.append(f"node pid {pid} ignored SIGTERM; killed")
        except (OSError, ProcessLookupError):
            pass
    # Drop the per-node session records: the rest of the CLI
    # (`ray-tpu status` default address, `stop`) trusts them, and a
    # dead cluster's files would point it at gone pids/ports.
    for n in nodes:
        f = n.get("info_file")
        if f:
            try:
                os.unlink(f)
            except OSError:
                pass
    return errors


def down(cfg: dict, *, gcp_client=None) -> List[str]:
    """Tear down a cluster previously brought up with ``up``. If any
    cloud slice could not be deleted, its handle is RE-persisted (the
    state file survives, holding only the survivors) so a later `down`
    can retry — losing the handle of a still-billing slice is worse
    than a leftover file."""
    name = cfg["cluster_name"]
    sp = _state_path(name)
    if not os.path.exists(sp):
        raise RuntimeError(f"no recorded state for cluster {name!r}")
    with open(sp) as f:
        state = json.load(f)
    errors = _teardown(state, cfg, gcp_client=gcp_client)
    if state.get("slice_handles"):
        state["nodes"] = []
        with open(sp, "w") as f:
            json.dump(state, f, indent=2)
    else:
        os.unlink(sp)
    return errors
