"""TPU-native LLM serving: continuous batching over jitted decode steps.

Analog of the reference's LLM layer (reference: python/ray/llm/ — the
`ray.serve.llm` / `ray.data.llm` entry points, which wrap vLLM engines);
here the engine itself is native jax: static-shape KV cache, bucketed
prefill, one jitted decode per token across all live requests.
"""

from ray_tpu.llm.engine import LLMEngine
from ray_tpu.llm.model import decode_step, init_cache, prefill

__all__ = ["LLMEngine", "prefill", "decode_step", "init_cache"]
