"""Continuous-batching LLM engine: token-level scheduling over jitted steps.

The serving engine the reference delegates to vLLM for (reference:
python/ray/llm/_internal/serve/deployments/llm/llm_server.py wrapping a
vLLM engine; python/ray/llm/_internal/serve/deployments/llm/vllm/*),
rebuilt TPU-native:

- requests join and leave a fixed set of decode SLOTS at token
  granularity (continuous batching — no waiting for the batch to drain),
- every decode step is ONE jitted call over all slots (static shapes:
  the MXU sees the same batched matmuls every step, zero recompiles),
- prompts prefill into a shared static KV cache through shape buckets
  (one compile per bucket), admitted before each decode step for low
  time-to-first-token.

The engine is asyncio-native so it drops straight into a Serve replica;
device steps run on an executor thread to keep the event loop live.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.llm import model as lm
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util import devmon, tracing


def _jx():
    """Lazy ``(jax, jax.numpy)`` accessor. jax must not be imported at
    module import time (worker processes import ray_tpu.llm without
    ever touching a backend), and every device-path method used to
    re-import it function-locally — this is the ONE copy of that
    idiom; device methods open with ``jax, jnp = _jx()``."""
    import jax
    import jax.numpy as jnp
    return jax, jnp


class KVHandoffError(RuntimeError):
    """A disaggregated request's shipped KV handle could not be
    resolved (prefill replica died / handle freed). Fails only its own
    request — never the shared scheduler loop."""


def engine_metrics() -> dict:
    """Get-or-create the engine's request-phase histograms (shared
    process registry; every engine in the process observes into the
    same series, and worker processes push them to the head via
    util/metrics.push_loop). Catalog:

      llm_queue_s        submit -> slot admission (waiting for a slot)
      llm_ttft_device_s  prefill device compute (block_until_ready)
      llm_ttft_wall_s    submit -> first token, wall clock
      llm_tpot_s         decode wall time per output token
      llm_batch_size     active decode slots per step block

    HBM attribution (the engine half of util/devmon.py's device plane):

      llm_kv_cache_bytes           live KV cache bytes on device
      llm_kv_cache_headroom_bytes  growth left before max_len capacity
    """
    from ray_tpu.util import metrics as m
    return {
        "queue": m.Histogram(
            "llm_queue_s",
            "Wait from request submission to slot admission"),
        "ttft_device": m.Histogram(
            "llm_ttft_device_s",
            "Device compute time producing the first token (prefill "
            "forward + cache write, block_until_ready-bounded)"),
        "ttft_wall": m.Histogram(
            "llm_ttft_wall_s",
            "Wall time from submission to first token"),
        "tpot": m.Histogram(
            "llm_tpot_s", "Decode wall time per output token",
            boundaries=(.0005, .001, .0025, .005, .01, .025, .05, .1,
                        .25, .5, 1, 2.5)),
        "batch": m.Histogram(
            "llm_batch_size", "Active decode slots per step block",
            boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256)),
        "kv_bytes": m.Gauge(
            "llm_kv_cache_bytes",
            "Bytes of the engine's static KV cache currently on device"),
        "kv_headroom": m.Gauge(
            "llm_kv_cache_headroom_bytes",
            "Bytes of bucketed KV growth left before the cache reaches "
            "its max_len capacity (0 = fully grown; watch next to "
            "device_hbm_used_bytes for OOM creep)"),
    }


@dataclass
class _Request:
    tokens: List[int]                       # prompt (token ids)
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    top_p: float = 1.0                      # 1.0 = disabled
    top_k: int = 0                          # 0 = disabled
    # stop sequences (token-id lists); on a suffix match generation
    # ends and the matched suffix is trimmed from the result
    stop: Optional[List[List[int]]] = None
    out: List[int] = field(default_factory=list)
    fut: Optional[asyncio.Future] = None
    stream: Optional[asyncio.Queue] = None
    submitted: float = field(default_factory=time.monotonic)
    # absolute wall-clock deadline (serve's propagated budget): the
    # scheduler refuses to admit an expired request and cancels an
    # active one at the next block boundary, reclaiming its slot
    deadline_ts: Optional[float] = None
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    prefill_device_s: float = 0.0           # block_until_ready-bounded
    # request trace context ambient at submission (the serve replica
    # binds it before user code): engine queue/prefill/generate spans
    # parent to the replica's handler span through it. Cleared once the
    # terminal "generate" span is recorded (one per request).
    trace: Optional[tracing.TraceContext] = None
    t_submit_wall: float = field(default_factory=time.time)
    # KV computed by a remote prefill engine (disaggregated serving):
    # {"k","v": (layers, bucket, kvh, hd) numpy, "logits": (vocab,)}
    prefilled: Optional[dict] = None


class LLMEngine:
    def __init__(self, cfg: LlamaConfig, params, *, max_slots: int = 8,
                 max_len: int = 1024,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 cache_dtype="bfloat16", seed: int = 0,
                 steps_per_sync: int = 8,
                 mesh=None, tensor_axis: str = "tensor",
                 detokenize: Optional[Callable[[List[int]], str]] = None):
        """With ``mesh``, the engine runs TENSOR-PARALLEL: params shard
        per lm.serve_param_specs (Megatron layout), the KV cache shards
        its kv-head dim, and every prefill/decode jit runs SPMD over the
        mesh with GSPMD inserting the two psums per layer. This is how a
        model larger than one chip's HBM serves (reference:
        llm/_internal/serve/configs/llm_config.py:181-186
        tensor_parallel_size + placement bundles per replica)."""
        jax, jnp = _jx()
        # jax is live in this process from here on: hook the compile
        # listeners now so even the cache-init compiles are spanned
        # (idempotent; no-op under RAY_TPU_DEVMON=0)
        devmon.install()
        if mesh is not None and getattr(cfg, "attn_impl", "auto") in (
                "auto", "flash", "flash_interpret", "ring"):
            # Tensor-parallel serving shards the head dim via GSPMD,
            # and the pallas flash kernel cannot be auto-partitioned
            # (training wraps it in shard_map; the serving jits don't)
            # — force the XLA reference attention, which GSPMD
            # partitions fine.
            import dataclasses
            cfg = dataclasses.replace(cfg, attn_impl="reference")
        self.cfg = cfg
        self.mesh = mesh
        self.tensor_axis = tensor_axis
        if mesh is not None:
            params = lm.shard_params_for_serving(params, mesh, cfg,
                                                 tensor_axis)
        self.params = params
        self.max_slots = max_slots
        self.max_len = max_len
        self.buckets = tuple(sorted(b for b in prefill_buckets
                                    if b <= max_len)) or (max_len,)
        self.detokenize = detokenize
        # Bucketed KV growth (the dense-cache answer to paged KV —
        # reference capability: vLLM's paged cache bounds HBM by live
        # tokens): the cache starts at a small length and DOUBLES, up
        # to max_len, only when an admitted request actually needs the
        # room — max_len=8k costs 8k-sized HBM only once an 8k request
        # arrives, and each growth step is one bounded recompile.
        self._cache_len = min(max_len, max(1024, self.buckets[-1]))
        self._cache = lm.init_cache(cfg, max_slots, self._cache_len,
                                    dtype=jnp.dtype(cache_dtype),
                                    mesh=mesh, axis=tensor_axis)
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self._waiting: "asyncio.Queue[_Request]" = asyncio.Queue()
        self._rng = np.random.default_rng(seed)
        self._key = jax.random.PRNGKey(seed)
        self._step = 0
        # Decode block size per host sync: throughput lever when the
        # device link is latency-bound. Kept power-of-2-bucketed so XLA
        # compiles at most log2(steps_per_sync)+1 block variants.
        self.steps_per_sync = max(1, steps_per_sync)
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = False
        # Request-phase telemetry rides the metrics registry (tagged
        # histograms, pushed to the head from worker processes); the
        # scalar counters below feed the legacy `stats` surface.
        self._m = engine_metrics()
        self._kv_account()
        self._requests = 0
        self._tokens_generated = 0
        self._ttft_sum = 0.0
        self._ttft_count = 0

    @property
    def stats(self) -> dict:
        """Scalar engine counters (the per-phase distributions live in
        the metrics registry — see engine_metrics())."""
        return {"requests": self._requests,
                "tokens_generated": self._tokens_generated,
                "ttft_sum": self._ttft_sum,
                "ttft_count": self._ttft_count,
                "cache_len": self._cache_len}

    def _kv_per_token_bytes(self) -> float:
        """Device bytes one KV position of one slot costs (both k and
        v, all layers) — the unit request-level HBM attribution is
        priced in."""
        n = self._cache["k"].nbytes + self._cache["v"].nbytes
        return n / float(self.max_slots * self._cache_len)

    def _kv_account(self) -> None:
        """Publish the engine's explicit KV HBM attribution: live cache
        bytes + the growth headroom still unspent before max_len
        capacity. Called at init and after every bucketed growth; the
        gauges ride the worker's metrics push to the head next to
        util/devmon.py's device_hbm_* series."""
        cur = self._cache["k"].nbytes + self._cache["v"].nbytes
        per_tok = self._kv_per_token_bytes()
        headroom = per_tok * self.max_slots \
            * (self.max_len - self._cache_len)
        self._m["kv_bytes"].set(cur)
        self._m["kv_headroom"].set(headroom)

    def _grow_cache(self, need: int) -> None:
        """Double the per-slot KV length (bucketed) until >= need,
        capped at max_len; active slots' KV is preserved (zero-pad on
        the length axis, resharded onto the mesh when tensor-parallel)."""
        new_len = self._cache_len
        while new_len < need:
            new_len *= 2
        new_len = min(new_len, self.max_len)
        pad = new_len - self._cache_len
        if pad <= 0:
            return
        jax, jnp = _jx()
        c = self._cache
        widths = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
        k, v = jnp.pad(c["k"], widths), jnp.pad(c["v"], widths)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            s = NamedSharding(self.mesh,
                              P(None, None, None, self.tensor_axis, None))
            k, v = jax.device_put(k, s), jax.device_put(v, s)
        self._cache = {"k": k, "v": v, "length": c["length"]}
        self._cache_len = new_len
        self._kv_account()

    # --- public API -----------------------------------------------------

    async def generate(self, tokens: Sequence[int], *,
                       max_new_tokens: int = 64,
                       temperature: float = 0.0,
                       eos_id: Optional[int] = None,
                       top_p: float = 1.0, top_k: int = 0,
                       stop: Optional[Sequence[Sequence[int]]] = None,
                       prefilled: Optional[dict] = None,
                       deadline_ts: Optional[float] = None) -> dict:
        """``prefilled`` skips the in-engine prompt forward pass: it is
        the KV payload a remote PrefillEngine computed for these tokens
        (prefill/decode disaggregation, ray_tpu/llm/pd.py; reference:
        llm/_internal/serve/serving_patterns/prefill_decode/, KV moved
        via NIXL there, via the object plane here). ``top_p``/``top_k``
        filter the on-device sampler (1.0/0 disable); ``stop`` is a list
        of token-id sequences that end generation (matched suffix
        trimmed from the result). ``deadline_ts`` (absolute wall clock,
        serve's propagated budget) cancels the request — and frees its
        decode slot for waiting requests — the moment the budget is
        spent, raising serve.DeadlineExceeded."""
        r = self._submit(tokens, max_new_tokens, temperature, eos_id,
                         top_p=top_p, top_k=top_k, stop=stop,
                         prefilled=prefilled, deadline_ts=deadline_ts)
        r.fut = asyncio.get_running_loop().create_future()
        await r.fut
        return self._result(r)

    async def generate_stream(self, tokens: Sequence[int], *,
                              max_new_tokens: int = 64,
                              temperature: float = 0.0,
                              eos_id: Optional[int] = None,
                              top_p: float = 1.0, top_k: int = 0,
                              stop: Optional[Sequence[Sequence[int]]] = None,
                              prefilled: Optional[dict] = None,
                              deadline_ts: Optional[float] = None):
        """Async generator of token ids as they are produced. NOTE:
        tokens belonging to a stop sequence may already have been
        yielded by the time the match completes — streaming consumers
        that care should trim client-side (the non-streaming result is
        always trimmed)."""
        r = self._submit(tokens, max_new_tokens, temperature, eos_id,
                         top_p=top_p, top_k=top_k, stop=stop,
                         prefilled=prefilled, deadline_ts=deadline_ts)
        r.stream = asyncio.Queue()
        while True:
            t = await r.stream.get()
            if t is None:
                return
            if isinstance(t, BaseException):
                raise t
            yield t

    async def generate_prefilled(self, tokens, prefilled: dict,
                                 **kw) -> dict:
        return await self.generate(tokens, prefilled=prefilled, **kw)

    def generate_stream_prefilled(self, tokens, prefilled: dict, **kw):
        return self.generate_stream(tokens, prefilled=prefilled, **kw)

    def _submit(self, tokens, max_new_tokens, temperature, eos_id,
                top_p=1.0, top_k=0, stop=None, prefilled=None,
                deadline_ts=None):
        if self._stopped:
            raise RuntimeError("engine is stopped")
        if deadline_ts is not None and time.time() > deadline_ts:
            # spent before submission: fail NOW — don't occupy queue
            # space the scheduler would only throw away later
            from ray_tpu.serve.fault import DeadlineExceeded
            raise DeadlineExceeded("budget spent before submission")
        tokens = list(map(int, tokens))
        if not tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        # prompts longer than the largest bucket stream through chunked
        # prefill (lm.prefill_chunk); only max_len bounds them
        if len(tokens) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(tokens)}+{max_new_tokens}) "
                f"exceeds max_len {self.max_len}")
        stop = [list(map(int, s)) for s in stop] if stop else None
        if stop and any(not s for s in stop):
            raise ValueError("empty stop sequence")
        if prefilled is not None:
            # validate at submission: a malformed payload must fail THIS
            # request, not blow up the shared scheduler loop mid-admit
            for k in ("k", "v", "logits", "length"):
                if k not in prefilled:
                    raise ValueError(f"prefilled payload missing {k!r}")
            if int(prefilled["length"]) != len(tokens):
                raise ValueError(
                    f"prefilled length {prefilled['length']} != prompt "
                    f"length {len(tokens)}")
            if prefilled["k"].shape[1] > self.max_len:
                raise ValueError(
                    f"prefilled KV spans {prefilled['k'].shape[1]} "
                    f"positions > decode max_len {self.max_len} "
                    "(prefill/decode bucket configs disagree)")
        r = _Request(tokens, max_new_tokens, temperature, eos_id,
                     top_p=float(top_p), top_k=int(top_k), stop=stop,
                     prefilled=prefilled, deadline_ts=deadline_ts,
                     trace=tracing.current_context())
        self._waiting.put_nowait(r)
        self._requests += 1
        self._ensure_loop()
        return r

    def _result(self, r: _Request) -> dict:
        out = {"tokens": r.out,
               "ttft_s": (r.first_token_at or 0) - r.submitted}
        if self.detokenize is not None:
            out["text"] = self.detokenize(r.out)
        return out

    async def stop(self):
        self._stopped = True
        if self._loop_task is not None:
            # The loop may be parked awaiting new work — cancel wakes it.
            self._loop_task.cancel()
            try:
                await self._loop_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    # --- scheduler loop -------------------------------------------------

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._run())

    def _bucket_for(self, n: int) -> int:
        return lm.bucket_for(self.buckets, n)

    async def _run(self):
        loop = asyncio.get_running_loop()
        try:
            while not self._stopped:
                # 1) admit waiting requests into free slots (prefill) —
                #    BEFORE the decode step, for low TTFT. Requests
                #    whose deadline passed while queued fail fast here:
                #    prefilling them would spend device time the client
                #    already gave up on.
                for slot in range(self.max_slots):
                    if self._slots[slot] is not None:
                        continue
                    r = None
                    while not self._waiting.empty():
                        cand = self._waiting.get_nowait()
                        if cand.deadline_ts is not None and \
                                time.time() > cand.deadline_ts:
                            self._expire(cand, None)
                            continue
                        r = cand
                        break
                    if r is None:
                        continue
                    try:
                        tok = await loop.run_in_executor(
                            None, self._admit_sync, slot, r)
                    except KVHandoffError as e:
                        # a dead/freed remote KV handle fails ITS request
                        # only — the shared loop and other slots live on
                        # (resolution happens before any cache write, so
                        # no partial state was left behind)
                        self._fail(r, None, e)
                        continue
                    self._emit_token(r, tok, slot)
                # deadline-cancel active slots at the block boundary:
                # the slot is reclaimed NOW (the next admit pass refills
                # it) instead of decoding to max_new_tokens for a client
                # whose budget is spent
                now = time.time()
                for i, r in enumerate(self._slots):
                    if r is not None and r.deadline_ts is not None \
                            and now > r.deadline_ts:
                        self._expire(r, i)
                active = [i for i, r in enumerate(self._slots)
                          if r is not None]
                if not active:
                    if self._waiting.empty():
                        # idle: park until work arrives
                        r = await self._waiting.get()
                        self._waiting.put_nowait(r)
                    continue
                # 2) a BLOCK of decode steps for every active slot, one
                # host sync per block. Sampling is on-device
                # (lm.sample); only token ids come back. Block size is
                # bounded by each slot's remaining budget so no request
                # over-runs max_new_tokens or the cache.
                # A slot hitting eos mid-block wastes its remaining
                # steps (discarded at emit, slot freed at the sync) —
                # the batch's throughput is worth more than the waste,
                # and headroom bounds below keep its cache writes legal.
                block = self.steps_per_sync
                for i in active:
                    r = self._slots[i]
                    block = min(block,
                                r.max_new_tokens - len(r.out),
                                self._cache_len - len(r.tokens)
                                - len(r.out))
                block = 1 << (max(1, block).bit_length() - 1)  # pow2 dn
                tokens = np.zeros((self.max_slots,), np.int32)
                temps = np.zeros((self.max_slots,), np.float32)
                top_ps = np.ones((self.max_slots,), np.float32)
                top_ks = np.zeros((self.max_slots,), np.int32)
                for i in active:
                    tokens[i] = self._slots[i].out[-1]
                    temps[i] = self._slots[i].temperature
                    top_ps[i] = self._slots[i].top_p
                    top_ks[i] = self._slots[i].top_k
                member_traces = sorted(
                    {self._slots[i].trace.trace_id
                     for i in active
                     if self._slots[i] is not None
                     and self._slots[i].trace is not None})
                first_ctx = next(
                    (self._slots[i].trace for i in active
                     if self._slots[i] is not None
                     and self._slots[i].trace is not None), None)
                t_dec = time.monotonic()
                t_dec_wall = time.time()
                out = await loop.run_in_executor(
                    None, self._decode_sync, tokens, temps, top_ps,
                    top_ks, block, first_ctx)
                # the block belongs to every member trace; the
                # EXEMPLAR can only name one — use the SAME member
                # whose context was bound inside _decode_sync, so
                # following the exemplar (`ray-tpu trace <id>`) shows
                # any decode-path compile span stamped during this
                # block, not a sibling's waterfall
                ex = first_ctx.trace_id if first_ctx is not None \
                    else None
                self._m["batch"].observe(len(active), exemplar=ex)
                self._m["tpot"].observe(
                    (time.monotonic() - t_dec) / block, exemplar=ex)
                # one span per decode BLOCK, linked to every member
                # trace: the block is shared compute, so it belongs to
                # all of them rather than to one (each member's
                # waterfall pulls it in via the links)
                tracing.record_batch_span(
                    "engine", "decode", member_traces,
                    t_dec_wall, time.time(), block=block,
                    slots=len(active))
                # the same interval is a device-compute window (the
                # decode block is block_until_ready-bounded by the
                # host transfer of its sampled tokens)
                devmon.record_device_window(
                    "decode", t_dec_wall, time.time(),
                    trace=ex or "")
                for step in range(block):
                    for i in active:
                        r = self._slots[i]
                        if r is None:  # finished earlier in this block
                            continue
                        self._emit_token(r, int(out[step, i]), i)
                await asyncio.sleep(0)
        except BaseException as e:  # noqa: BLE001 — fail all requests
            for i, r in enumerate(self._slots):
                if r is not None:
                    self._fail(r, i, e)
            while not self._waiting.empty():
                self._fail(self._waiting.get_nowait(), None, e)
            raise
        finally:
            for i, r in enumerate(self._slots):
                if r is not None:
                    self._finish(r, i)

    def _admit_sync(self, slot: int, r: _Request) -> int:
        """Prefill entry (executor thread): binds the request's trace
        context for the duration of the admit so any XLA compile it
        triggers (a cold shape bucket, a cache growth) is stamped with
        the request's trace id — util/devmon.py's compile listener
        reads the ambient context, and the span then rides this
        request's `ray-tpu trace` waterfall as a dev:compile lane."""
        if r.trace is None:
            return self._admit_impl(slot, r)
        tok = tracing.set_request_context(r.trace)
        try:
            return self._admit_impl(slot, r)
        finally:
            tracing.reset_request_context(tok)

    def _admit_impl(self, slot: int, r: _Request) -> int:
        """Prefill (executor thread): pad to bucket, fill cache slot.
        Returns the first sampled token. Remotely-prefilled requests
        skip the forward pass: their shipped KV is written straight
        into the slot."""
        jax, jnp = _jx()
        n = len(r.tokens)
        r.admitted_at = time.monotonic()
        self._m["queue"].observe(r.admitted_at - r.submitted)
        if r.trace is not None:
            # engine hop, segment 1: submit -> slot admission
            tracing.record_request_span(
                "engine", "queue", r.trace, r.trace.span_id,
                r.t_submit_wall,
                r.t_submit_wall + (r.admitted_at - r.submitted))
        # Bucketed growth runs HERE (executor thread): padding and
        # re-uploading a multi-GB cache on the event loop would stall
        # every in-flight stream. Admits and decode blocks are awaited
        # one at a time by the loop, so cache mutation stays serialized.
        need = n + r.max_new_tokens
        if r.prefilled is not None:
            need = max(need, int(r.prefilled["k"].shape[1]))
        if need > self._cache_len:
            self._grow_cache(need)
        if r.prefilled is not None:
            p = r.prefilled
            r.prefilled = None          # free the host copy after write
            from ray_tpu.runtime.device_store import TensorRef

            def take(x):
                """Unwrap the device-path KV handoff (reference: RDT
                tensor_transport_manager.py:37): same-process resolution
                never leaves HBM; cross-process is one fetch +
                device_put; the handle is single-use (freed here). A
                dead handle becomes a per-request KVHandoffError. Plain
                arrays pass through for the host-staged path."""
                if not isinstance(x, TensorRef):
                    return x
                try:
                    arr = x.resolve()
                except Exception as e:
                    raise KVHandoffError(
                        f"prefilled KV handle unresolvable: {e}") from e
                x.free()                # cache write below copies it
                return arr

            t0 = time.monotonic()
            kv = {"k": jnp.asarray(take(p["k"])),
                  "v": jnp.asarray(take(p["v"]))}
            self._cache = lm.write_prefill_to_cache(
                self._cache, kv, slot, jnp.int32(n))
            logits_np = np.asarray(take(p["logits"]))
            # device TTFT for a disaggregated request is the handoff
            # resolution + cache write on THIS engine (the prefill
            # forward ran on the remote tier)
            jax.block_until_ready(self._cache["k"])
            r.prefill_device_s = time.monotonic() - t0
            self._record_prefill_span(r)
            self._slots[slot] = r
            return self._sample_one(logits_np, r)
        t0 = time.monotonic()
        if n <= self.buckets[-1]:
            b = self._bucket_for(n)
            padded = lm.pad_prompt(r.tokens, b)
            logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                    jnp.int32(n), self.cfg,
                                    self._cache_len)
        else:
            logits, kv = self._chunked_prefill(r.tokens)
        self._cache = lm.write_prefill_to_cache(
            self._cache, kv, slot, jnp.int32(n))
        # block_until_ready bounds the DEVICE portion of TTFT: dispatch
        # above is async, so the wall clock alone can't attribute a slow
        # first token to compute vs queueing (round-6 SERVE_BENCH ask)
        logits_np = np.asarray(logits)
        jax.block_until_ready(self._cache["k"])
        r.prefill_device_s = time.monotonic() - t0
        self._record_prefill_span(r)
        self._slots[slot] = r
        return self._sample_one(logits_np, r)

    @staticmethod
    def _record_prefill_span(r: _Request) -> None:
        """Engine hop, segment 2: the prefill device compute that
        produced the first token (block_until_ready-bounded, so the
        span is the DEVICE portion of TTFT, ending now). The same
        interval feeds the duty-cycle estimator as a device window."""
        now = time.time()
        devmon.record_device_window(
            "prefill", now - r.prefill_device_s, now,
            trace=r.trace.trace_id if r.trace is not None else "")
        if r.trace is None:
            return
        tracing.record_request_span(
            "engine", "prefill", r.trace, r.trace.span_id,
            now - r.prefill_device_s, now, tokens=len(r.tokens))

    def _chunked_prefill(self, tokens: List[int]):
        """Prompts past the largest bucket stream through
        lm.prefill_chunk in bucket-sized pieces, each attending to the
        accumulated KV of the pieces before it. Returns (last-token
        logits, {"k","v"} (layers, max_len, kvh, hd)) — the same shape
        contract as lm.prefill, so the cache write is identical."""
        jax, jnp = _jx()
        cdt = self._cache["k"].dtype
        chunk = self.buckets[-1]
        # accumulator length is a BUCKET MULTIPLE >= the current cache
        # length: a padded final chunk written at a chunk-multiple
        # offset then never overruns it (dynamic_update_slice CLAMPS
        # the start index on overrun, which would silently shift the
        # chunk and corrupt earlier positions); sliced back to
        # _cache_len before the cache write
        acc_len = ((self._cache_len + chunk - 1) // chunk) * chunk
        shape = (self.cfg.n_layers, acc_len, self.cfg.n_kv_heads,
                 self.cfg.head_dim)
        acc = {"k": jnp.zeros(shape, cdt), "v": jnp.zeros(shape, cdt)}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            s = NamedSharding(self.mesh,
                              P(None, None, self.tensor_axis, None))
            acc = {k: jax.device_put(v, s) for k, v in acc.items()}
        off = 0
        logits = None
        while off < len(tokens):
            part = tokens[off:off + chunk]
            b = self._bucket_for(len(part))
            padded = lm.pad_prompt(part, b)
            logits, acc = lm.prefill_chunk(
                self.params, jnp.asarray(padded), jnp.int32(len(part)),
                jnp.int32(off), acc, self.cfg)
            off += len(part)
        if acc_len > self._cache_len:
            acc = {k: v[:, :self._cache_len] for k, v in acc.items()}
        return logits, acc

    def _decode_sync(self, tokens: np.ndarray, temps: np.ndarray,
                     top_ps: np.ndarray, top_ks: np.ndarray,
                     block: int,
                     trace_ctx: Optional[tracing.TraceContext] = None
                     ) -> np.ndarray:
        """Returns (block, slots) int32 sampled tokens. ``trace_ctx``
        (the first member trace of the batch) is bound while the block
        runs so a decode-path XLA compile — a new block-size variant,
        a filter toggle — stamps a member's trace id onto its
        dev:compile span instead of vanishing into unattributed time."""
        if trace_ctx is None:
            return self._decode_impl(tokens, temps, top_ps, top_ks,
                                     block)
        tok = tracing.set_request_context(trace_ctx)
        try:
            return self._decode_impl(tokens, temps, top_ps, top_ks,
                                     block)
        finally:
            tracing.reset_request_context(tok)

    def _decode_impl(self, tokens: np.ndarray, temps: np.ndarray,
                     top_ps: np.ndarray, top_ks: np.ndarray,
                     block: int) -> np.ndarray:
        jax, jnp = _jx()
        self._step += block
        key = jax.random.fold_in(self._key, self._step)
        # The top-p/top-k filters cost two O(V log V) vocab sorts per
        # decode step: only pay them when some ACTIVE request enabled
        # a filter (None compiles the plain sampler — one extra jit
        # variant, bounded).
        filters_on = bool((top_ps < 1.0).any() or (top_ks > 0).any())
        out, self._cache = lm.decode_steps(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(temps), key, self.cfg, block,
            jnp.asarray(top_ps) if filters_on else None,
            jnp.asarray(top_ks) if filters_on else None)
        return np.asarray(out)

    def _sample_one(self, logits: np.ndarray, r: _Request) -> int:
        """Host-side sampling for the FIRST token (prefill output is a
        single logits vector). Mirrors lm.sample's temperature ->
        top-k -> top-p order; also serves as the numpy reference the
        on-device sampler is parity-tested against."""
        if r.temperature <= 0:
            return int(np.argmax(logits))
        z = logits.astype(np.float64) / r.temperature
        if r.top_k > 0:
            kth = np.sort(z)[::-1][min(r.top_k, len(z)) - 1]
            z = np.where(z < kth, -np.inf, z)
        if r.top_p < 1.0:
            zm = z - z[np.isfinite(z)].max()
            p = np.exp(zm)
            p /= p.sum()
            order = np.argsort(p)[::-1]
            sp = p[order]
            keep_sorted = (np.cumsum(sp) - sp) < r.top_p
            thresh = sp[keep_sorted].min()
            z = np.where(p < thresh, -np.inf, z)
        z -= z[np.isfinite(z)].max()
        p = np.exp(z)
        p /= p.sum()
        return int(self._rng.choice(len(p), p=p))

    def _emit_token(self, r: _Request, tok: int, slot: int):
        """Append one sampled token; finish the request if done."""
        if r.first_token_at is None:
            r.first_token_at = time.monotonic()
            wall = r.first_token_at - r.submitted
            self._ttft_sum += wall
            self._ttft_count += 1
            self._m["ttft_wall"].observe(wall)
            # device time is a sub-interval of the wall interval; min()
            # guards the invariant against clock jitter. The exemplar
            # links the TTFT bucket to the concrete request trace.
            self._m["ttft_device"].observe(
                min(r.prefill_device_s, wall),
                exemplar=r.trace.trace_id if r.trace else None)
        r.out.append(tok)
        self._tokens_generated += 1
        if r.stream is not None:
            r.stream.put_nowait(tok)
        if r.stop:
            for seq in r.stop:
                if len(r.out) >= len(seq) and r.out[-len(seq):] == seq:
                    del r.out[-len(seq):]   # trim the stop sequence
                    self._finish(r, slot)
                    return
        if (len(r.out) >= r.max_new_tokens
                or (r.eos_id is not None and tok == r.eos_id)):
            self._finish(r, slot)

    def _record_done(self, r: _Request, error: bool) -> None:
        """Terminal engine span for one request: submit -> done, with
        the produced token count and the request's KV high-watermark
        (prompt + generated positions priced at the cache's per-token
        bytes) — the trace drill-down shows what the request cost in
        HBM, not just time. Recorded at most once (finish, fail, and
        the loop's shutdown sweep can all reach a request)."""
        if r.trace is None:
            return
        tracing.record_request_span(
            "engine", "generate", r.trace, r.trace.span_id,
            r.t_submit_wall, time.time(), error=error,
            tokens=len(r.out),
            kv_bytes=int(self._kv_per_token_bytes()
                         * (len(r.tokens) + len(r.out))))
        r.trace = None

    def _finish(self, r: _Request, slot: Optional[int]):
        self._record_done(r, error=False)
        if slot is not None and self._slots[slot] is r:
            self._slots[slot] = None
        if r.stream is not None:
            r.stream.put_nowait(None)
        if r.fut is not None and not r.fut.done():
            r.fut.set_result(True)

    def _expire(self, r: _Request, slot: Optional[int]):
        """Cancel one request whose deadline budget is spent (queued or
        mid-generation); its slot — if it held one — is reclaimed for
        the next admit pass."""
        from ray_tpu.serve.fault import DeadlineExceeded, fault_metrics
        fault_metrics()["deadline"].inc(tags={"where": "engine"})
        self._fail(r, slot, DeadlineExceeded(
            f"generation cancelled at the deadline after "
            f"{len(r.out)} token(s)"))

    def _fail(self, r: _Request, slot: Optional[int], e: BaseException):
        from ray_tpu.serve.fault import DeadlineExceeded
        self._record_done(r, error=True)
        # deadline cancellations cross the serve boundary TYPED so the
        # proxy can answer 504 instead of a generic 500
        err = e if isinstance(e, DeadlineExceeded) else RuntimeError(
            f"llm engine failed: {e}")
        if slot is not None and self._slots[slot] is r:
            self._slots[slot] = None
        if r.stream is not None:
            r.stream.put_nowait(err)  # raised by generate_stream
        if r.fut is not None and not r.fut.done():
            r.fut.set_exception(err)
