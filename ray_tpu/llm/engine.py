"""Continuous-batching LLM engine: token-level scheduling over jitted steps.

The serving engine the reference delegates to vLLM for (reference:
python/ray/llm/_internal/serve/deployments/llm/llm_server.py wrapping a
vLLM engine; python/ray/llm/_internal/serve/deployments/llm/vllm/*),
rebuilt TPU-native:

- requests join and leave a fixed set of decode SLOTS at token
  granularity (continuous batching — no waiting for the batch to drain),
- every decode step is ONE jitted call over all slots (static shapes:
  the MXU sees the same batched matmuls every step, zero recompiles),
- prompts prefill into a shared static KV cache through shape buckets
  (one compile per bucket), admitted before each decode step for low
  time-to-first-token.

The engine is asyncio-native so it drops straight into a Serve replica;
device steps run on an executor thread to keep the event loop live.
"""

from __future__ import annotations

import asyncio
import math
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from ray_tpu.llm import kvcache, model as lm, spec as specdec
from ray_tpu.models.llama import LlamaConfig
from ray_tpu.util import devmon, tracing


def _jx():
    """Lazy ``(jax, jax.numpy)`` accessor. jax must not be imported at
    module import time (worker processes import ray_tpu.llm without
    ever touching a backend), and every device-path method used to
    re-import it function-locally — this is the ONE copy of that
    idiom; device methods open with ``jax, jnp = _jx()``."""
    import jax
    import jax.numpy as jnp
    return jax, jnp


class KVHandoffError(RuntimeError):
    """A disaggregated request's shipped KV handle could not be
    resolved (prefill replica died / handle freed). Fails only its own
    request — never the shared scheduler loop."""


def engine_metrics() -> dict:
    """Get-or-create the engine's request-phase histograms (shared
    process registry; every engine in the process observes into the
    same series, and worker processes push them to the head via
    util/metrics.push_loop). Catalog:

      llm_queue_s        submit -> slot admission (waiting for a slot)
      llm_ttft_device_s  prefill device compute (block_until_ready)
      llm_ttft_wall_s    submit -> first token, wall clock
      llm_tpot_s         decode wall time per output token
      llm_batch_size     active decode slots per step block

    HBM attribution (the engine half of util/devmon.py's device plane):

      llm_kv_cache_bytes           live KV cache bytes on device
      llm_kv_cache_headroom_bytes  growth left before max_len capacity
    """
    from ray_tpu.util import metrics as m
    return {
        "queue": m.Histogram(
            "llm_queue_s",
            "Wait from request submission to slot admission"),
        "ttft_device": m.Histogram(
            "llm_ttft_device_s",
            "Device compute time producing the first token (prefill "
            "forward + cache write, block_until_ready-bounded)"),
        "ttft_wall": m.Histogram(
            "llm_ttft_wall_s",
            "Wall time from submission to first token"),
        "tpot": m.Histogram(
            "llm_tpot_s", "Decode wall time per output token",
            boundaries=(.0005, .001, .0025, .005, .01, .025, .05, .1,
                        .25, .5, 1, 2.5)),
        "batch": m.Histogram(
            "llm_batch_size", "Active decode slots per step block",
            boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256)),
        "kv_bytes": m.Gauge(
            "llm_kv_cache_bytes",
            "Bytes of the engine's static KV cache currently on device"),
        "kv_headroom": m.Gauge(
            "llm_kv_cache_headroom_bytes",
            "Bytes of bucketed KV growth left before the cache reaches "
            "its max_len capacity (0 = fully grown; watch next to "
            "device_hbm_used_bytes for OOM creep)"),
    }


@dataclass
class _Request:
    tokens: List[int]                       # prompt (token ids)
    max_new_tokens: int
    temperature: float
    eos_id: Optional[int]
    top_p: float = 1.0                      # 1.0 = disabled
    top_k: int = 0                          # 0 = disabled
    # stop sequences (token-id lists); on a suffix match generation
    # ends and the matched suffix is trimmed from the result
    stop: Optional[List[List[int]]] = None
    out: List[int] = field(default_factory=list)
    fut: Optional[asyncio.Future] = None
    stream: Optional[asyncio.Queue] = None
    submitted: float = field(default_factory=time.monotonic)
    # absolute wall-clock deadline (serve's propagated budget): the
    # scheduler refuses to admit an expired request and cancels an
    # active one at the next block boundary, reclaiming its slot
    deadline_ts: Optional[float] = None
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    prefill_device_s: float = 0.0           # block_until_ready-bounded
    # request trace context ambient at submission (the serve replica
    # binds it before user code): engine queue/prefill/generate spans
    # parent to the replica's handler span through it. Cleared once the
    # terminal "generate" span is recorded (one per request).
    trace: Optional[tracing.TraceContext] = None
    t_submit_wall: float = field(default_factory=time.time)
    # KV computed by a remote prefill engine (disaggregated serving):
    # {"k","v": (layers, bucket, kvh, hd) numpy, "logits": (vocab,)}
    prefilled: Optional[dict] = None
    # paged-KV state (engine paged mode): engine-unique sequence id,
    # the block allocation handed out at admission, and the prompt
    # tokens served from cached prefix blocks (stamped on the
    # terminal trace span and surfaced in the result)
    seq: int = 0
    kv_alloc: Optional[dict] = None
    prefix_hit: int = 0
    kv_written: bool = False    # prefill scatter reached the pool
    handoff_bytes: int = 0      # disaggregated KV shipped for this req
    # speculative decoding (engine spec mode): the per-request
    # prompt-lookup drafter (accept-window state; the token history it
    # matches against IS tokens+out) and the request's draft/accept
    # totals — accept rate lands on the terminal trace span and the
    # llm_spec_accept_rate gauge
    drafter: Optional[specdec.PromptLookupDrafter] = None
    spec_drafted: int = 0
    spec_accepted: int = 0


class LLMEngine:
    def __init__(self, cfg: LlamaConfig, params, *, max_slots: int = 8,
                 max_len: int = 1024,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 cache_dtype="bfloat16", seed: int = 0,
                 steps_per_sync: int = 8,
                 mesh=None, tensor_axis: str = "tensor",
                 kv_block_size: Optional[int] = None,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 kv_impl: Optional[str] = None,
                 spec: Optional[bool] = None,
                 detokenize: Optional[Callable[[List[int]], str]] = None):
        """With ``mesh``, the engine runs TENSOR-PARALLEL: params shard
        per lm.serve_param_specs (Megatron layout), the KV cache shards
        its kv-head dim, and every prefill/decode jit runs SPMD over the
        mesh with GSPMD inserting the two psums per layer. This is how a
        model larger than one chip's HBM serves (reference:
        llm/_internal/serve/configs/llm_config.py:181-186
        tensor_parallel_size + placement bundles per replica)."""
        jax, jnp = _jx()
        # jax is live in this process from here on: hook the compile
        # listeners now so even the cache-init compiles are spanned
        # (idempotent; no-op under RAY_TPU_DEVMON=0)
        devmon.install()
        if mesh is not None and getattr(cfg, "attn_impl", "auto") in (
                "auto", "flash", "flash_interpret", "ring"):
            # Tensor-parallel serving shards the head dim via GSPMD,
            # and the pallas flash kernel cannot be auto-partitioned
            # (training wraps it in shard_map; the serving jits don't)
            # — force the XLA reference attention, which GSPMD
            # partitions fine.
            import dataclasses
            cfg = dataclasses.replace(cfg, attn_impl="reference")
        self.cfg = cfg
        self.mesh = mesh
        self.tensor_axis = tensor_axis
        if mesh is not None:
            params = lm.shard_params_for_serving(params, mesh, cfg,
                                                 tensor_axis)
        self.params = params
        self.max_slots = max_slots
        self.max_len = max_len
        self.buckets = tuple(sorted(b for b in prefill_buckets
                                    if b <= max_len)) or (max_len,)
        self.detokenize = detokenize
        # Paged KV (llm/kvcache.py) is the default serving cache:
        # fixed-size token blocks from a preallocated pool, per-request
        # block tables, and prefix reuse for shared system prompts —
        # tensor-parallel engines included (the pool shards its kv-head
        # dim over the mesh; the block-index ops and the decode
        # attention are head-local, so tables stay replicated and no
        # collective is added). kv_block_size=0 selects the legacy
        # MONOLITHIC cache (bucketed doubling growth). None reads the
        # Config knobs (kvcache_block_size etc.).
        from ray_tpu.config import get_config
        _cfg = get_config()
        if kv_block_size is None:
            kv_block_size = int(getattr(_cfg, "kvcache_block_size", 16))
        if kv_pool_blocks is None:
            kv_pool_blocks = int(getattr(_cfg, "kvcache_pool_blocks", 0))
        if prefix_cache is None:
            prefix_cache = bool(getattr(_cfg, "kvcache_prefix_cache",
                                        True))
        if kv_impl is None:
            kv_impl = str(getattr(_cfg, "paged_attn_impl", "auto"))
        if spec is None:
            spec = bool(getattr(_cfg, "spec_decode", False))
        self._paged = kv_block_size > 0
        # Speculative decoding (llm/spec.py): draft-and-verify rides
        # the block-table verify forward, so it requires paged mode;
        # on the monolithic cache the knob is ignored.
        self._spec = bool(spec) and self._paged
        self._spec_k = max(1, int(getattr(_cfg, "spec_draft_tokens", 4)))
        self._spec_ngram = max(1, int(getattr(_cfg, "spec_ngram_max", 3)))
        self._spec_window = max(1, int(getattr(_cfg,
                                               "spec_backoff_window", 16)))
        self._spec_buckets = specdec.width_buckets(self._spec_k)
        self._specm = specdec.spec_metrics() if self._spec else None
        self._kvm = kvcache.kvcache_metrics()
        if self._paged:
            from ray_tpu.ops.attention import _on_tpu
            # decode attention impl: the fused block-table kernel
            # (paged_flash) vs the materialized gather view; "auto"
            # resolves by backend. Off-TPU the kernel runs through the
            # pallas interpreter — tier-1 exercises the real table
            # walk, not a shadow path.
            self._kv_impl = kvcache.resolve_attn_impl(kv_impl)
            self._kv_interpret = bool(
                getattr(_cfg, "paged_attn_interpret", False)) or (
                    self._kv_impl == "paged_flash" and not _on_tpu())
            # effective block size must divide every prefill bucket
            # and max_len (prefill writes land block-aligned): shrink
            # to the gcd instead of erroring on small test buckets
            b = kv_block_size
            for v in (*self.buckets, max_len):
                b = math.gcd(b, v)
            self._block = max(1, b)
            self._table_w = max_len // self._block
            per_tok = (cfg.n_layers * cfg.n_kv_heads * cfg.head_dim
                       * 2 * jnp.dtype(cache_dtype).itemsize)
            nb = kvcache.auto_pool_blocks(
                max_slots, self._table_w, per_tok * self._block,
                kv_pool_blocks)
            self._cache_len = max_len     # no growth: tables span it
            self._pool = kvcache.init_pool(cfg, nb, self._block,
                                           jnp.dtype(cache_dtype))
            if mesh is not None:
                # pool shards its kv-head dim (Megatron layout, same
                # axis as the monolithic cache); block ids index dim 1,
                # orthogonal to the shard, so scatter/gather/copy jits
                # run under GSPMD unchanged
                from jax.sharding import NamedSharding, PartitionSpec \
                    as P
                s = NamedSharding(
                    mesh, P(None, None, None, tensor_axis, None))
                self._pool = {k: jax.device_put(v, s)
                              for k, v in self._pool.items()}
            # what one decode step would have copied materializing the
            # gathered (slots, table_w * block) view, per layer and
            # k+v — the bytes the fused kernel keeps out of HBM
            self._gather_step_bytes = (
                max_slots * self._table_w
                * kvcache.pool_block_bytes(self._pool))
            self._kv = kvcache.KVBlockManager(
                nb, self._block, table_width=self._table_w,
                prefix_cache=prefix_cache, metrics=self._kvm)
            self._tables = np.full((max_slots, self._table_w),
                                   kvcache.TRASH, np.int32)
            self._blocked: deque = deque()   # admits parked on pool
            self._seq_counter = 0
            self._cache = None
        else:
            # Bucketed KV growth (the dense-cache fallback): the cache
            # starts at a small length and DOUBLES, up to max_len, only
            # when an admitted request actually needs the room —
            # max_len=8k costs 8k-sized HBM only once an 8k request
            # arrives, and each growth step is one bounded recompile.
            self._cache_len = min(max_len, max(1024, self.buckets[-1]))
            self._cache = lm.init_cache(cfg, max_slots, self._cache_len,
                                        dtype=jnp.dtype(cache_dtype),
                                        mesh=mesh, axis=tensor_axis)
        self._slots: List[Optional[_Request]] = [None] * max_slots
        self._waiting: "asyncio.Queue[_Request]" = asyncio.Queue()
        self._rng = np.random.default_rng(seed)
        self._key = jax.random.PRNGKey(seed)
        self._step = 0
        # Decode block size per host sync: throughput lever when the
        # device link is latency-bound. Kept power-of-2-bucketed so XLA
        # compiles at most log2(steps_per_sync)+1 block variants.
        self.steps_per_sync = max(1, steps_per_sync)
        self._loop_task: Optional[asyncio.Task] = None
        self._stopped = False
        # Request-phase telemetry rides the metrics registry (tagged
        # histograms, pushed to the head from worker processes); the
        # scalar counters below feed the legacy `stats` surface.
        self._m = engine_metrics()
        self._kv_account()
        self._requests = 0
        self._tokens_generated = 0
        self._ttft_sum = 0.0
        self._ttft_count = 0
        # Postmortem bundles snapshot engine state through a weakref —
        # the provider must not keep a dead engine (and its KV cache)
        # alive, and a collected engine silently drops out of dumps.
        import weakref
        from ray_tpu.util import forensics
        ref = weakref.ref(self)
        forensics.register_state_provider(
            f"llm_engine:{id(self):x}",
            lambda: (lambda e: e.stats if e is not None else None)(ref()))

    @property
    def stats(self) -> dict:
        """Scalar engine counters (the per-phase distributions live in
        the metrics registry — see engine_metrics())."""
        out = {"requests": self._requests,
               "tokens_generated": self._tokens_generated,
               "ttft_sum": self._ttft_sum,
               "ttft_count": self._ttft_count,
               "cache_len": self._cache_len,
               "paged": self._paged}
        if self._paged:
            out.update(block_size=self._block,
                       blocks_used=self._kv.used_blocks(),
                       blocks_cached=self._kv.cached_blocks(),
                       blocks_free=self._kv.free_blocks(),
                       prefix_hit_tokens=self._kv.hit_tokens_total,
                       kv_impl=self._kv_impl,
                       spec=self._spec)
        return out

    def _kv_per_token_bytes(self) -> float:
        """Device bytes one KV position of one slot costs (both k and
        v, all layers) — the unit request-level HBM attribution is
        priced in."""
        if self._paged:
            return kvcache.pool_block_bytes(self._pool) / self._block
        n = self._cache["k"].nbytes + self._cache["v"].nbytes
        return n / float(self.max_slots * self._cache_len)

    def _kv_account(self) -> None:
        """Publish the engine's explicit KV HBM attribution. Paged:
        live bytes = blocks referenced by live requests plus resident
        prefix-cache blocks (the pool bounds HBM by LIVE tokens, the
        vLLM property); headroom = free blocks. Monolithic: cache
        bytes + the bucketed growth left before max_len capacity. The
        gauges ride the worker's metrics push to the head next to
        util/devmon.py's device_hbm_* series."""
        if self._paged:
            bb = kvcache.pool_block_bytes(self._pool)
            live = self._kv.used_blocks() + self._kv.cached_blocks()
            self._m["kv_bytes"].set(bb * live)
            self._m["kv_headroom"].set(bb * self._kv.free_blocks())
            return
        cur = self._cache["k"].nbytes + self._cache["v"].nbytes
        per_tok = self._kv_per_token_bytes()
        headroom = per_tok * self.max_slots \
            * (self.max_len - self._cache_len)
        self._m["kv_bytes"].set(cur)
        self._m["kv_headroom"].set(headroom)

    def _grow_cache(self, need: int) -> None:
        """Double the per-slot KV length (bucketed) until >= need,
        capped at max_len; active slots' KV is preserved (zero-pad on
        the length axis, resharded onto the mesh when tensor-parallel)."""
        new_len = self._cache_len
        while new_len < need:
            new_len *= 2
        new_len = min(new_len, self.max_len)
        pad = new_len - self._cache_len
        if pad <= 0:
            return
        jax, jnp = _jx()
        c = self._cache
        widths = ((0, 0), (0, 0), (0, pad), (0, 0), (0, 0))
        k, v = jnp.pad(c["k"], widths), jnp.pad(c["v"], widths)
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            s = NamedSharding(self.mesh,
                              P(None, None, None, self.tensor_axis, None))
            k, v = jax.device_put(k, s), jax.device_put(v, s)
        self._cache = {"k": k, "v": v, "length": c["length"]}
        self._cache_len = new_len
        self._kv_account()

    # --- public API -----------------------------------------------------

    async def generate(self, tokens: Sequence[int], *,
                       max_new_tokens: int = 64,
                       temperature: float = 0.0,
                       eos_id: Optional[int] = None,
                       top_p: float = 1.0, top_k: int = 0,
                       stop: Optional[Sequence[Sequence[int]]] = None,
                       prefilled: Optional[dict] = None,
                       deadline_ts: Optional[float] = None) -> dict:
        """``prefilled`` skips the in-engine prompt forward pass: it is
        the KV payload a remote PrefillEngine computed for these tokens
        (prefill/decode disaggregation, ray_tpu/llm/pd.py; reference:
        llm/_internal/serve/serving_patterns/prefill_decode/, KV moved
        via NIXL there, via the object plane here). ``top_p``/``top_k``
        filter the on-device sampler (1.0/0 disable); ``stop`` is a list
        of token-id sequences that end generation (matched suffix
        trimmed from the result). ``deadline_ts`` (absolute wall clock,
        serve's propagated budget) cancels the request — and frees its
        decode slot for waiting requests — the moment the budget is
        spent, raising serve.DeadlineExceeded."""
        r = self._submit(tokens, max_new_tokens, temperature, eos_id,
                         top_p=top_p, top_k=top_k, stop=stop,
                         prefilled=prefilled, deadline_ts=deadline_ts)
        r.fut = asyncio.get_running_loop().create_future()
        await r.fut
        return self._result(r)

    async def generate_stream(self, tokens: Sequence[int], *,
                              max_new_tokens: int = 64,
                              temperature: float = 0.0,
                              eos_id: Optional[int] = None,
                              top_p: float = 1.0, top_k: int = 0,
                              stop: Optional[Sequence[Sequence[int]]] = None,
                              prefilled: Optional[dict] = None,
                              deadline_ts: Optional[float] = None):
        """Async generator of token ids as they are produced. NOTE:
        tokens belonging to a stop sequence may already have been
        yielded by the time the match completes — streaming consumers
        that care should trim client-side (the non-streaming result is
        always trimmed)."""
        r = self._submit(tokens, max_new_tokens, temperature, eos_id,
                         top_p=top_p, top_k=top_k, stop=stop,
                         prefilled=prefilled, deadline_ts=deadline_ts)
        r.stream = asyncio.Queue()
        while True:
            t = await r.stream.get()
            if t is None:
                return
            if isinstance(t, BaseException):
                raise t
            yield t

    async def generate_prefilled(self, tokens, prefilled: dict,
                                 **kw) -> dict:
        return await self.generate(tokens, prefilled=prefilled, **kw)

    def generate_stream_prefilled(self, tokens, prefilled: dict, **kw):
        return self.generate_stream(tokens, prefilled=prefilled, **kw)

    def _submit(self, tokens, max_new_tokens, temperature, eos_id,
                top_p=1.0, top_k=0, stop=None, prefilled=None,
                deadline_ts=None):
        if self._stopped:
            raise RuntimeError("engine is stopped")
        if deadline_ts is not None and time.time() > deadline_ts:
            # spent before submission: fail NOW — don't occupy queue
            # space the scheduler would only throw away later
            from ray_tpu.serve.fault import DeadlineExceeded
            raise DeadlineExceeded("budget spent before submission")
        tokens = list(map(int, tokens))
        if not tokens:
            raise ValueError("empty prompt")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        if top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {top_k}")
        # prompts longer than the largest bucket stream through chunked
        # prefill (lm.prefill_chunk); only max_len bounds them
        if len(tokens) + max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt+generation ({len(tokens)}+{max_new_tokens}) "
                f"exceeds max_len {self.max_len}")
        stop = [list(map(int, s)) for s in stop] if stop else None
        if stop and any(not s for s in stop):
            raise ValueError("empty stop sequence")
        if prefilled is not None:
            # validate at submission: a malformed payload must fail THIS
            # request, not blow up the shared scheduler loop mid-admit
            for k in ("k", "v", "logits", "length"):
                if k not in prefilled:
                    raise ValueError(f"prefilled payload missing {k!r}")
            if int(prefilled["length"]) != len(tokens):
                raise ValueError(
                    f"prefilled length {prefilled['length']} != prompt "
                    f"length {len(tokens)}")
            if prefilled["k"].shape[1] > self.max_len:
                raise ValueError(
                    f"prefilled KV spans {prefilled['k'].shape[1]} "
                    f"positions > decode max_len {self.max_len} "
                    "(prefill/decode bucket configs disagree)")
        r = _Request(tokens, max_new_tokens, temperature, eos_id,
                     top_p=float(top_p), top_k=int(top_k), stop=stop,
                     prefilled=prefilled, deadline_ts=deadline_ts,
                     trace=tracing.current_context())
        if self._paged:
            self._seq_counter += 1
            r.seq = self._seq_counter
        if self._spec:
            r.drafter = specdec.PromptLookupDrafter(
                k=self._spec_k, ngram_max=self._spec_ngram,
                window=self._spec_window)
        self._waiting.put_nowait(r)
        self._requests += 1
        self._ensure_loop()
        return r

    def _result(self, r: _Request) -> dict:
        out = {"tokens": r.out,
               "ttft_s": (r.first_token_at or 0) - r.submitted}
        if self._paged:
            out["prefix_hit_tokens"] = r.prefix_hit
        if self.detokenize is not None:
            out["text"] = self.detokenize(r.out)
        return out

    async def stop(self):
        self._stopped = True
        try:
            from ray_tpu.util import forensics
            forensics.unregister_state_provider(f"llm_engine:{id(self):x}")
        except Exception:  # noqa: BLE001
            pass
        if self._loop_task is not None:
            # The loop may be parked awaiting new work — cancel wakes it.
            self._loop_task.cancel()
            try:
                await self._loop_task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001
                pass

    # --- scheduler loop -------------------------------------------------

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._run())

    def _bucket_for(self, n: int) -> int:
        return lm.bucket_for(self.buckets, n)

    def _pop_candidate(self) -> Optional[_Request]:
        """Next admissible request: pool-parked admits first (FIFO —
        paged mode re-tries them once blocks free up), then the
        waiting queue. Deadline-expired candidates fail fast here."""
        while self._paged and self._blocked:
            cand = self._blocked.popleft()
            if cand.deadline_ts is not None and \
                    time.time() > cand.deadline_ts:
                self._expire(cand, None)
                continue
            return cand
        while not self._waiting.empty():
            cand = self._waiting.get_nowait()
            if cand.deadline_ts is not None and \
                    time.time() > cand.deadline_ts:
                self._expire(cand, None)
                continue
            return cand
        return None

    async def _run(self):
        loop = asyncio.get_running_loop()
        try:
            while not self._stopped:
                # 1) admit waiting requests into free slots (prefill) —
                #    BEFORE the decode step, for low TTFT. Requests
                #    whose deadline passed while queued fail fast here:
                #    prefilling them would spend device time the client
                #    already gave up on.
                for slot in range(self.max_slots):
                    if self._slots[slot] is not None:
                        continue
                    r = self._pop_candidate()
                    if r is None:
                        continue
                    if self._paged and r.kv_alloc is None:
                        # full-horizon block reservation at admission:
                        # decode can then never fail mid-flight on pool
                        # pressure — overload parks the ADMIT instead
                        # (FIFO; a parked head-of-line also blocks the
                        # queue behind it, preserving arrival order)
                        try:
                            alloc = self._kv.alloc_seq(
                                r.seq, r.tokens, r.max_new_tokens)
                        except kvcache.BlockPoolExhausted as e:
                            self._fail(r, None, e)
                            continue
                        if alloc is None:
                            self._blocked.appendleft(r)
                            break
                        r.kv_alloc = alloc
                        r.prefix_hit = alloc["hit_tokens"]
                        # publish live-bytes/headroom NOW: a wave of
                        # long decodes would otherwise report
                        # init-time gauges until the first finish —
                        # exactly the overload window the gauges
                        # exist for
                        self._kv_account()
                    try:
                        tok = await loop.run_in_executor(
                            None, self._admit_sync, slot, r)
                    except KVHandoffError as e:
                        # a dead/freed remote KV handle fails ITS request
                        # only — the shared loop and other slots live on
                        # (resolution happens before any cache write, so
                        # no partial state was left behind; the slot is
                        # passed so a paged table row set before the
                        # failure reverts to trash with the blocks)
                        self._fail(r, slot, e)
                        continue
                    except BaseException as e:  # noqa: BLE001
                        # any other admit failure kills the loop below —
                        # but the candidate is in no queue and no slot
                        # yet, so the outer sweep can't see it: fail it
                        # HERE or its caller hangs forever on a future
                        # nobody owns (the old behavior: a broken
                        # prefill path turned into a silent stall)
                        self._fail(r, slot, e)
                        raise
                    self._emit_token(r, tok, slot)
                # deadline-cancel active slots at the block boundary:
                # the slot is reclaimed NOW (the next admit pass refills
                # it) instead of decoding to max_new_tokens for a client
                # whose budget is spent
                now = time.time()
                for i, r in enumerate(self._slots):
                    if r is not None and r.deadline_ts is not None \
                            and now > r.deadline_ts:
                        self._expire(r, i)
                active = [i for i, r in enumerate(self._slots)
                          if r is not None]
                if not active:
                    if self._paged and self._blocked:
                        # pool-parked admits with nothing running can
                        # only be waiting on eviction — re-try shortly
                        # instead of parking on the (possibly empty)
                        # waiting queue forever
                        await asyncio.sleep(0.01)
                        continue
                    if self._waiting.empty():
                        # idle: park until work arrives
                        r = await self._waiting.get()
                        self._waiting.put_nowait(r)
                    continue
                # 2a) speculative verify round (engine spec mode): ask
                # each active slot's drafter for a continuation guess.
                # Any drafting slot flips this round from "one decode
                # step per emitted token" to ONE batched verify forward
                # scoring 1..k+1 positions per slot — non-drafting
                # slots co-batch at width 1 (their row emits exactly
                # its first verified token). When NOBODY drafts (spec
                # off, drafters cooling off on low-hit prompts, or
                # nothing to match yet) the engine falls through to the
                # vanilla block path below — that fallback plus the
                # drafter's accept-rate backoff is what bounds the
                # adversarial-prompt overhead.
                drafts: dict = {}
                if self._spec:
                    for i in active:
                        r = self._slots[i]
                        if r.drafter is None:
                            continue
                        # leave room for the bonus token and never
                        # draft past the request's horizon
                        budget = min(
                            self._spec_k,
                            r.max_new_tokens - len(r.out) - 1,
                            self._cache_len - len(r.tokens)
                            - len(r.out) - 1)
                        if budget < 1:
                            continue
                        d = r.drafter.propose(r.tokens + r.out, budget)
                        if d:
                            drafts[i] = d
                if drafts:
                    await self._spec_round(loop, active, drafts)
                    await asyncio.sleep(0)
                    continue
                # 2) a BLOCK of decode steps for every active slot, one
                # host sync per block. Sampling is on-device
                # (lm.sample); only token ids come back. Block size is
                # bounded by each slot's remaining budget so no request
                # over-runs max_new_tokens or the cache.
                # A slot hitting eos mid-block wastes its remaining
                # steps (discarded at emit, slot freed at the sync) —
                # the batch's throughput is worth more than the waste,
                # and headroom bounds below keep its cache writes legal.
                block = self.steps_per_sync
                for i in active:
                    r = self._slots[i]
                    block = min(block,
                                r.max_new_tokens - len(r.out),
                                self._cache_len - len(r.tokens)
                                - len(r.out))
                block = 1 << (max(1, block).bit_length() - 1)  # pow2 dn
                tokens = np.zeros((self.max_slots,), np.int32)
                temps = np.zeros((self.max_slots,), np.float32)
                top_ps = np.ones((self.max_slots,), np.float32)
                top_ks = np.zeros((self.max_slots,), np.int32)
                for i in active:
                    tokens[i] = self._slots[i].out[-1]
                    temps[i] = self._slots[i].temperature
                    top_ps[i] = self._slots[i].top_p
                    top_ks[i] = self._slots[i].top_k
                member_traces = sorted(
                    {self._slots[i].trace.trace_id
                     for i in active
                     if self._slots[i] is not None
                     and self._slots[i].trace is not None})
                first_ctx = next(
                    (self._slots[i].trace for i in active
                     if self._slots[i] is not None
                     and self._slots[i].trace is not None), None)
                t_dec = time.monotonic()
                t_dec_wall = time.time()
                out = await loop.run_in_executor(
                    None, self._decode_sync, tokens, temps, top_ps,
                    top_ks, block, first_ctx)
                # the block belongs to every member trace; the
                # EXEMPLAR can only name one — use the SAME member
                # whose context was bound inside _decode_sync, so
                # following the exemplar (`ray-tpu trace <id>`) shows
                # any decode-path compile span stamped during this
                # block, not a sibling's waterfall
                ex = first_ctx.trace_id if first_ctx is not None \
                    else None
                self._m["batch"].observe(len(active), exemplar=ex)
                self._m["tpot"].observe(
                    (time.monotonic() - t_dec) / block, exemplar=ex)
                # one span per decode BLOCK, linked to every member
                # trace: the block is shared compute, so it belongs to
                # all of them rather than to one (each member's
                # waterfall pulls it in via the links). The span also
                # names the attention impl the block ran and the HBM
                # copy bytes the fused kernel avoided — the trace
                # answers "which decode path was this" directly.
                kv_impl = self._kv_impl if self._paged else "monolithic"
                avoided = (block * self._gather_step_bytes
                           if self._paged
                           and self._kv_impl == "paged_flash" else 0)
                tracing.record_batch_span(
                    "engine", "decode", member_traces,
                    t_dec_wall, time.time(), block=block,
                    slots=len(active), kv_impl=kv_impl,
                    gather_bytes_avoided=avoided)
                # the same interval is a device-compute window (the
                # decode block is block_until_ready-bounded by the
                # host transfer of its sampled tokens)
                devmon.record_device_window(
                    "decode", t_dec_wall, time.time(),
                    trace=ex or "")
                for step in range(block):
                    for i in active:
                        r = self._slots[i]
                        if r is None:  # finished earlier in this block
                            continue
                        self._emit_token(r, int(out[step, i]), i)
                await asyncio.sleep(0)
        except BaseException as e:  # noqa: BLE001 — fail all requests
            for i, r in enumerate(self._slots):
                if r is not None:
                    self._fail(r, i, e)
            while self._paged and self._blocked:
                self._fail(self._blocked.popleft(), None, e)
            while not self._waiting.empty():
                self._fail(self._waiting.get_nowait(), None, e)
            raise
        finally:
            for i, r in enumerate(self._slots):
                if r is not None:
                    self._finish(r, i)

    def _admit_sync(self, slot: int, r: _Request) -> int:
        """Prefill entry (executor thread): binds the request's trace
        context for the duration of the admit so any XLA compile it
        triggers (a cold shape bucket, a cache growth) is stamped with
        the request's trace id — util/devmon.py's compile listener
        reads the ambient context, and the span then rides this
        request's `ray-tpu trace` waterfall as a dev:compile lane."""
        if r.trace is None:
            return self._admit_impl(slot, r)
        tok = tracing.set_request_context(r.trace)
        try:
            return self._admit_impl(slot, r)
        finally:
            tracing.reset_request_context(tok)

    @staticmethod
    def _take_handoff(x):
        """Unwrap the device-path KV handoff (reference: RDT
        tensor_transport_manager.py:37): same-process resolution
        never leaves HBM; cross-process is one fetch + device_put;
        the handle is single-use (freed here — the prefill replica's
        copy dies at handoff instead of surviving next to the decode
        copy). A dead handle becomes a per-request KVHandoffError.
        Plain arrays pass through for the host-staged path."""
        from ray_tpu.runtime.device_store import TensorRef
        if not isinstance(x, TensorRef):
            return x
        try:
            arr = x.resolve()
        except Exception as e:
            raise KVHandoffError(
                f"prefilled KV handle unresolvable: {e}") from e
        x.free()                # cache write below copies it
        return arr

    def _admit_impl(self, slot: int, r: _Request) -> int:
        """Prefill (executor thread): pad to bucket, fill cache slot
        (monolithic) or scatter into the request's block table
        (paged). Returns the first sampled token. Remotely-prefilled
        requests skip the forward pass: their shipped KV is written
        straight into the slot."""
        jax, jnp = _jx()
        n = len(r.tokens)
        r.admitted_at = time.monotonic()
        self._m["queue"].observe(r.admitted_at - r.submitted)
        if r.trace is not None:
            # engine hop, segment 1: submit -> slot admission
            tracing.record_request_span(
                "engine", "queue", r.trace, r.trace.span_id,
                r.t_submit_wall,
                r.t_submit_wall + (r.admitted_at - r.submitted))
        if self._paged:
            return self._admit_paged(slot, r)
        # Bucketed growth runs HERE (executor thread): padding and
        # re-uploading a multi-GB cache on the event loop would stall
        # every in-flight stream. Admits and decode blocks are awaited
        # one at a time by the loop, so cache mutation stays serialized.
        need = n + r.max_new_tokens
        pad_to = 0
        if r.prefilled is not None:
            # pd.py ships BLOCK-granular KV (transfer scales with the
            # prompt); re-pad to a bucket multiple here so the donated
            # write_prefill_to_cache keeps bucket-bounded compile
            # variants instead of one per distinct block count
            L = int(r.prefilled["k"].shape[1])
            big = self.buckets[-1]
            pad_to = (lm.bucket_for(self.buckets, L) if L <= big
                      else -(-L // big) * big)
            pad_to = min(pad_to, self.max_len)
            need = max(need, pad_to)
        if need > self._cache_len:
            self._grow_cache(need)
        if r.prefilled is not None:
            p = r.prefilled
            r.prefilled = None          # free the host copy after write
            take = self._take_handoff
            t0 = time.monotonic()
            kv_k = jnp.asarray(take(p["k"]))
            kv_v = jnp.asarray(take(p["v"]))
            r.handoff_bytes = int(kv_k.nbytes + kv_v.nbytes)
            self._kvm["handoff_bytes"].inc(r.handoff_bytes)
            padw = pad_to - kv_k.shape[1]
            if padw > 0:
                widths = ((0, 0), (0, padw), (0, 0), (0, 0))
                kv_k = jnp.pad(kv_k, widths)
                kv_v = jnp.pad(kv_v, widths)
            kv = {"k": kv_k, "v": kv_v}
            self._cache = lm.write_prefill_to_cache(
                self._cache, kv, slot, jnp.int32(n))
            logits_np = np.asarray(take(p["logits"]))
            # device TTFT for a disaggregated request is the handoff
            # resolution + cache write on THIS engine (the prefill
            # forward ran on the remote tier)
            jax.block_until_ready(self._cache["k"])
            r.prefill_device_s = time.monotonic() - t0
            self._record_prefill_span(r)
            self._slots[slot] = r
            return self._sample_one(logits_np, r)
        t0 = time.monotonic()
        if n <= self.buckets[-1]:
            b = self._bucket_for(n)
            padded = lm.pad_prompt(r.tokens, b)
            logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                    jnp.int32(n), self.cfg,
                                    self._cache_len)
        else:
            logits, kv = self._chunked_prefill(r.tokens)
        self._cache = lm.write_prefill_to_cache(
            self._cache, kv, slot, jnp.int32(n))
        # block_until_ready bounds the DEVICE portion of TTFT: dispatch
        # above is async, so the wall clock alone can't attribute a slow
        # first token to compute vs queueing (round-6 SERVE_BENCH ask)
        logits_np = np.asarray(logits)
        jax.block_until_ready(self._cache["k"])
        r.prefill_device_s = time.monotonic() - t0
        self._record_prefill_span(r)
        self._slots[slot] = r
        return self._sample_one(logits_np, r)

    def _acc_len(self) -> int:
        """Accumulator length for block-table prefill: the full table
        span rounded to a chunk multiple PLUS one slack chunk — a
        prefix-hit suffix whose first piece starts off the chunk grid
        can bucket-pad past the next boundary, and dynamic_update_slice
        must never clamp (a clamped write silently shifts the chunk
        and corrupts earlier positions)."""
        chunk = self.buckets[-1]
        span = self._table_w * self._block
        return ((span + chunk - 1) // chunk) * chunk + chunk

    def _prefill_start(self, hit: int) -> int:
        """First position the suffix prefill computes for a
        ``hit``-token prefix hit. On a flash-capable chunked-prefill
        path the start rounds DOWN to the chunk grid: every piece then
        sits at a chunk-multiple offset and enters the per-offset
        COMPILED flash variants (bounded: ceil(max_len/chunk)
        compiles) instead of minting a fresh compile per distinct hit
        length — or falling to the dynamic-offset XLA path. The
        recomputed rows (< one chunk) land in full hit blocks, whose
        scatter targets are already trash, and recomputation is
        bitwise-identical to the cached values (same chunk grid a cold
        request ran), so reuse accounting and parity are untouched."""
        if hit == 0:
            return 0
        from ray_tpu.ops.attention import _on_tpu
        impl = lm._serve_attn_impl(self.cfg)
        flashy = impl in ("flash", "flash_interpret") or (
            impl == "auto" and _on_tpu())
        if not flashy:
            return hit
        chunk = self.buckets[-1]
        return (hit // chunk) * chunk

    def _admit_paged(self, slot: int, r: _Request) -> int:
        """Paged prefill: the scheduler already reserved the block
        table (r.kv_alloc); write the prompt's KV through it. Three
        paths: shipped-KV handoff (disaggregated), cold bucketed
        prefill (one forward, scatter — bitwise-identical to the
        monolithic path), and prefix-hit / long-prompt chunked prefill
        (gather cached prefix blocks, run lm.prefill_chunk on the
        suffix only — the prefix's device time is ~eliminated)."""
        jax, jnp = _jx()
        n = len(r.tokens)
        table = r.kv_alloc["table"]
        hit = r.prefix_hit
        B = self._block
        self._tables[slot] = table
        t0 = time.monotonic()
        if r.prefilled is not None:
            p = r.prefilled
            r.prefilled = None
            take = self._take_handoff
            k_np = np.asarray(take(p["k"]))
            v_np = np.asarray(take(p["v"]))
            logits_np = np.asarray(take(p["logits"]))
            r.handoff_bytes = int(k_np.nbytes + v_np.nbytes)
            self._kvm["handoff_bytes"].inc(r.handoff_bytes)
            acc_len = self._acc_len()
            pad = acc_len - k_np.shape[1]
            widths = ((0, 0), (0, pad), (0, 0), (0, 0))
            acc = {"k": jnp.asarray(np.pad(k_np, widths)),
                   "v": jnp.asarray(np.pad(v_np, widths))}
            # shared prefix blocks (a hit makes the shipped bytes for
            # them redundant) and beyond-horizon slots write to trash
            targets = table.copy()
            targets[:hit // B] = kvcache.TRASH
            self._pool = kvcache.scatter_table(self._pool, acc,
                                               jnp.asarray(targets))
        elif hit == 0 and n <= self.buckets[-1]:
            # cache-cold short prompt: the SAME lm.prefill forward the
            # monolithic engine runs (bitwise parity), padded only to
            # its bucket; pad-garbage blocks redirect to trash via the
            # table's unallocated tail
            b = self._bucket_for(n)
            padded = lm.pad_prompt(r.tokens, b)
            logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                    jnp.int32(n), self.cfg, b)
            nb = b // B
            phys = np.full((nb,), kvcache.TRASH, np.int32)
            phys[:min(nb, self._table_w)] = table[:min(nb,
                                                       self._table_w)]
            self._pool = kvcache.scatter_bucket(
                self._pool, kv, jnp.asarray(phys), nb)
            logits_np = np.asarray(logits)
        else:
            logits_np = self._prefill_into_blocks(r, table, hit)
        jax.block_until_ready(self._pool["k"])
        r.kv_written = True
        r.prefill_device_s = time.monotonic() - t0
        self._record_prefill_span(r)
        self._slots[slot] = r
        return self._sample_one(logits_np, r)

    def _prefill_into_blocks(self, r: _Request, table: np.ndarray,
                             hit: int) -> np.ndarray:
        """Prefix-hit (and long-prompt) prefill: gather the table's
        cached blocks into a contiguous accumulator, run the suffix
        through lm.prefill_chunk at the prefix offset (pieces aligned
        to the absolute chunk grid so a cold and a hit request compute
        every suffix row identically — the bitwise-parity contract the
        tests pin), then scatter the NEW positions' KV back into the
        request's own blocks. Shared prefix blocks are never written
        (their scatter targets are the trash block)."""
        jax, jnp = _jx()
        n = len(r.tokens)
        B = self._block
        chunk = self.buckets[-1]
        acc_len = self._acc_len()
        acc = kvcache.gather_table(self._pool, jnp.asarray(table),
                                   acc_len)
        off = self._prefill_start(hit)
        logits = None
        while off < n:
            end = min(n, ((off // chunk) + 1) * chunk)
            part = r.tokens[off:end]
            b = self._bucket_for(len(part))
            padded = lm.pad_prompt(part, b)
            logits, acc = lm.prefill_chunk(
                self.params, jnp.asarray(padded),
                jnp.int32(len(part)), jnp.int32(off), acc, self.cfg)
            off = end
        targets = table.copy()
        targets[:hit // B] = kvcache.TRASH
        self._pool = kvcache.scatter_table(self._pool, acc,
                                           jnp.asarray(targets))
        return np.asarray(logits)

    @staticmethod
    def _record_prefill_span(r: _Request) -> None:
        """Engine hop, segment 2: the prefill device compute that
        produced the first token (block_until_ready-bounded, so the
        span is the DEVICE portion of TTFT, ending now). The same
        interval feeds the duty-cycle estimator as a device window."""
        now = time.time()
        devmon.record_device_window(
            "prefill", now - r.prefill_device_s, now,
            trace=r.trace.trace_id if r.trace is not None else "")
        if r.trace is None:
            return
        tracing.record_request_span(
            "engine", "prefill", r.trace, r.trace.span_id,
            now - r.prefill_device_s, now, tokens=len(r.tokens))

    def _chunked_prefill(self, tokens: List[int]):
        """Prompts past the largest bucket stream through
        lm.prefill_chunk in bucket-sized pieces, each attending to the
        accumulated KV of the pieces before it. Returns (last-token
        logits, {"k","v"} (layers, max_len, kvh, hd)) — the same shape
        contract as lm.prefill, so the cache write is identical."""
        jax, jnp = _jx()
        cdt = self._cache["k"].dtype
        chunk = self.buckets[-1]
        # accumulator length is a BUCKET MULTIPLE >= the current cache
        # length: a padded final chunk written at a chunk-multiple
        # offset then never overruns it (dynamic_update_slice CLAMPS
        # the start index on overrun, which would silently shift the
        # chunk and corrupt earlier positions); sliced back to
        # _cache_len before the cache write
        acc_len = ((self._cache_len + chunk - 1) // chunk) * chunk
        shape = (self.cfg.n_layers, acc_len, self.cfg.n_kv_heads,
                 self.cfg.head_dim)
        acc = {"k": jnp.zeros(shape, cdt), "v": jnp.zeros(shape, cdt)}
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            s = NamedSharding(self.mesh,
                              P(None, None, self.tensor_axis, None))
            acc = {k: jax.device_put(v, s) for k, v in acc.items()}
        off = 0
        logits = None
        while off < len(tokens):
            part = tokens[off:off + chunk]
            b = self._bucket_for(len(part))
            padded = lm.pad_prompt(part, b)
            logits, acc = lm.prefill_chunk(
                self.params, jnp.asarray(padded), jnp.int32(len(part)),
                jnp.int32(off), acc, self.cfg)
            off += len(part)
        if acc_len > self._cache_len:
            acc = {k: v[:, :self._cache_len] for k, v in acc.items()}
        return logits, acc

    def _decode_sync(self, tokens: np.ndarray, temps: np.ndarray,
                     top_ps: np.ndarray, top_ks: np.ndarray,
                     block: int,
                     trace_ctx: Optional[tracing.TraceContext] = None
                     ) -> np.ndarray:
        """Returns (block, slots) int32 sampled tokens. ``trace_ctx``
        (the first member trace of the batch) is bound while the block
        runs so a decode-path XLA compile — a new block-size variant,
        a filter toggle — stamps a member's trace id onto its
        dev:compile span instead of vanishing into unattributed time."""
        if trace_ctx is None:
            return self._decode_impl(tokens, temps, top_ps, top_ks,
                                     block)
        tok = tracing.set_request_context(trace_ctx)
        try:
            return self._decode_impl(tokens, temps, top_ps, top_ks,
                                     block)
        finally:
            tracing.reset_request_context(tok)

    def _decode_impl(self, tokens: np.ndarray, temps: np.ndarray,
                     top_ps: np.ndarray, top_ks: np.ndarray,
                     block: int) -> np.ndarray:
        jax, jnp = _jx()
        self._step += block
        key = jax.random.fold_in(self._key, self._step)
        # The top-p/top-k filters cost two O(V log V) vocab sorts per
        # decode step: only pay them when some ACTIVE request enabled
        # a filter (None compiles the plain sampler — one extra jit
        # variant, bounded).
        filters_on = bool((top_ps < 1.0).any() or (top_ks > 0).any())
        tp = jnp.asarray(top_ps) if filters_on else None
        tk = jnp.asarray(top_ks) if filters_on else None
        if self._paged:
            # per-slot write positions are host-derived (prompt +
            # emitted - 1: the last emitted token's KV lands this
            # step), matching the monolithic cache's device-side
            # length counter by construction; empty slots write into
            # the trash block
            lengths = np.zeros((self.max_slots,), np.int32)
            for i, r in enumerate(self._slots):
                if r is not None:
                    lengths[i] = len(r.tokens) + len(r.out) - 1
            out, self._pool = kvcache.paged_decode_steps(
                self.params, self._pool, jnp.asarray(self._tables),
                jnp.asarray(lengths), jnp.asarray(tokens),
                jnp.asarray(temps), key, self.cfg, block, tp, tk,
                impl=self._kv_impl, interpret=self._kv_interpret,
                mesh=self.mesh, axis=self.tensor_axis)
            self._kvm["attn_steps"].inc(
                block, tags={"impl": self._kv_impl})
            if self._kv_impl == "paged_flash":
                self._kvm["gather_avoided"].inc(
                    block * self._gather_step_bytes)
            return np.asarray(out)
        out, self._cache = lm.decode_steps(
            self.params, self._cache, jnp.asarray(tokens),
            jnp.asarray(temps), key, self.cfg, block, tp, tk)
        return np.asarray(out)

    async def _spec_round(self, loop, active: List[int],
                          drafts: dict) -> None:
        """One draft-and-verify round: pad every active slot's
        [last_token, draft...] row to a verify-width bucket (repeating
        the last token — pad rows write garbage KV beyond the slot's
        logical length, masked out of every attention and overwritten
        by the next real write), score all positions in one forward,
        accept per slot (exact greedy match / rejection sampling in
        llm/spec.py), roll back the host block accounting for rejected
        tails, and emit 1..k+1 tokens per slot."""
        w = specdec.bucket_width(
            self._spec_buckets,
            1 + max(len(d) for d in drafts.values()))
        tokens_bw = np.zeros((self.max_slots, w), np.int32)
        lengths = np.zeros((self.max_slots,), np.int32)
        for i in active:
            r = self._slots[i]
            row = [r.out[-1]] + drafts.get(i, [])
            row += [row[-1]] * (w - len(row))
            tokens_bw[i] = row
            lengths[i] = len(r.tokens) + len(r.out) - 1
        member_traces = sorted(
            {self._slots[i].trace.trace_id for i in active
             if self._slots[i] is not None
             and self._slots[i].trace is not None})
        first_ctx = next(
            (self._slots[i].trace for i in active
             if self._slots[i] is not None
             and self._slots[i].trace is not None), None)
        t_dec = time.monotonic()
        t_dec_wall = time.time()
        logits = await loop.run_in_executor(
            None, self._verify_sync, tokens_bw, lengths, first_ctx)
        emitted_total = 0
        for i in active:
            r = self._slots[i]
            if r is None:
                continue
            d = drafts.get(i, [])
            emitted, n_acc = specdec.accept_tokens(
                logits[i, :len(d) + 1], d,
                temperature=r.temperature, top_k=r.top_k,
                top_p=r.top_p, rng=self._rng)
            if d:
                r.drafter.record(len(d), n_acc)
                r.spec_drafted += len(d)
                r.spec_accepted += n_acc
                self._specm["tokens"].inc(len(d),
                                          tags={"kind": "drafted"})
                if n_acc:
                    self._specm["tokens"].inc(
                        n_acc, tags={"kind": "accepted"})
                if len(d) > n_acc:
                    self._specm["tokens"].inc(
                        len(d) - n_acc, tags={"kind": "rejected"})
                    # host-side rollback of the rejected tail. Under
                    # the engine's full-horizon reservation this frees
                    # no blocks (min_blocks pins the reservation —
                    # giving promised blocks back could deadlock a
                    # re-acquire against a newer admit); it keeps the
                    # sequence's hash chain honest and IS the real
                    # rollback for COW forks (tests pin both).
                    self._kv.truncate_seq(
                        r.seq,
                        len(r.tokens) + len(r.out) + len(emitted),
                        min_blocks=self._kv.blocks_needed(
                            len(r.tokens), r.max_new_tokens))
            emitted_total += len(emitted)
            for t in emitted:
                if self._slots[i] is not r:
                    break   # finished mid-accept (eos/stop/max_new):
                            # the tail of an accepted draft is dropped
                self._emit_token(r, int(t), i)
        ex = first_ctx.trace_id if first_ctx is not None else None
        self._m["batch"].observe(len(active), exemplar=ex)
        per_slot = max(1.0, emitted_total / max(1, len(active)))
        self._m["tpot"].observe(
            (time.monotonic() - t_dec) / per_slot, exemplar=ex)
        tracing.record_batch_span(
            "engine", "decode", member_traces,
            t_dec_wall, time.time(), block=emitted_total,
            slots=len(active), kv_impl=self._kv_impl,
            gather_bytes_avoided=0, spec_k=w - 1)
        devmon.record_device_window(
            "decode", t_dec_wall, time.time(), trace=ex or "")

    def _verify_sync(self, tokens_bw: np.ndarray, lengths: np.ndarray,
                     trace_ctx: Optional[tracing.TraceContext] = None
                     ) -> np.ndarray:
        """Returns (slots, w, vocab) f32 verify logits; binds the first
        member trace like _decode_sync so a cold verify-width compile
        is attributed to a real request."""
        if trace_ctx is None:
            return self._verify_impl(tokens_bw, lengths)
        tok = tracing.set_request_context(trace_ctx)
        try:
            return self._verify_impl(tokens_bw, lengths)
        finally:
            tracing.reset_request_context(tok)

    def _verify_impl(self, tokens_bw: np.ndarray,
                     lengths: np.ndarray) -> np.ndarray:
        jax, jnp = _jx()
        logits, self._pool = kvcache.paged_verify_steps(
            self.params, self._pool, jnp.asarray(self._tables),
            jnp.asarray(lengths), jnp.asarray(tokens_bw), self.cfg,
            impl=self._kv_impl, interpret=self._kv_interpret,
            mesh=self.mesh, axis=self.tensor_axis)
        self._kvm["attn_steps"].inc(1, tags={"impl": self._kv_impl})
        return np.asarray(logits)

    def _sample_one(self, logits: np.ndarray, r: _Request) -> int:
        """Host-side sampling for the FIRST token (prefill output is a
        single logits vector). Built on spec.host_probs ->
        lm.filter_logits — the ONE temperature -> top-k -> top-p
        transform shared with the on-device sampler and the
        speculative verify-acceptance path, so the three can never
        drift (this host path is also the numpy reference the device
        sampler is parity-tested against)."""
        if r.temperature <= 0:
            return int(np.argmax(logits))
        p = specdec.host_probs(np.asarray(logits), r.temperature,
                               r.top_k, r.top_p)
        return int(self._rng.choice(len(p), p=p))

    def _emit_token(self, r: _Request, tok: int, slot: int):
        """Append one sampled token; finish the request if done."""
        if r.first_token_at is None:
            r.first_token_at = time.monotonic()
            wall = r.first_token_at - r.submitted
            self._ttft_sum += wall
            self._ttft_count += 1
            self._m["ttft_wall"].observe(wall)
            # device time is a sub-interval of the wall interval; min()
            # guards the invariant against clock jitter. The exemplar
            # links the TTFT bucket to the concrete request trace.
            self._m["ttft_device"].observe(
                min(r.prefill_device_s, wall),
                exemplar=r.trace.trace_id if r.trace else None)
        r.out.append(tok)
        self._tokens_generated += 1
        if r.stream is not None:
            r.stream.put_nowait(tok)
        if r.stop:
            for seq in r.stop:
                if len(r.out) >= len(seq) and r.out[-len(seq):] == seq:
                    del r.out[-len(seq):]   # trim the stop sequence
                    self._finish(r, slot)
                    return
        if (len(r.out) >= r.max_new_tokens
                or (r.eos_id is not None and tok == r.eos_id)):
            self._finish(r, slot)

    def _record_done(self, r: _Request, error: bool) -> None:
        """Terminal engine span for one request: submit -> done, with
        the produced token count and the request's KV high-watermark
        (prompt + generated positions priced at the cache's per-token
        bytes) — the trace drill-down shows what the request cost in
        HBM, not just time. Recorded at most once (finish, fail, and
        the loop's shutdown sweep can all reach a request)."""
        # the accept-rate gauge tracks every finished speculative
        # request, traced or not (the span extra below needs a trace)
        if r.spec_drafted and self._specm is not None:
            self._specm["accept_rate"].set(r.spec_accepted / r.spec_drafted)
        if r.trace is None:
            return
        extra = {}
        if self._paged:
            extra["prefix_hit_tokens"] = r.prefix_hit
        if r.handoff_bytes:
            extra["kv_handoff_bytes"] = r.handoff_bytes
        if r.spec_drafted:
            rate = r.spec_accepted / r.spec_drafted
            extra["spec_accept_rate"] = round(rate, 4)
        tracing.record_request_span(
            "engine", "generate", r.trace, r.trace.span_id,
            r.t_submit_wall, time.time(), error=error,
            tokens=len(r.out),
            kv_bytes=int(self._kv_per_token_bytes()
                         * (len(r.tokens) + len(r.out))), **extra)
        r.trace = None

    def _free_kv(self, r: _Request, slot: Optional[int]) -> None:
        """Return a finished/failed request's blocks to the pool; its
        full prompt+output block chain enters the prefix index (a
        follow-up conversation turn extends the same chain). The
        slot's table row reverts to trash so post-finish garbage
        writes can't land in reallocated blocks."""
        if not self._paged or r.kv_alloc is None:
            return
        # kv_written gates the prefix-cache insert: a request that
        # failed BEFORE its prefill scatter holds zero/stale blocks —
        # caching them under the prompt's hashes would serve garbage
        # KV to every later request sharing the prefix. The FINAL
        # sampled token is excluded from the cached chain: each decode
        # step writes the PREVIOUS token's KV, so the last token's
        # position is never written — a stream ending exactly on a
        # block boundary would otherwise cache one stale position.
        stream = list(r.tokens) + list(r.out)
        if r.out:
            stream = stream[:-1]
        self._kv.free_seq(r.seq, stream, cache=r.kv_written)
        r.kv_alloc = None
        if slot is not None:
            self._tables[slot] = kvcache.TRASH
        self._kv_account()

    def _finish(self, r: _Request, slot: Optional[int]):
        self._record_done(r, error=False)
        self._free_kv(r, slot)
        if slot is not None and self._slots[slot] is r:
            self._slots[slot] = None
        if r.stream is not None:
            r.stream.put_nowait(None)
        if r.fut is not None and not r.fut.done():
            r.fut.set_result(True)

    def _expire(self, r: _Request, slot: Optional[int]):
        """Cancel one request whose deadline budget is spent (queued or
        mid-generation); its slot — if it held one — is reclaimed for
        the next admit pass."""
        from ray_tpu.serve.fault import DeadlineExceeded, fault_metrics
        fault_metrics()["deadline"].inc(tags={"where": "engine"})
        self._fail(r, slot, DeadlineExceeded(
            f"generation cancelled at the deadline after "
            f"{len(r.out)} token(s)"))

    def _fail(self, r: _Request, slot: Optional[int], e: BaseException):
        from ray_tpu.serve.fault import DeadlineExceeded
        self._record_done(r, error=True)
        self._free_kv(r, slot)
        # deadline cancellations cross the serve boundary TYPED so the
        # proxy can answer 504 instead of a generic 500
        err = e if isinstance(e, DeadlineExceeded) else RuntimeError(
            f"llm engine failed: {e}")
        if slot is not None and self._slots[slot] is r:
            self._slots[slot] = None
        if r.stream is not None:
            r.stream.put_nowait(err)  # raised by generate_stream
        if r.fut is not None and not r.fut.done():
            r.fut.set_exception(err)
