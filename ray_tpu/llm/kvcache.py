"""Paged KV cache: fixed-size token blocks, prefix reuse, COW, LRU.

The memory subsystem production serving needs (reference capability:
vLLM's PagedAttention — block tables over a fixed pool bound HBM by
LIVE tokens, and ref-counted block sharing lets requests with a common
system-prompt prefix skip prefill for the shared blocks). Rebuilt
TPU-native on the engine's static-shape rules:

- the POOL is one preallocated tensor pair per engine,
  ``(layers, num_blocks, block_size, kv_heads, head_dim)`` — shapes
  never change, so XLA compiles the paged decode step exactly once;
- each request owns a BLOCK TABLE (fixed width ``max_len //
  block_size``) of physical block ids; decode gathers the table's
  blocks into the attention view and scatters the new token's KV back
  through it (bitwise-identical to the monolithic cache: gathered
  values are the same bytes in the same order, and masked tail
  positions contribute exact zeros);
- a PREFIX CHAIN INDEX (hash-chained per full token block, the radix
  structure flattened into parent links) maps prompt prefixes to
  cached block chains: a request sharing a cached prefix adopts those
  blocks ref-counted and prefills only its suffix (lm.prefill_chunk at
  the prefix offset — the spike-verified bitwise-parity path);
- blocks are copy-on-write: a shared (or cached) block is never
  written; ``ensure_writable`` gives a forked sequence its own copy at
  the first divergent write;
- refcount-0 chains stay cached and are LRU-evicted LEAF-FIRST under
  pool pressure (a parent evicted before its child would orphan the
  child: chain lookups walk from the root).

Physical block 0 is the TRASH block: writes for finished/empty slots
and bucket-padding garbage are redirected there so freed blocks can be
reallocated immediately without a device sync.

Host bookkeeping (``KVBlockManager``) is pure python/numpy — unit-
testable without jax; device ops (pool init, gather/scatter, the paged
decode step) live beside it and are only imported by the engine.
"""

from __future__ import annotations

import hashlib
from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

TRASH = 0   # physical block 0: garbage-write target, never allocated


def kvcache_metrics() -> dict:
    """Get-or-create the paged-KV gauges/counters (shared process
    registry, pushed to the head like every llm_* series). Catalog:

      llm_kv_blocks_used          blocks referenced by live requests
      llm_kv_blocks_cached        refcount-0 blocks held by the prefix
                                  index (reclaimable via LRU eviction)
      llm_kv_blocks_evicted_total cached chains evicted under pressure
      llm_prefix_hit_tokens_total prompt tokens whose prefill was
                                  skipped via a prefix-cache hit
      llm_kv_handoff_bytes_total  KV bytes shipped prefill->decode at
                                  block granularity (llm/pd.py)
      llm_paged_attn_steps_total  paged decode steps by attention impl
                                  ({impl}: paged_flash | gather)
      llm_kv_gather_bytes_avoided_total
                                  HBM bytes the fused kernel did NOT
                                  copy materializing the gathered view
    """
    from ray_tpu.util import metrics as m
    return {
        "used": m.Gauge(
            "llm_kv_blocks_used",
            "KV pool blocks referenced by live requests"),
        "cached": m.Gauge(
            "llm_kv_blocks_cached",
            "Refcount-0 KV pool blocks held by the prefix index "
            "(reclaimable by LRU eviction)"),
        "evicted": m.Counter(
            "llm_kv_blocks_evicted_total",
            "Cached KV blocks evicted from the prefix index under "
            "pool pressure"),
        "hit_tokens": m.Counter(
            "llm_prefix_hit_tokens_total",
            "Prompt tokens served from cached prefix blocks instead "
            "of prefill compute"),
        "handoff_bytes": m.Counter(
            "llm_kv_handoff_bytes_total",
            "KV bytes shipped prefill->decode at block granularity "
            "in the disaggregated path"),
        "attn_steps": m.Counter(
            "llm_paged_attn_steps_total",
            "Paged decode steps taken, tagged by attention impl "
            "(paged_flash = fused block-table kernel, gather = "
            "materialized view)",
            tag_keys=("impl",)),
        "gather_avoided": m.Counter(
            "llm_kv_gather_bytes_avoided_total",
            "HBM bytes the fused paged-attention kernel avoided "
            "copying versus materializing the gathered "
            "(slots, max_len) attention view every decode step"),
    }


def chain_hashes(tokens: Sequence[int], block_size: int, *,
                 seed: bytes = b"", start_block: int = 0) -> List[str]:
    """One digest per FULL block of ``tokens`` from ``start_block``
    on; each digest covers the entire prefix up to that block's end
    (hash chaining), so equal digests imply equal prefixes — the
    prefix-index key. ``seed`` is the digest of block start_block-1
    (chain extension: free_seq continues a stored prompt chain over
    the generated tokens without rehashing the prompt)."""
    out: List[str] = []
    h = seed
    for i in range(start_block, len(tokens) // block_size):
        blk = tokens[i * block_size:(i + 1) * block_size]
        d = hashlib.blake2b(digest_size=16)
        d.update(h)
        d.update(np.asarray(blk, np.int64).tobytes())
        h = d.digest()
        out.append(h.hex())
    return out


@dataclass
class _CacheEntry:
    phys: int
    hash: str
    parent: Optional[str]       # previous block's chain hash
    children: int = 0           # cached continuations (evict leaves 1st)
    last_used: int = 0          # manager tick, LRU order


@dataclass
class _Seq:
    table: List[int]            # logical block idx -> physical id
    n_prompt: int
    hit_tokens: int
    hashes: List[str] = field(default_factory=list)  # full prompt blocks


class BlockPoolExhausted(RuntimeError):
    """The request can NEVER fit: its full horizon needs more blocks
    than the pool holds even if everything cacheable were evicted."""


class KVBlockManager:
    """Host-side accounting for one engine's block pool. Not
    thread-safe by itself — the engine serializes admits/frees on its
    scheduler loop, matching the monolithic cache's discipline."""

    def __init__(self, num_blocks: int, block_size: int, *,
                 table_width: int, prefix_cache: bool = True,
                 metrics: Optional[dict] = None):
        if num_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (one is trash)")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.table_width = int(table_width)
        self.prefix_cache = bool(prefix_cache)
        self.free: deque = deque(range(1, num_blocks))   # 0 = trash
        self.ref: Dict[int, int] = {}                    # phys -> count
        self.entries: Dict[str, _CacheEntry] = {}        # hash -> entry
        self.by_phys: Dict[int, _CacheEntry] = {}
        self.seqs: Dict[object, _Seq] = {}
        self.evicted_total = 0
        self.hit_tokens_total = 0
        self._tick = 0
        self._m = metrics

    # -- introspection ---------------------------------------------------

    def used_blocks(self) -> int:
        return sum(1 for c in self.ref.values() if c > 0)

    def cached_blocks(self) -> int:
        return sum(1 for h, e in self.entries.items()
                   if self.ref.get(e.phys, 0) == 0)

    def free_blocks(self) -> int:
        return len(self.free)

    def _publish(self) -> None:
        if self._m is None:
            return
        self._m["used"].set(self.used_blocks())
        self._m["cached"].set(self.cached_blocks())

    def blocks_needed(self, n_tokens: int, max_new: int) -> int:
        """Full-horizon reservation: admission allocates every block
        the request can ever touch, so decode can never fail mid-
        flight on pool pressure (the pool's overload answer is a
        queued admit, not a dropped stream)."""
        return -(-(n_tokens + max_new) // self.block_size)

    # -- prefix lookup ---------------------------------------------------

    def lookup(self, tokens: Sequence[int]) -> Tuple[int, List[int]]:
        """(hit_tokens, physical blocks) for the longest cached chain
        of FULL prompt blocks — capped one token short of the prompt
        so the last token's logits always come from live compute (a
        full-hit request still needs something to sample from)."""
        hit, phys, _ = self._lookup(tokens)
        return hit, phys

    def _lookup(self, tokens: Sequence[int]
                ) -> Tuple[int, List[int], List[str]]:
        """lookup + the prompt's chain hashes (alloc_seq records them
        on the sequence — hashing a long prompt once, not twice)."""
        hashes = chain_hashes(tokens, self.block_size) \
            if self.prefix_cache else []
        if not self.prefix_cache:
            return 0, [], hashes
        cap_blocks = (len(tokens) - 1) // self.block_size
        phys: List[int] = []
        self._tick += 1
        for h in hashes[:cap_blocks]:
            e = self.entries.get(h)
            if e is None:
                break
            e.last_used = self._tick
            phys.append(e.phys)
        return len(phys) * self.block_size, phys, hashes

    # -- allocation ------------------------------------------------------

    def alloc_seq(self, seq_id, tokens: Sequence[int],
                  max_new: int) -> Optional[dict]:
        """Admit one request: adopt the cached prefix (ref-counted),
        reserve fresh blocks for the rest of its horizon. Returns
        {"table": np.int32 (table_width,), "hit_tokens": int,
        "new_blocks": [phys]} — or None when the pool can't cover it
        right now (caller re-queues the request; eviction of
        refcount-0 chains was already attempted). Raises
        BlockPoolExhausted when the request can never fit."""
        if seq_id in self.seqs:
            raise ValueError(f"seq {seq_id!r} already allocated")
        n = len(tokens)
        total = self.blocks_needed(n, max_new)
        if total > self.table_width:
            raise BlockPoolExhausted(
                f"request horizon spans {total} blocks > table width "
                f"{self.table_width}")
        if total > self.num_blocks - 1:
            raise BlockPoolExhausted(
                f"request horizon needs {total} blocks; pool holds "
                f"{self.num_blocks - 1}")
        hit_tokens, hit_phys, hashes = self._lookup(tokens)
        # pin the hit blocks BEFORE any eviction: at refcount 0 they
        # are themselves eviction candidates once their chain suffix
        # is gone, and an evicted-then-reallocated hit block would
        # appear TWICE in the table (prefix view + fresh write target)
        # — silent KV corruption
        for p in hit_phys:
            self.ref[p] = self.ref.get(p, 0) + 1
        need = total - len(hit_phys)
        if need > len(self.free):
            self.evict(need - len(self.free))
        if need > len(self.free):
            for p in hit_phys:          # un-pin; caller re-queues
                self._release(p)
            return None
        table = np.full((self.table_width,), TRASH, np.int32)
        for i, p in enumerate(hit_phys):
            table[i] = p
        new_blocks = []
        for i in range(len(hit_phys), total):
            p = self.free.popleft()
            self.ref[p] = 1
            table[i] = p
            new_blocks.append(p)
        self.seqs[seq_id] = _Seq(list(table), n, hit_tokens, hashes)
        self.hit_tokens_total += hit_tokens
        if self._m is not None and hit_tokens:
            self._m["hit_tokens"].inc(hit_tokens)
        self._publish()
        return {"table": table, "hit_tokens": hit_tokens,
                "new_blocks": new_blocks}

    def _release(self, phys: int) -> None:
        """Drop one live reference; a block neither referenced nor
        cached returns to the free list."""
        c = self.ref.get(phys, 0) - 1
        if c > 0:
            self.ref[phys] = c
            return
        self.ref.pop(phys, None)
        if phys not in self.by_phys and phys != TRASH:
            self.free.append(phys)

    def free_seq(self, seq_id, out_tokens: Sequence[int] = (),
                 cache: bool = True) -> None:
        """Finish one request: insert its full-block chain (prompt +
        generated tokens — a follow-up turn extends the same chain)
        into the prefix index, then drop the live references. Cached
        blocks stay resident at refcount 0 until LRU eviction.
        ``cache=False`` skips the insert — REQUIRED for a request
        whose KV was never written (admit failed before the scatter):
        indexing its zero/stale blocks under the prompt's chain hashes
        would poison every later request sharing the prefix."""
        seq = self.seqs.pop(seq_id, None)
        if seq is None:
            return
        if self.prefix_cache and cache:
            # ``out_tokens`` is the FULL token stream (prompt +
            # generated) when the caller wants generated full blocks
            # cached too (a follow-up conversation turn extends the
            # same chain); absent, the alloc-time prompt hashes
            # serve. The stored prompt chain is EXTENDED from its
            # last digest — the prompt (a 100k shared context on the
            # target workload) is never rehashed at finish.
            hashes = seq.hashes
            if len(out_tokens) >= seq.n_prompt:
                seed = bytes.fromhex(hashes[-1]) if hashes else b""
                hashes = hashes + chain_hashes(
                    list(out_tokens), self.block_size, seed=seed,
                    start_block=len(hashes))
            self._tick += 1
            parent: Optional[str] = None
            for i, h in enumerate(hashes):
                phys = seq.table[i]
                if phys == TRASH:
                    break
                cur = self.entries.get(h)
                if cur is None:
                    # only cache blocks this seq exclusively owns or
                    # already-cached shared ones; a shared-but-uncached
                    # block (fork) must not be indexed under a hash
                    # another writer could invalidate
                    e = _CacheEntry(phys, h, parent,
                                    last_used=self._tick)
                    if phys in self.by_phys:
                        # same phys already cached under another hash
                        # (can't happen via chain hashing; guard)
                        break
                    self.entries[h] = e
                    self.by_phys[phys] = e
                    if parent is not None and parent in self.entries:
                        self.entries[parent].children += 1
                else:
                    cur.last_used = self._tick
                parent = h
        for phys in seq.table:
            if phys != TRASH:
                self._release(phys)
        self._publish()

    # -- copy-on-write / fork --------------------------------------------

    def fork_seq(self, src_id, dst_id) -> List[int]:
        """Share every block of ``src`` with a new sequence (parallel
        sampling / beam fork). Writes to shared blocks must go through
        ensure_writable."""
        src = self.seqs.get(src_id)
        if src is None:
            raise KeyError(src_id)
        if dst_id in self.seqs:
            raise ValueError(f"seq {dst_id!r} already allocated")
        for p in src.table:
            if p != TRASH:
                self.ref[p] = self.ref.get(p, 0) + 1
        self.seqs[dst_id] = _Seq(list(src.table), src.n_prompt,
                                 src.hit_tokens, list(src.hashes))
        self._publish()
        return list(src.table)

    def ensure_writable(self, seq_id,
                        logical: int) -> Optional[Tuple[int, int]]:
        """Copy-on-write guard: before writing into ``logical``, a
        block that is shared (refcount > 1) or held by the prefix
        index is replaced by a private copy. Returns (old_phys,
        new_phys) when the caller must issue the device block copy,
        None when the block was already private."""
        seq = self.seqs[seq_id]
        phys = seq.table[logical]
        if phys == TRASH:
            return None
        if self.ref.get(phys, 0) <= 1 and phys not in self.by_phys:
            return None
        if not self.free:
            self.evict(1)
        if not self.free:
            return None     # caller treats as pool pressure
        new = self.free.popleft()
        self.ref[new] = 1
        seq.table[logical] = new
        self._release(phys)
        self._publish()
        return phys, new

    def truncate_seq(self, seq_id, n_tokens: int, *,
                     min_blocks: int = 0) -> List[int]:
        """Roll a live sequence back to its first ``n_tokens`` tokens —
        the speculative-decode rejection path, and the branch-abandon
        primitive for COW forks. Table blocks whose every position lies
        beyond ``n_tokens`` are released (refcount decrement: a shared
        or prefix-indexed block survives for its other holders — the
        prefix index's own accounting is never touched) and the row is
        re-pointed at trash. The sequence's hash chain is cut to the
        full blocks ``n_tokens`` still covers, so a digest over
        truncated content can never reach the prefix index at
        ``free_seq`` — a rolled-back draft tail must never satisfy a
        later prefix hit.

        ``min_blocks`` keeps at least that many leading table rows
        (the engine passes its full-horizon reservation so a rollback
        never returns blocks admission already promised the request —
        re-acquiring them later could deadlock against a newer admit).
        No device op: rejected-draft KV lives beyond the sequence's
        logical length, so it is masked out of every attention (exact
        zeros) and overwritten by the next real write at that position.
        Returns the physical blocks released."""
        seq = self.seqs.get(seq_id)
        if seq is None:
            raise KeyError(seq_id)
        keep = max(-(-n_tokens // self.block_size), min_blocks)
        freed: List[int] = []
        for i in range(len(seq.table) - 1, keep - 1, -1):
            phys = seq.table[i]
            if phys == TRASH:
                continue
            seq.table[i] = TRASH
            self._release(phys)
            freed.append(phys)
        seq.hashes = seq.hashes[:n_tokens // self.block_size]
        seq.n_prompt = min(seq.n_prompt, n_tokens)
        self._publish()
        return freed

    # -- eviction --------------------------------------------------------

    def evict(self, k: int) -> int:
        """Evict up to ``k`` cached refcount-0 blocks, LRU leaf-first
        (children evict before parents so surviving chains stay
        walkable from the root). One heapify + O(k log n) — this runs
        on the engine's serialized admit path, so a per-block rescan
        of every cache entry would stall in-flight streams under a
        large prefix cache. Returns blocks actually freed."""
        import heapq
        heap = [(e.last_used, e.hash) for e in self.entries.values()
                if e.children == 0 and self.ref.get(e.phys, 0) == 0]
        heapq.heapify(heap)
        freed = 0
        while freed < k and heap:
            _, h = heapq.heappop(heap)
            e = self.entries.get(h)
            if e is None or e.children != 0 \
                    or self.ref.get(e.phys, 0) != 0:
                continue            # stale heap entry
            del self.entries[h]
            self.by_phys.pop(e.phys, None)
            if e.parent is not None:
                p = self.entries.get(e.parent)
                if p is not None:
                    p.children -= 1
                    if p.children == 0 and \
                            self.ref.get(p.phys, 0) == 0:
                        heapq.heappush(heap, (p.last_used, p.hash))
            self.free.append(e.phys)
            freed += 1
            self.evicted_total += 1
            if self._m is not None:
                self._m["evicted"].inc()
        if freed:
            self._publish()
        return freed


# --- device ops (jax only from here down) ------------------------------


def _jx():
    import jax
    import jax.numpy as jnp
    return jax, jnp


def init_pool(cfg, num_blocks: int, block_size: int, dtype) -> dict:
    """The pool tensors: k/v of shape
    (layers, num_blocks, block_size, kv_heads, head_dim)."""
    _, jnp = _jx()
    shape = (cfg.n_layers, num_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def pool_block_bytes(pool: dict) -> int:
    """Device bytes one block costs (k + v, all layers)."""
    nb = pool["k"].shape[1]
    return (pool["k"].nbytes + pool["v"].nbytes) // nb


def auto_pool_blocks(slots: int, table_width: int, block_bytes: int,
                     configured: int = 0) -> int:
    """Pool size: the explicit knob wins; otherwise worst case (every
    slot at max_len) plus one full chain of prefix-cache headroom,
    capped by the devmon HBM headroom gauges when the backend reports
    them (half the free HBM — the engine is not the only tenant).
    The cap never shrinks below ONE full-horizon request
    (table_width blocks): a max_len-sized request must be servable —
    serially — on any pool the engine auto-sizes, matching what the
    monolithic cache guarantees."""
    if configured:
        return max(2, int(configured))
    base = slots * table_width + table_width
    try:
        from ray_tpu.util import devmon
        rows = devmon.hbm_snapshot(record=False)
        headrooms = [r["limit_bytes"] - r["used_bytes"] for r in rows
                     if r.get("limit_bytes")]
        if headrooms:
            cap = int(min(headrooms) * 0.5 // max(1, block_bytes))
            base = max(table_width, min(base, cap))
    except Exception:   # noqa: BLE001 — sizing hint only
        pass
    return base + 1     # + trash block


_JITS: dict = {}    # (op, pool geometry, dtype) -> jitted callable


def _pool_key(pool: dict) -> tuple:
    """Cache-key component identifying one pool's compiled geometry."""
    return (tuple(pool["k"].shape), str(pool["k"].dtype))


def _jit(name: str, pool: dict):
    """Build-once cache for the jitted device ops: jax must not be
    imported at module import time (the engine's lazy-import rule),
    and a fresh jax.jit wrapper per call would retrace every call.
    Keyed on (op, pool geometry, dtype) — NOT op name alone: one
    process serving two model configs (two replicas, a debug engine
    next to a prod one) must not replay a callable whose donated
    buffers and reshape constants were traced for the other pool's
    shape."""
    key = (name, *_pool_key(pool))
    fn = _JITS.get(key)
    if fn is not None:
        return fn
    jax, jnp = _jx()

    if name == "scatter_bucket":
        @partial(jax.jit, donate_argnums=(0,), static_argnames=("nb",))
        def fn(pool, kv, phys, nb):
            L = kv["k"].shape[0]
            bs = pool["k"].shape[2]
            k = kv["k"].reshape(L, nb, bs, *kv["k"].shape[2:])
            v = kv["v"].reshape(L, nb, bs, *kv["v"].shape[2:])
            return {"k": pool["k"].at[:, phys].set(
                        k.astype(pool["k"].dtype)),
                    "v": pool["v"].at[:, phys].set(
                        v.astype(pool["v"].dtype))}
    elif name == "gather_table":
        @partial(jax.jit, static_argnames=("acc_len",))
        def fn(pool, phys, acc_len):
            L, _, bs, kvh, hd = pool["k"].shape
            w = phys.shape[0]
            out = {}
            for key in ("k", "v"):
                g = pool[key][:, phys]           # (L, w, bs, kvh, hd)
                g = g.reshape(L, w * bs, kvh, hd)
                pad = acc_len - w * bs
                if pad > 0:
                    g = jnp.pad(g, ((0, 0), (0, pad), (0, 0), (0, 0)))
                out[key] = g
            return out
    elif name == "scatter_table":
        @partial(jax.jit, donate_argnums=(0,))
        def fn(pool, acc, phys):
            L, _, bs, kvh, hd = pool["k"].shape
            w = phys.shape[0]
            out = {}
            for key in ("k", "v"):
                a = acc[key][:, :w * bs].reshape(L, w, bs, kvh, hd)
                out[key] = pool[key].at[:, phys].set(
                    a.astype(pool[key].dtype))
            return out
    elif name == "copy_block":
        @partial(jax.jit, donate_argnums=(0,))
        def fn(pool, src, dst):
            return {"k": pool["k"].at[:, dst].set(pool["k"][:, src]),
                    "v": pool["v"].at[:, dst].set(pool["v"][:, src])}
    else:
        raise KeyError(name)
    _JITS[key] = fn
    return fn


def scatter_bucket(pool: dict, kv: dict, phys, nb: int) -> dict:
    """Write a bucket-padded prefill's KV into ``nb`` physical blocks
    (pad-garbage blocks redirected to trash by the caller's phys).
    One compile per bucket size."""
    return _jit("scatter_bucket", pool)(pool, kv, phys, nb)


def gather_table(pool: dict, phys, acc_len: int) -> dict:
    """Gather one block table's KV into a contiguous accumulator
    (layers, acc_len, kvh, hd) for chunked prefill over a cached
    prefix. acc_len >= table_width * block_size (zero tail). No
    longer on the decode hot path — decode attends straight through
    the table (ops/pallas/paged_attention.py); this stays for the
    prefix-hit prefill accumulator and debug/parity tooling."""
    return _jit("gather_table", pool)(pool, phys, acc_len)


def scatter_table(pool: dict, acc: dict, phys) -> dict:
    """Write an accumulator back through a full-width physical target
    vector (shared-prefix and beyond-horizon slots point at trash so
    shared blocks are never written). One compile total."""
    return _jit("scatter_table", pool)(pool, acc, phys)


def copy_block(pool: dict, src: int, dst: int) -> dict:
    """Device-side block copy (the COW divergence path)."""
    _, jnp = _jx()
    return _jit("copy_block", pool)(pool, jnp.int32(src),
                                    jnp.int32(dst))


def resolve_attn_impl(impl: str) -> str:
    """Resolve the paged decode attention impl knob. ``auto`` picks
    the fused block-table kernel on a real TPU backend and the gather
    view elsewhere (CPU tier-1 still exercises the kernel explicitly
    via impl='paged_flash' + interpret)."""
    if impl not in ("auto", "paged_flash", "gather"):
        raise ValueError(
            f"paged attn impl must be auto|paged_flash|gather, "
            f"got {impl!r}")
    if impl == "auto":
        from ray_tpu.ops.attention import _on_tpu
        return "paged_flash" if _on_tpu() else "gather"
    return impl


def _paged_decode_core(params, pool, tables, lengths, tokens, temps,
                       key, cfg, top_ps=None, top_ks=None, *,
                       impl="gather", interpret=False, mesh=None,
                       axis="tensor"):
    """One token for every slot against the paged pool. Runs
    lm.decode_token_core — the SAME transformer body as the monolithic
    cache — with block-table write/attend plugged in.

    impl='gather': the attention view is materialized per layer as
    ck[tables].reshape(b, W*bs, kvh, hd) — the gathered view holds the
    same bytes in the same order as the monolithic cache, so the
    attention math (and therefore the sampled tokens) is bitwise
    identical (pinned by tests/test_zz_kvcache.py parity tests).

    impl='paged_flash': the pallas kernel walks the block table
    directly (ops/pallas/paged_attention.py) — no gathered view, no
    O(slots x max_len x layers) copy per emitted token. Same f32
    attention math; online softmax agrees with the gather path to f32
    rounding (bitwise on the integer constructions
    tests/test_zz_paged_attn.py pins).

    With ``mesh``, the kernel path runs under shard_map: kv heads
    sharded over ``axis``, block tables/lengths replicated — each
    shard walks the same tables over its own head slice, no
    collectives (the gather path needs nothing: GSPMD partitions the
    plain-jnp view fine)."""
    jax, jnp = _jx()
    from ray_tpu.llm.model import decode_token_core
    b = tokens.shape[0]
    bs = pool["k"].shape[2]
    w = tables.shape[1]
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    positions = lengths
    blk = jnp.clip(positions // bs, 0, w - 1)
    off = positions % bs
    phys = tables[jnp.arange(b), blk]

    def write(ck, cv, k, v):    # ck/cv: (num_blocks, bs, kvh, hd)
        return (ck.at[phys, off].set(k.astype(ck.dtype)),
                cv.at[phys, off].set(v.astype(cv.dtype)))

    def view(ck, cv):
        return (ck[tables].reshape(b, w * bs, kvh, hd),
                cv[tables].reshape(b, w * bs, kvh, hd))

    attend = None
    if impl == "paged_flash":
        from ray_tpu.ops.pallas.paged_attention import paged_attention

        def _kernel(qg, ck, cv, tb, ln):
            return paged_attention(qg, ck, cv, tb, ln,
                                   interpret=interpret)

        def attend(q, ck, cv, pos):     # q: (b, h, hd)
            g = cfg.n_heads // kvh
            qg = q.reshape(b, kvh, g, hd)
            if mesh is not None:
                from jax.sharding import PartitionSpec as P
                from ray_tpu.ops import shard_map
                t = axis
                fn = shard_map(
                    _kernel, mesh,
                    in_specs=(P(None, t, None, None),
                              P(None, None, t, None),
                              P(None, None, t, None), P(), P()),
                    out_specs=P(None, t, None, None),
                    check_vma=False)
            else:
                fn = _kernel
            o = fn(qg, ck, cv, tables, pos + 1)
            return o.reshape(b, cfg.n_heads * hd)

    out, nk, nv = decode_token_core(
        params, pool["k"], pool["v"], tokens, positions, temps, key,
        cfg, write, view, top_ps, top_ks, attend)
    return out, {"k": nk, "v": nv}


def paged_decode_steps(params, pool, tables, lengths, tokens, temps,
                       key, cfg, n: int, top_ps=None, top_ks=None, *,
                       impl="gather", interpret=False, mesh=None,
                       axis="tensor"):
    """n chained decode steps against the block pool in ONE dispatch —
    the paged twin of lm.decode_steps (same fold_in schedule, same
    block semantics; slots past their request produce discardable
    garbage in the trash block). ``impl``/``interpret``/``mesh`` are
    trace-time constants — each combination (x pool geometry) compiles
    its own variant, cached in _JITS."""
    impl = resolve_attn_impl(impl)
    key_ = ("paged_decode_steps", *_pool_key(pool), impl,
            bool(interpret), mesh, axis)
    fn = _JITS.get(key_)
    if fn is None:
        jax, jnp = _jx()
        from jax import lax as _lax

        @partial(jax.jit, static_argnames=("cfg", "n"),
                 donate_argnums=(1,))
        def fn(params, pool, tables, lengths, tokens, temps, key, cfg,
               n, top_ps, top_ks):
            def body(carry, i):
                pool, toks = carry
                out, pool = _paged_decode_core(
                    params, pool, tables, lengths + i, toks, temps,
                    jax.random.fold_in(key, i), cfg, top_ps, top_ks,
                    impl=impl, interpret=interpret, mesh=mesh,
                    axis=axis)
                return (pool, out), out
            (pool, _), outs = _lax.scan(body, (pool, tokens),
                                        jnp.arange(n, dtype=jnp.int32))
            return outs, pool
        _JITS[key_] = fn
    return fn(params, pool, tables, lengths, tokens, temps, key,
              cfg, n, top_ps, top_ks)


def _paged_verify_core(params, pool, tables, lengths, tokens, cfg, *,
                       impl="gather", interpret=False, mesh=None,
                       axis="tensor"):
    """Speculative verify against the block pool: score w in-flight
    tokens per slot (last emitted + up to w-1 drafts) in ONE forward.
    Runs lm.verify_tokens_core — decode_token_core widened to w — with
    the block-table write/attend plugged in, so verify numerics can
    never drift from sequential paged decode.

    tokens: (b, w) int32, column 0 at cache position ``lengths``;
    writes all w KVs through the table (positions past a slot's table
    clamp into its last row — within the full-horizon reservation
    those writes land beyond the logical length, masked out of every
    attention and overwritten by the next real write, so no rollback
    device op exists). Returns ((b, w, vocab) f32 logits, pool): row j
    is the distribution for position lengths+j+1, the verdict on
    draft j+1. Acceptance is a host decision (llm/spec.py) — the
    device ships w*vocab floats per slot per ROUND, not per token.

    impl='paged_flash' uses the gather-twin multi-query attention
    (ops/pallas/paged_attention.paged_attention_verify) — the fused
    single-query kernel doesn't take multi-query rows yet; the twin
    still gathers ONCE per round where sequential decode gathered per
    token, which is the spec-decode win the bench measures."""
    jax, jnp = _jx()
    from ray_tpu.llm.model import verify_tokens_core
    b, wq = tokens.shape
    bs = pool["k"].shape[2]
    w = tables.shape[1]
    kvh, hd = cfg.n_kv_heads, cfg.head_dim
    positions = lengths
    pos = positions[:, None] + jnp.arange(wq, dtype=jnp.int32)[None]
    blk = jnp.clip(pos // bs, 0, w - 1)
    off = pos % bs
    phys = jnp.take_along_axis(tables, blk, axis=1)     # (b, wq)

    def write(ck, cv, k, v):    # k/v: (b, wq, kvh, hd)
        return (ck.at[phys, off].set(k.astype(ck.dtype)),
                cv.at[phys, off].set(v.astype(cv.dtype)))

    def view(ck, cv):
        return (ck[tables].reshape(b, w * bs, kvh, hd),
                cv[tables].reshape(b, w * bs, kvh, hd))

    attend = None
    if impl == "paged_flash":
        from ray_tpu.ops.pallas.paged_attention import (
            paged_attention_verify)

        def attend(q, ck, cv, pos_grid):    # q: (b, wq, h, hd)
            g = cfg.n_heads // kvh
            qg = q.reshape(b, wq, kvh, g, hd)
            if mesh is not None:
                from jax.sharding import PartitionSpec as P
                from ray_tpu.ops import shard_map
                t = axis
                fn = shard_map(
                    paged_attention_verify, mesh,
                    in_specs=(P(None, None, t, None, None),
                              P(None, None, t, None),
                              P(None, None, t, None), P(), P()),
                    out_specs=P(None, None, t, None, None),
                    check_vma=False)
            else:
                fn = paged_attention_verify
            o = fn(qg, ck, cv, tables, pos_grid + 1)
            return o.reshape(b, wq, cfg.n_heads * hd)

    logits, nk, nv = verify_tokens_core(
        params, pool["k"], pool["v"], tokens, positions, cfg,
        write, view, attend)
    return logits, {"k": nk, "v": nv}


def paged_verify_steps(params, pool, tables, lengths, tokens, cfg, *,
                       impl="gather", interpret=False, mesh=None,
                       axis="tensor"):
    """One speculative verify round in one dispatch — the verify twin
    of paged_decode_steps. tokens: (b, w) with w drawn from the
    engine's verify-width buckets; each (pool geometry, w, impl)
    combination compiles exactly once, cached in _JITS (the
    compile-discipline tests count both the _JITS keys and devmon's
    jit(paged_verify_steps) compile spans)."""
    impl = resolve_attn_impl(impl)
    wq = int(tokens.shape[1])
    key_ = ("paged_verify_steps", wq, *_pool_key(pool), impl,
            bool(interpret), mesh, axis)
    fn = _JITS.get(key_)
    if fn is None:
        jax, _ = _jx()

        @partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
        def paged_verify_steps(params, pool, tables, lengths, tokens,
                               cfg):
            return _paged_verify_core(
                params, pool, tables, lengths, tokens, cfg, impl=impl,
                interpret=interpret, mesh=mesh, axis=axis)
        fn = paged_verify_steps
        _JITS[key_] = fn
    return fn(params, pool, tables, lengths, tokens, cfg)
