"""Cache-aware llama forwards for inference: prefill + single-token decode.

The model side of the LLM serving stack (reference:
python/ray/llm/_internal/serve/... wraps vLLM; here the engine is native:
the training model in models/llama.py is reused — same params, same
config — with two inference-shaped entry points that XLA compiles once
per shape bucket):

- `prefill`: full-sequence forward that also emits per-layer K/V, written
  into a static-shape slot cache (TPU rule: no dynamic shapes — prompts
  are padded to a bucket, the cache is (layers, slots, max_len, kvh, hd)).
- `decode_step`: one token for every active slot, attending against the
  cache with a position mask. Batch dimension = slots, so the MXU sees
  one batched matmul per layer regardless of how many requests are live.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models.llama import (LlamaConfig, _rmsnorm, _rope,
                                  _rope_tables)


def bucket_for(buckets, n: int) -> int:
    """Smallest prefill shape bucket holding an n-token prompt (shared
    by the unified and disaggregated engines so the policy can't
    drift)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_prompt(tokens, bucket: int):
    """Zero-pad a prompt to its bucket (numpy, int32)."""
    import numpy as np
    out = np.zeros((bucket,), np.int32)
    out[:len(tokens)] = tokens
    return out


def init_cache(cfg: LlamaConfig, slots: int, max_len: int,
               dtype=jnp.bfloat16, mesh: Optional[Mesh] = None,
               axis: str = "tensor") -> dict:
    """Static KV slot cache. With a mesh, k/v shard their KV-head dim
    over the tensor axis — the engine's decode attention then runs
    fully local per tensor shard (Megatron layout)."""
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
             "length": jnp.zeros((slots,), jnp.int32)}
    if mesh is not None:
        kv_s = NamedSharding(mesh, P(None, None, None, axis, None))
        rep = NamedSharding(mesh, P())
        cache = {"k": jax.device_put(cache["k"], kv_s),
                 "v": jax.device_put(cache["v"], kv_s),
                 "length": jax.device_put(cache["length"], rep)}
    return cache


def serve_param_specs(cfg: LlamaConfig, axis: str = "tensor") -> dict:
    """Megatron tensor-parallel PartitionSpecs for INFERENCE: attention
    heads and ffn split over `axis`; the row-parallel matmuls (wo,
    w_down) reduce over it (GSPMD inserts the psum). Unlike training's
    param_shardings there is no fsdp dim — serving replicates what it
    doesn't tensor-split, trading memory for zero gather latency on the
    decode critical path. Reference capability: vLLM's
    tensor_parallel_size per replica
    (llm/_internal/serve/configs/llm_config.py:181-186)."""
    t = axis
    return {
        "embed": P(None, None),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, None, t),
            "wk": P(None, None, t),
            "wv": P(None, None, t),
            "wo": P(None, t, None),
            "mlp_norm": P(None, None),
            "w_gate": P(None, None, t),
            "w_up": P(None, None, t),
            "w_down": P(None, t, None),
        },
        "final_norm": P(None),
        "lm_head": P(None, t),
    }


def shard_params_for_serving(params: dict, mesh: Mesh, cfg: LlamaConfig,
                             axis: str = "tensor") -> dict:
    """Place params on the mesh per serve_param_specs. Validates the
    divisibility the layout needs (heads, kv heads, ffn, vocab all
    split over the tensor axis)."""
    tp = mesh.shape[axis]
    for name, n in (("n_heads", cfg.n_heads),
                    ("n_kv_heads", cfg.n_kv_heads),
                    ("ffn_dim", cfg.ffn_dim),
                    ("vocab_size", cfg.vocab_size)):
        if n % tp:
            raise ValueError(
                f"{name}={n} not divisible by tensor-parallel size {tp}")
    specs = serve_param_specs(cfg, axis)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, specs, is_leaf=lambda x: isinstance(x, P))


def _qkv(y, lp, cfg: LlamaConfig):
    b, s = y.shape[:2]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q = (y @ lp["wq"]).reshape(b, s, h, hd)
    k = (y @ lp["wk"]).reshape(b, s, kvh, hd)
    v = (y @ lp["wv"]).reshape(b, s, kvh, hd)
    return q, k, v


def _gqa_attend_cached(q, cache_k, cache_v, lengths, cfg: LlamaConfig):
    """q: (b, h, hd) current-token queries; cache_k/v: (b, L, kvh, hd);
    lengths: (b,) valid cache entries per slot (incl. current token)."""
    b = q.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    g = h // kvh
    qg = q.reshape(b, kvh, g, hd).astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    scores = jnp.einsum("bkgd,blkd->bkgl", qg, kf) / jnp.sqrt(hd)
    mask = jnp.arange(cache_k.shape[1])[None] < lengths[:, None]  # (b, L)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgl,blkd->bkgd", probs,
                     cache_v.astype(jnp.float32))
    return out.reshape(b, h * hd)


def _serve_attn_impl(cfg: LlamaConfig) -> str:
    """Map the model's attn_impl onto the serving prefill dispatch:
    'ring' is a training-only (context-parallel) layout — serving falls
    back to 'auto' (flash on TPU for long prompts, reference
    elsewhere)."""
    impl = getattr(cfg, "attn_impl", "auto")
    return "auto" if impl == "ring" else impl


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill(params: dict, tokens: jax.Array, length: jax.Array,
            cfg: LlamaConfig, max_len: int) -> Tuple[jax.Array, dict]:
    """One padded prompt. tokens: (s,) int32 (padded to a bucket);
    length: () actual prompt length. Returns (last-token logits (vocab,),
    per-layer kv padded to max_len: k/v (layers, max_len, kvh, hd)).

    Attention dispatches through ops.attention (cfg.attn_impl): the
    pallas flash kernel tiles long prompts on TPU instead of
    materializing the O(s^2) score tensor. Causal alone is exact here:
    pad keys sit at positions >= length, and every USED query row is
    < length, so causality already excludes them (pad rows' outputs are
    garbage but only row length-1 is read)."""
    from ray_tpu.ops.attention import attention as _attention
    s = tokens.shape[0]
    x = jnp.take(params["embed"], tokens[None], axis=0)  # (1, s, emb)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    rc, rs = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def layer(x, lp):
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        h, hd = cfg.n_heads, cfg.head_dim
        o = _attention(q, k, v, causal=True, sm_scale=hd ** -0.5,
                       impl=_serve_attn_impl(cfg))
        o = o.reshape(1, s, h * hd).astype(x.dtype)
        x = x + o @ lp["wo"]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (k[0], v[0])

    x, (ks, vs) = lax.scan(layer, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take(x[0], length - 1, axis=0)
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    # pad kv (layers, s, kvh, hd) -> (layers, max_len, kvh, hd)
    pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]
    return logits, {"k": jnp.pad(ks, pad), "v": jnp.pad(vs, pad)}


def prefill_chunk(params: dict, tokens: jax.Array, length: jax.Array,
                  offset, acc: dict,
                  cfg: LlamaConfig) -> Tuple[jax.Array, dict]:
    """One CHUNK of a long prompt: process `tokens` (one padded bucket)
    starting at absolute position `offset`, attending to all earlier
    chunks' K/V in `acc` plus causally within the chunk. Lets prompts
    longer than the largest prefill bucket stream through in
    bucket-sized pieces at O(chunk x max_len) attention per piece —
    long-prompt serving without a max_len-sized compile per prompt
    (reference capability: vLLM chunked prefill).

    tokens: (s,) int32 padded chunk; length: () valid tokens in it;
    offset: () absolute start position; acc: {"k","v"}
    (layers, max_len, kvh, hd), donated — earlier chunks' KV, updated
    in place with this chunk's. Returns (logits of the chunk's last
    valid token (vocab,), updated acc). Positions in acc beyond
    offset+length may hold pad garbage; every consumer masks by total
    length, so it is never attended to.

    Dispatch: flash-capable impls route to the pallas kernel with the
    chunk's absolute offset placing the causal diagonal (one compile
    per distinct offset — offsets are chunk-size multiples, so at most
    ceil(max_len / chunk) variants); otherwise the dynamic-offset XLA
    path below compiles once."""
    from ray_tpu.ops.attention import _on_tpu
    impl = _serve_attn_impl(cfg)
    if impl == "flash" or impl == "flash_interpret" or (
            impl == "auto" and _on_tpu() and tokens.shape[0] >= 128):
        if impl == "auto":
            impl = "flash"
        return _prefill_chunk_flash(params, tokens, length, int(offset),
                                    acc, cfg, impl)
    return _prefill_chunk_dyn(params, tokens, length,
                              jnp.asarray(offset, jnp.int32), acc, cfg)


@partial(jax.jit, static_argnames=("cfg", "offset", "impl"),
         donate_argnums=(4,))
def _prefill_chunk_flash(params: dict, tokens: jax.Array,
                         length: jax.Array, offset: int, acc: dict,
                         cfg: LlamaConfig, impl: str):
    """Flash chunked prefill: the kernel's q_offset places the causal
    diagonal at the chunk's absolute position, so no O(s x L) mask or
    score tensor is materialized. Causal alone is exact for every USED
    query row (see prefill)."""
    from ray_tpu.ops.attention import attention as _attention
    s = tokens.shape[0]
    h, hd = cfg.n_heads, cfg.head_dim
    x = jnp.take(params["embed"], tokens[None], axis=0)     # (1, s, emb)
    positions = (offset + jnp.arange(s, dtype=jnp.int32))[None]
    rc, rs = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def layer(carry, xs):
        x = carry
        lp, ak, av = xs     # ak/av: (L, kvh, hd) this layer's acc
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        ak = lax.dynamic_update_slice(
            ak, k[0].astype(ak.dtype),
            (jnp.int32(offset), jnp.int32(0), jnp.int32(0)))
        av = lax.dynamic_update_slice(
            av, v[0].astype(av.dtype),
            (jnp.int32(offset), jnp.int32(0), jnp.int32(0)))
        o = _attention(q, ak[None].astype(q.dtype),
                       av[None].astype(q.dtype), causal=True,
                       sm_scale=hd ** -0.5, impl=impl, q_offset=offset)
        o = o.reshape(1, s, h * hd).astype(x.dtype)
        x = x + o @ lp["wo"]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (ak, av)

    x, (nk, nv) = lax.scan(layer, x, (params["layers"],
                                      acc["k"], acc["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take(x[0], length - 1, axis=0)
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": nk, "v": nv}


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(4,))
def _prefill_chunk_dyn(params: dict, tokens: jax.Array,
                       length: jax.Array, offset: jax.Array, acc: dict,
                       cfg: LlamaConfig) -> Tuple[jax.Array, dict]:
    """Dynamic-offset XLA path (single compile; O(s x L) scores)."""
    s = tokens.shape[0]
    L = acc["k"].shape[1]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    g = h // kvh
    x = jnp.take(params["embed"], tokens[None], axis=0)     # (1, s, emb)
    positions = (offset + jnp.arange(s, dtype=jnp.int32))[None]
    rc, rs = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)
    q_pos = positions[0]                                    # (s,)
    k_pos = jnp.arange(L, dtype=jnp.int32)                  # (L,)
    # causal over ABSOLUTE positions (covers both earlier chunks and
    # intra-chunk order), limited to valid keys
    m = (k_pos[None, :] <= q_pos[:, None]) & \
        (k_pos[None, :] < offset + length)

    def layer(carry, xs):
        x = carry
        lp, ak, av = xs     # ak/av: (L, kvh, hd) this layer's acc
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        ak = lax.dynamic_update_slice(
            ak, k[0].astype(ak.dtype), (offset, jnp.int32(0), jnp.int32(0)))
        av = lax.dynamic_update_slice(
            av, v[0].astype(av.dtype), (offset, jnp.int32(0), jnp.int32(0)))
        qg = q[0].reshape(s, kvh, g, hd).astype(jnp.float32)
        kf = ak.astype(jnp.float32)                         # (L, kvh, hd)
        scores = jnp.einsum("skgd,lkd->kgsl", qg, kf) / jnp.sqrt(hd)
        scores = jnp.where(m[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("kgsl,lkd->skgd", probs,
                       av.astype(jnp.float32))
        o = o.reshape(1, s, h * hd).astype(x.dtype)
        x = x + o @ lp["wo"]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (ak, av)

    x, (nk, nv) = lax.scan(layer, x, (params["layers"],
                                      acc["k"], acc["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take(x[0], length - 1, axis=0)
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    return logits, {"k": nk, "v": nv}


def sample(logits: jax.Array, temps: jax.Array, key: jax.Array,
           top_ps: Optional[jax.Array] = None,
           top_ks: Optional[jax.Array] = None) -> jax.Array:
    """Per-slot sampling ON DEVICE: greedy where temp<=0, else
    temperature -> top-k -> top-p -> categorical (the standard filter
    order; reference capability = vLLM's SamplingParams temperature/
    top_p/top_k). Keeping sampling inside the jitted step means each
    decode ships 4 bytes per slot to the host instead of the full vocab
    logits — the device->host link (PCIe, or a network tunnel in this
    environment) must never carry O(vocab) per token.

    top_ks: (slots,) int32, 0 disables; top_ps: (slots,) f32 in (0,1],
    1.0 disables. Both filters run as sorts + masks over the vocab —
    O(V log V) on the VPU, negligible next to the decode matmuls."""
    b, v = logits.shape
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    masked = filter_logits(scaled, top_ks, top_ps)
    keys = jax.random.split(key, b)
    drawn = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, drawn)


def filter_logits(scaled, top_ks=None, top_ps=None):
    """The top-k -> top-p logits mask, shared by the on-device sampler
    (`sample`, above) and the HOST-side rejection-sampling acceptance
    in speculative decoding (llm/spec.py). The host sampler is what
    the device sampler is parity-tested against, and the speculative
    accept must judge draft tokens under exactly the distribution the
    device would sample from — so there is ONE implementation of the
    filter order, generic over jnp (traced inside jit) and plain
    numpy (host float arrays). `scaled` is logits already divided by
    temperature, shape (slots, vocab); top_ks (slots,) int32 with 0
    disabling; top_ps (slots,) f32 in (0, 1] with 1.0 disabling.
    Returns masked logits with filtered entries at -inf."""
    import numpy as np
    onp = isinstance(scaled, np.ndarray)
    xp = np if onp else jnp
    v = scaled.shape[-1]
    masked = scaled
    if top_ks is not None:
        desc = xp.sort(scaled, axis=-1)[:, ::-1]
        kth = xp.take_along_axis(
            desc, xp.clip(top_ks - 1, 0, v - 1)[:, None], axis=1)
        masked = xp.where((top_ks[:, None] > 0) & (scaled < kth),
                          -xp.inf, masked)
    if top_ps is not None:
        if onp:
            e = np.exp(masked - np.max(masked, axis=-1, keepdims=True))
            probs = e / np.sum(e, axis=-1, keepdims=True)
        else:
            probs = jax.nn.softmax(masked, axis=-1)
        sp = xp.sort(probs, axis=-1)[:, ::-1]
        cum = xp.cumsum(sp, axis=-1)
        # nucleus rule: keep the smallest prefix of the sorted probs
        # whose mass reaches p — i.e. tokens whose EXCLUSIVE cumulative
        # mass is still < p (the top token always survives)
        keep = (cum - sp) < top_ps[:, None]
        thresh = xp.min(xp.where(keep, sp, xp.inf), axis=-1)
        enabled = (top_ps < 1.0)[:, None]
        masked = xp.where(enabled & (probs < thresh[:, None]),
                          -xp.inf, masked)
    return masked


def decode_token_core(params: dict, kcache: jax.Array,
                      vcache: jax.Array, tokens: jax.Array,
                      positions: jax.Array, temps: jax.Array,
                      key: jax.Array, cfg: LlamaConfig,
                      write, view,
                      top_ps: Optional[jax.Array] = None,
                      top_ks: Optional[jax.Array] = None,
                      attend=None):
    """THE decode-step transformer, shared by the monolithic slot
    cache and the paged block pool (llm/kvcache.py) so the two can
    never drift numerically — the paged engine's bitwise-parity
    contract hangs on both running exactly this op sequence. The
    cache layout is abstracted by two callables applied per layer:
    ``write(ck, cv, k, v) -> (ck, cv)`` appends the new token's KV
    (k/v: (slots, kvh, hd)); ``view(ck, cv) -> (vk, vv)`` yields the
    (slots, L, kvh, hd) attention view. ``attend(q, ck, cv,
    positions) -> (slots, h*hd) f32`` REPLACES the view +
    _gqa_attend_cached pair when set — the paged-flash path computes
    attention straight through the block table without ever
    materializing the view (ops/pallas/paged_attention.py). Returns
    (sampled tokens, new kcache, new vcache)."""
    x = jnp.take(params["embed"], tokens[:, None], axis=0)  # (b, 1, emb)
    rc, rs = _rope_tables(positions[:, None], cfg.head_dim,
                          cfg.rope_theta)

    def layer(carry, xs):
        x = carry
        lp, ck, cv = xs
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)  # (b, 1, ...)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        ck, cv = write(ck, cv, k[:, 0], v[:, 0])
        if attend is not None:
            o = attend(q[:, 0], ck, cv, positions)
        else:
            vk, vv = view(ck, cv)
            o = _gqa_attend_cached(q[:, 0], vk, vv, positions + 1, cfg)
        x = x + (o.astype(x.dtype) @ lp["wo"])[:, None]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (ck, cv)

    x, (nk, nv) = lax.scan(layer, x, (params["layers"],
                                      kcache, vcache))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    return sample(logits, temps, key, top_ps, top_ks), nk, nv


def _gqa_attend_multi(q, cache_k, cache_v, lengths, cfg: LlamaConfig):
    """Multi-query twin of _gqa_attend_cached for the speculative
    verify forward: w in-flight queries per slot attend the same cache
    view under a PER-QUERY causal mask (query j sees keys < its own
    position + 1 — cached history plus the draft tokens written ahead
    of it this round). q: (b, w, h*hd); cache_k/v: (b, L, kvh, hd);
    lengths: (b, w) valid entries per query (incl. that query's own
    token). Exact-zero masking (-1e30 then softmax) keeps cache bytes
    beyond each mask bitwise-irrelevant, and the per-row reduction
    order matches the single-query path — verify row j reproduces what
    sequential decode would compute at that position."""
    b, w = q.shape[:2]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    g = h // kvh
    qg = q.reshape(b, w, kvh, g, hd).astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    scores = jnp.einsum("bwkgd,blkd->bwkgl", qg, kf) / jnp.sqrt(hd)
    mask = (jnp.arange(cache_k.shape[1])[None, None]
            < lengths[:, :, None])                      # (b, w, L)
    scores = jnp.where(mask[:, :, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bwkgl,blkd->bwkgd", probs,
                     cache_v.astype(jnp.float32))
    return out.reshape(b, w, h * hd)


def verify_tokens_core(params: dict, kcache: jax.Array,
                       vcache: jax.Array, tokens: jax.Array,
                       positions: jax.Array, cfg: LlamaConfig,
                       write, view, attend=None):
    """The speculative-verify transformer: decode_token_core widened
    from one token per slot to w — same layer scan, same cache
    write/view plumbing, so the verify forward can never drift from
    sequential decode. tokens: (b, w) int32 where column 0 is the last
    emitted token and columns 1..w-1 the draft; positions: (b,) cache
    position of column 0 (= tokens_so_far - 1). All w KVs are written
    (position p+j for column j); the returned logits (b, w, vocab)
    f32 row j is the model's distribution for position p+j+1 — the
    verdict on draft token j+1. No device sampling: acceptance is a
    host decision (llm/spec.py) so rejection sampling can inspect the
    full distribution. ``write(ck, cv, k, v)`` takes (b, w, kvh, hd)
    slabs; ``attend(q, ck, cv, pos)`` takes q (b, w, h, hd) and the
    (b, w) positions grid."""
    b, w = tokens.shape
    x = jnp.take(params["embed"], tokens, axis=0)           # (b, w, emb)
    pos = positions[:, None] + jnp.arange(w, dtype=jnp.int32)[None]
    rc, rs = _rope_tables(pos, cfg.head_dim, cfg.rope_theta)

    def layer(carry, xs):
        x = carry
        lp, ck, cv = xs
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)                          # (b, w, ...)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        ck, cv = write(ck, cv, k, v)
        if attend is not None:
            o = attend(q, ck, cv, pos)
        else:
            vk, vv = view(ck, cv)
            o = _gqa_attend_multi(q.reshape(b, w, -1), vk, vv,
                                  pos + 1, cfg)
        x = x + o.astype(x.dtype) @ lp["wo"]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (ck, cv)

    x, (nk, nv) = lax.scan(layer, x, (params["layers"],
                                      kcache, vcache))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.float32)    # (b, w, V)
    return logits, nk, nv


def _decode_core(params: dict, cache: dict, tokens: jax.Array,
                 temps: jax.Array, key: jax.Array,
                 cfg: LlamaConfig,
                 top_ps: Optional[jax.Array] = None,
                 top_ks: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, dict]:
    """One token for every slot. tokens: (slots,) int32 (last sampled
    token per slot); temps: (slots,) f32 sampling temperatures; key: rng
    for this step; cache["length"]: (slots,) current lengths (cache
    position of `tokens` = length, appended here). Returns
    (sampled next tokens (slots,) int32, updated cache)."""
    b = tokens.shape[0]
    positions = cache["length"]  # (b,) where the new token goes

    def write(ck, cv, k, v):
        return (ck.at[jnp.arange(b), positions].set(k.astype(ck.dtype)),
                cv.at[jnp.arange(b), positions].set(v.astype(cv.dtype)))

    def view(ck, cv):
        return ck, cv           # the slot cache IS the attention view

    out, nk, nv = decode_token_core(
        params, cache["k"], cache["v"], tokens, positions, temps, key,
        cfg, write, view, top_ps, top_ks)
    return out, {"k": nk, "v": nv, "length": cache["length"] + 1}


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(params: dict, cache: dict, tokens: jax.Array,
                temps: jax.Array, key: jax.Array,
                cfg: LlamaConfig) -> Tuple[jax.Array, dict]:
    return _decode_core(params, cache, tokens, temps, key, cfg)


@partial(jax.jit, static_argnames=("cfg", "n"), donate_argnums=(1,))
def decode_steps(params: dict, cache: dict, tokens: jax.Array,
                 temps: jax.Array, key: jax.Array, cfg: LlamaConfig,
                 n: int, top_ps: Optional[jax.Array] = None,
                 top_ks: Optional[jax.Array] = None
                 ) -> Tuple[jax.Array, dict]:
    """n chained decode steps in ONE dispatch (lax.scan on device).
    Amortizes the host<->device roundtrip — essential when the link is
    a network tunnel (each sync costs a full RTT) and still worthwhile
    on PCIe. Returns (tokens (n, slots) int32, updated cache). Slots
    whose request finishes mid-block produce discardable garbage; the
    caller masks on eos and bounds n by cache headroom."""
    def body(carry, i):
        cache, toks = carry
        out, cache = _decode_core(params, cache, toks, temps,
                                  jax.random.fold_in(key, i), cfg,
                                  top_ps, top_ks)
        return (cache, out), out

    (cache, _), outs = lax.scan(body, (cache, tokens),
                                jnp.arange(n, dtype=jnp.int32))
    return outs, cache


@partial(jax.jit, donate_argnums=(0,))
def write_prefill_to_cache(cache: dict, kv: dict, slot: jax.Array,
                           length: jax.Array) -> dict:
    """Install a prefilled request's KV into `slot`. The cache is
    donated so XLA updates it in place instead of copying the full
    (layers, slots, max_len, ...) buffers per admission."""
    zero = jnp.int32(0)
    k = lax.dynamic_update_slice(
        cache["k"], kv["k"][:, None].astype(cache["k"].dtype),
        (zero, slot, zero, zero, zero))
    v = lax.dynamic_update_slice(
        cache["v"], kv["v"][:, None].astype(cache["v"].dtype),
        (zero, slot, zero, zero, zero))
    return {"k": k, "v": v,
            "length": cache["length"].at[slot].set(length)}
