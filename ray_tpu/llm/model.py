"""Cache-aware llama forwards for inference: prefill + single-token decode.

The model side of the LLM serving stack (reference:
python/ray/llm/_internal/serve/... wraps vLLM; here the engine is native:
the training model in models/llama.py is reused — same params, same
config — with two inference-shaped entry points that XLA compiles once
per shape bucket):

- `prefill`: full-sequence forward that also emits per-layer K/V, written
  into a static-shape slot cache (TPU rule: no dynamic shapes — prompts
  are padded to a bucket, the cache is (layers, slots, max_len, kvh, hd)).
- `decode_step`: one token for every active slot, attending against the
  cache with a position mask. Batch dimension = slots, so the MXU sees
  one batched matmul per layer regardless of how many requests are live.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu.models.llama import (LlamaConfig, _rmsnorm, _rope,
                                  _rope_tables)


def bucket_for(buckets, n: int) -> int:
    """Smallest prefill shape bucket holding an n-token prompt (shared
    by the unified and disaggregated engines so the policy can't
    drift)."""
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def pad_prompt(tokens, bucket: int):
    """Zero-pad a prompt to its bucket (numpy, int32)."""
    import numpy as np
    out = np.zeros((bucket,), np.int32)
    out[:len(tokens)] = tokens
    return out


def init_cache(cfg: LlamaConfig, slots: int, max_len: int,
               dtype=jnp.bfloat16) -> dict:
    shape = (cfg.n_layers, slots, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype),
            "length": jnp.zeros((slots,), jnp.int32)}


def _qkv(y, lp, cfg: LlamaConfig):
    b, s = y.shape[:2]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    q = (y @ lp["wq"]).reshape(b, s, h, hd)
    k = (y @ lp["wk"]).reshape(b, s, kvh, hd)
    v = (y @ lp["wv"]).reshape(b, s, kvh, hd)
    return q, k, v


def _gqa_attend_cached(q, cache_k, cache_v, lengths, cfg: LlamaConfig):
    """q: (b, h, hd) current-token queries; cache_k/v: (b, L, kvh, hd);
    lengths: (b,) valid cache entries per slot (incl. current token)."""
    b = q.shape[0]
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    g = h // kvh
    qg = q.reshape(b, kvh, g, hd).astype(jnp.float32)
    kf = cache_k.astype(jnp.float32)
    scores = jnp.einsum("bkgd,blkd->bkgl", qg, kf) / jnp.sqrt(hd)
    mask = jnp.arange(cache_k.shape[1])[None] < lengths[:, None]  # (b, L)
    scores = jnp.where(mask[:, None, None, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgl,blkd->bkgd", probs,
                     cache_v.astype(jnp.float32))
    return out.reshape(b, h * hd)


@partial(jax.jit, static_argnames=("cfg", "max_len"))
def prefill(params: dict, tokens: jax.Array, length: jax.Array,
            cfg: LlamaConfig, max_len: int) -> Tuple[jax.Array, dict]:
    """One padded prompt. tokens: (s,) int32 (padded to a bucket);
    length: () actual prompt length. Returns (last-token logits (vocab,),
    per-layer kv padded to max_len: k/v (layers, max_len, kvh, hd))."""
    s = tokens.shape[0]
    x = jnp.take(params["embed"], tokens[None], axis=0)  # (1, s, emb)
    positions = jnp.arange(s, dtype=jnp.int32)[None]
    rc, rs = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def layer(x, lp):
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        # causal reference attention (prompt lengths are modest; the
        # pallas flash path stays on the training side)
        h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
        g = h // kvh
        qg = q[0].reshape(s, kvh, g, hd).astype(jnp.float32)
        kf = k[0].astype(jnp.float32)  # (s, kvh, hd)
        scores = jnp.einsum("skgd,lkd->kgsl", qg, kf) / jnp.sqrt(hd)
        causal = jnp.tril(jnp.ones((s, s), bool))
        valid = jnp.arange(s)[None, :] < length  # keys within prompt
        m = causal & valid
        scores = jnp.where(m[None, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        o = jnp.einsum("kgsl,lkd->skgd", probs,
                       v[0].astype(jnp.float32))
        o = o.reshape(1, s, h * hd).astype(x.dtype)
        x = x + o @ lp["wo"]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (k[0], v[0])

    x, (ks, vs) = lax.scan(layer, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    last = jnp.take(x[0], length - 1, axis=0)
    logits = (last @ params["lm_head"]).astype(jnp.float32)
    # pad kv (layers, s, kvh, hd) -> (layers, max_len, kvh, hd)
    pad = [(0, 0), (0, max_len - s), (0, 0), (0, 0)]
    return logits, {"k": jnp.pad(ks, pad), "v": jnp.pad(vs, pad)}


def sample(logits: jax.Array, temps: jax.Array,
           key: jax.Array) -> jax.Array:
    """Per-slot sampling ON DEVICE: greedy where temp<=0, else
    temperature-scaled categorical. Keeping sampling inside the jitted
    step means each decode ships 4 bytes per slot to the host instead of
    the full vocab logits — the device->host link (PCIe, or a network
    tunnel in this environment) must never carry O(vocab) per token."""
    b = logits.shape[0]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    keys = jax.random.split(key, b)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    drawn = jax.vmap(jax.random.categorical)(keys, scaled).astype(jnp.int32)
    return jnp.where(temps <= 0, greedy, drawn)


def _decode_core(params: dict, cache: dict, tokens: jax.Array,
                 temps: jax.Array, key: jax.Array,
                 cfg: LlamaConfig) -> Tuple[jax.Array, dict]:
    """One token for every slot. tokens: (slots,) int32 (last sampled
    token per slot); temps: (slots,) f32 sampling temperatures; key: rng
    for this step; cache["length"]: (slots,) current lengths (cache
    position of `tokens` = length, appended here). Returns
    (sampled next tokens (slots,) int32, updated cache)."""
    b = tokens.shape[0]
    positions = cache["length"]  # (b,) where the new token goes
    x = jnp.take(params["embed"], tokens[:, None], axis=0)  # (b, 1, emb)
    rc, rs = _rope_tables(positions[:, None], cfg.head_dim, cfg.rope_theta)

    def layer(carry, xs):
        x = carry
        lp, ck, cv = xs  # ck/cv: (b, L, kvh, hd) this layer's cache
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q, k, v = _qkv(y, lp, cfg)  # (b, 1, ...)
        q, k = _rope(q, rc, rs), _rope(k, rc, rs)
        ck = ck.at[jnp.arange(b), positions].set(
            k[:, 0].astype(ck.dtype))
        cv = cv.at[jnp.arange(b), positions].set(
            v[:, 0].astype(cv.dtype))
        o = _gqa_attend_cached(q[:, 0], ck, cv, positions + 1, cfg)
        x = x + (o.astype(x.dtype) @ lp["wo"])[:, None]
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        x = x + ((jax.nn.silu(y @ lp["w_gate"]) * (y @ lp["w_up"]))
                 @ lp["w_down"])
        return x, (ck, cv)

    x, (nk, nv) = lax.scan(layer, x, (params["layers"],
                                      cache["k"], cache["v"]))
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x[:, 0] @ params["lm_head"]).astype(jnp.float32)
    out = sample(logits, temps, key)
    return out, {"k": nk, "v": nv, "length": cache["length"] + 1}


@partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def decode_step(params: dict, cache: dict, tokens: jax.Array,
                temps: jax.Array, key: jax.Array,
                cfg: LlamaConfig) -> Tuple[jax.Array, dict]:
    return _decode_core(params, cache, tokens, temps, key, cfg)


@partial(jax.jit, static_argnames=("cfg", "n"), donate_argnums=(1,))
def decode_steps(params: dict, cache: dict, tokens: jax.Array,
                 temps: jax.Array, key: jax.Array, cfg: LlamaConfig,
                 n: int) -> Tuple[jax.Array, dict]:
    """n chained decode steps in ONE dispatch (lax.scan on device).
    Amortizes the host<->device roundtrip — essential when the link is
    a network tunnel (each sync costs a full RTT) and still worthwhile
    on PCIe. Returns (tokens (n, slots) int32, updated cache). Slots
    whose request finishes mid-block produce discardable garbage; the
    caller masks on eos and bounds n by cache headroom."""
    def body(carry, i):
        cache, toks = carry
        out, cache = _decode_core(params, cache, toks, temps,
                                  jax.random.fold_in(key, i), cfg)
        return (cache, out), out

    (cache, _), outs = lax.scan(body, (cache, tokens),
                                jnp.arange(n, dtype=jnp.int32))
    return outs, cache


@partial(jax.jit, donate_argnums=(0,))
def write_prefill_to_cache(cache: dict, kv: dict, slot: jax.Array,
                           length: jax.Array) -> dict:
    """Install a prefilled request's KV into `slot`. The cache is
    donated so XLA updates it in place instead of copying the full
    (layers, slots, max_len, ...) buffers per admission."""
    zero = jnp.int32(0)
    k = lax.dynamic_update_slice(
        cache["k"], kv["k"][:, None].astype(cache["k"].dtype),
        (zero, slot, zero, zero, zero))
    v = lax.dynamic_update_slice(
        cache["v"], kv["v"][:, None].astype(cache["v"].dtype),
        (zero, slot, zero, zero, zero))
    return {"k": k, "v": v,
            "length": cache["length"].at[slot].set(length)}
