"""Prefill/decode disaggregation: compute-bound prefill on one set of
replicas, latency-bound decode on another.

The TPU-native analog of the reference's prefill-decode serving pattern
(reference: llm/_internal/serve/serving_patterns/prefill_decode/builder.py:184
+ engines/vllm/kv_transfer/nixl.py — there the KV cache moves GPU-to-GPU
over NIXL; here it moves host-staged over the runtime's shared-memory
object plane, sliced to the prompt's prefill bucket so the transfer is
proportional to the prompt, not max_len).

Why disaggregate on TPU: a prefill of a long prompt is one large
MXU-bound matmul burst that stalls every decode slot sharing the chip;
separate prefill replicas keep decode steps (latency-bound, small
batches) off the critical path. Decode admits shipped KV with one
dynamic_update_slice — no forward pass.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ray_tpu.llm import model as lm
from ray_tpu.models.llama import LlamaConfig


class PrefillEngine:
    """Stateless prompt prefill: tokens -> {kv, logits, length}.

    Shape-bucketed like LLMEngine's in-engine prefill (one compile per
    bucket); the returned KV is bucket-sized, and
    LLMEngine.generate_prefilled() writes it into a decode slot.
    """

    def __init__(self, cfg: LlamaConfig, params, *,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 max_len: int = 1024,
                 cache_dtype: str = "bfloat16"):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.buckets = tuple(sorted(b for b in prefill_buckets
                                    if b <= max_len)) or (max_len,)
        self.cache_dtype = cache_dtype

    def prefill(self, tokens: Sequence[int]) -> dict:
        """Runs the prompt forward pass; returns host numpy
        {"k","v": (layers, bucket, kvh, hd), "logits": (vocab,),
        "length": n} ready to ship to a decode engine."""
        import jax.numpy as jnp
        tokens = list(map(int, tokens))
        n = len(tokens)
        if n == 0:
            raise ValueError("empty prompt")
        if n > self.buckets[-1]:
            raise ValueError(
                f"prompt of {n} tokens exceeds the largest prefill "
                f"bucket {self.buckets[-1]}")
        b = lm.bucket_for(self.buckets, n)
        padded = lm.pad_prompt(tokens, b)
        # pad KV only to the bucket (not max_len): the shipped payload
        # scales with the prompt
        logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                jnp.int32(n), self.cfg, b)
        dt = jnp.dtype(self.cache_dtype)
        return {"k": np.asarray(kv["k"].astype(dt)),
                "v": np.asarray(kv["v"].astype(dt)),
                "logits": np.asarray(logits),
                "length": n}
