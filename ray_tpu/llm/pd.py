"""Prefill/decode disaggregation: compute-bound prefill on one set of
replicas, latency-bound decode on another.

The TPU-native analog of the reference's prefill-decode serving pattern
(reference: llm/_internal/serve/serving_patterns/prefill_decode/builder.py:184
+ engines/vllm/kv_transfer/nixl.py — there the KV cache moves GPU-to-GPU
over NIXL; here it moves host-staged over the runtime's shared-memory
object plane, sliced to the prompt's prefill bucket so the transfer is
proportional to the prompt, not max_len).

Why disaggregate on TPU: a prefill of a long prompt is one large
MXU-bound matmul burst that stalls every decode slot sharing the chip;
separate prefill replicas keep decode steps (latency-bound, small
batches) off the critical path. Decode admits shipped KV with one
dynamic_update_slice — no forward pass.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ray_tpu.llm import model as lm
from ray_tpu.models.llama import LlamaConfig


class PrefillEngine:
    """Stateless prompt prefill: tokens -> {kv, logits, length}.

    Shape-bucketed like LLMEngine's in-engine prefill (one compile per
    bucket); the returned KV is bucket-sized, and
    LLMEngine.generate_prefilled() writes it into a decode slot.
    """

    def __init__(self, cfg: LlamaConfig, params, *,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 max_len: int = 1024,
                 cache_dtype: str = "bfloat16"):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.buckets = tuple(sorted(b for b in prefill_buckets
                                    if b <= max_len)) or (max_len,)
        self.cache_dtype = cache_dtype

    def prefill(self, tokens: Sequence[int], *,
                device: bool = False) -> dict:
        """Runs the prompt forward pass; returns
        {"k","v": (layers, bucket, kvh, hd), "logits": (vocab,),
        "length": n} ready to ship to a decode engine. Prompts longer
        than the largest bucket stream through lm.prefill_chunk in
        bucket-sized pieces (chunked prefill — long prompts are the
        very case disaggregation targets), shipping KV padded to the
        smallest bucket multiple that holds them.

        ``device=True`` keeps k/v ON DEVICE and returns TensorRef
        handles (runtime/device_store.py — the RDT analog): a decode
        engine in the same process admits them without the KV ever
        touching the host; a remote decode engine pays exactly one
        host hop (fetch + device_put). ``device=False`` is the fully
        host-staged numpy payload (rides the object plane as before)."""
        import jax.numpy as jnp
        tokens = list(map(int, tokens))
        n = len(tokens)
        if n == 0:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError(
                f"prompt of {n} tokens exceeds max_len {self.max_len}")
        dt = jnp.dtype(self.cache_dtype)
        big = self.buckets[-1]
        if n <= big:
            b = lm.bucket_for(self.buckets, n)
            padded = lm.pad_prompt(tokens, b)
            # pad KV only to the bucket (not max_len): the shipped
            # payload scales with the prompt
            logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                    jnp.int32(n), self.cfg, b)
            k, v = kv["k"], kv["v"]
        else:
            cfg = self.cfg
            # accumulate into the smallest bucket-multiple >= n: chunk
            # compile shapes and the shipped payload stay bucketed
            # (bounded compile variants, prompt-proportional transfer),
            # AND a padded final chunk can never overrun the buffer —
            # dynamic_update_slice would CLAMP the start on overrun and
            # silently corrupt earlier chunks' KV
            ship = ((n + big - 1) // big) * big
            shape = (cfg.n_layers, ship, cfg.n_kv_heads, cfg.head_dim)
            acc = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            off = 0
            logits = None
            while off < n:
                part = tokens[off:off + big]
                b = lm.bucket_for(self.buckets, len(part))
                padded = lm.pad_prompt(part, b)
                logits, acc = lm.prefill_chunk(
                    self.params, jnp.asarray(padded),
                    jnp.int32(len(part)), jnp.int32(off), acc, cfg)
                off += len(part)
            # decode caches span max_len positions; the bucket-rounded
            # tail beyond it is pad garbage
            k = acc["k"][:, :self.max_len]
            v = acc["v"][:, :self.max_len]
        if device:
            from ray_tpu.runtime.device_store import put_device
            return {"k": put_device(k.astype(dt)),
                    "v": put_device(v.astype(dt)),
                    "logits": np.asarray(logits),
                    "length": n}
        return {"k": np.asarray(k.astype(dt)),
                "v": np.asarray(v.astype(dt)),
                "logits": np.asarray(logits),
                "length": n}
