"""Prefill/decode disaggregation: compute-bound prefill on one set of
replicas, latency-bound decode on another.

The TPU-native analog of the reference's prefill-decode serving pattern
(reference: llm/_internal/serve/serving_patterns/prefill_decode/builder.py:184
+ engines/vllm/kv_transfer/nixl.py — there the KV cache moves GPU-to-GPU
over NIXL; here it moves host-staged over the runtime's shared-memory
object plane, sliced to the prompt's prefill bucket so the transfer is
proportional to the prompt, not max_len).

Why disaggregate on TPU: a prefill of a long prompt is one large
MXU-bound matmul burst that stalls every decode slot sharing the chip;
separate prefill replicas keep decode steps (latency-bound, small
batches) off the critical path. Decode admits shipped KV with one
dynamic_update_slice — no forward pass.
"""

from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np

from ray_tpu.llm import model as lm
from ray_tpu.models.llama import LlamaConfig


class PrefillEngine:
    """Stateless prompt prefill: tokens -> {kv, logits, length}.

    Shape-bucketed like LLMEngine's in-engine prefill (one compile per
    bucket); the returned KV is sliced to BLOCK granularity (the paged
    cache's token-block size) before shipping, so the handoff moves
    ceil(n / block) blocks instead of a whole padded bucket — a
    65-token prompt ships 80 positions at block 16, not 128. The
    decode engine re-pads on arrival (paged: into its accumulator;
    monolithic: to its bucket) and frees the prefill side's copy at
    handoff (TensorRef handles are single-use; the host-staged numpy
    copy dies with the request object).
    """

    def __init__(self, cfg: LlamaConfig, params, *,
                 prefill_buckets: Sequence[int] = (64, 128, 256, 512),
                 max_len: int = 1024,
                 cache_dtype: str = "bfloat16",
                 block_size: Optional[int] = None):
        self.cfg = cfg
        self.params = params
        self.max_len = max_len
        self.buckets = tuple(sorted(b for b in prefill_buckets
                                    if b <= max_len)) or (max_len,)
        self.cache_dtype = cache_dtype
        if block_size is None:
            from ray_tpu.config import get_config
            block_size = int(getattr(get_config(),
                                     "kvcache_block_size", 16))
        # same gcd adjustment the engine applies, so both tiers agree
        # on what a block is; 0 = bucket-granular legacy shipping
        if block_size > 0:
            for v in (*self.buckets, max_len):
                block_size = math.gcd(block_size, v)
        self.block_size = max(0, block_size)

    def _ship_len(self, n: int, upper: int) -> int:
        """Positions to ship for an n-token prompt: the smallest block
        multiple covering it (bucket-granular when blocks are off)."""
        if self.block_size <= 0:
            return upper
        b = self.block_size
        return min(upper, -(-n // b) * b)

    def prefill(self, tokens: Sequence[int], *,
                device: bool = False) -> dict:
        """Runs the prompt forward pass; returns
        {"k","v": (layers, bucket, kvh, hd), "logits": (vocab,),
        "length": n} ready to ship to a decode engine. Prompts longer
        than the largest bucket stream through lm.prefill_chunk in
        bucket-sized pieces (chunked prefill — long prompts are the
        very case disaggregation targets), shipping KV padded to the
        smallest bucket multiple that holds them.

        ``device=True`` keeps k/v ON DEVICE and returns TensorRef
        handles (runtime/device_store.py — the RDT analog): a decode
        engine in the same process admits them without the KV ever
        touching the host; a remote decode engine pays exactly one
        host hop (fetch + device_put). ``device=False`` is the fully
        host-staged numpy payload (rides the object plane as before)."""
        import jax.numpy as jnp
        tokens = list(map(int, tokens))
        n = len(tokens)
        if n == 0:
            raise ValueError("empty prompt")
        if n > self.max_len:
            raise ValueError(
                f"prompt of {n} tokens exceeds max_len {self.max_len}")
        dt = jnp.dtype(self.cache_dtype)
        big = self.buckets[-1]
        if n <= big:
            b = lm.bucket_for(self.buckets, n)
            padded = lm.pad_prompt(tokens, b)
            # compute at the bucket shape (bounded compiles), ship
            # only the covering BLOCKS: the payload scales with the
            # prompt at block granularity, not bucket granularity
            logits, kv = lm.prefill(self.params, jnp.asarray(padded),
                                    jnp.int32(n), self.cfg, b)
            ship = self._ship_len(n, b)
            k, v = kv["k"][:, :ship], kv["v"][:, :ship]
        else:
            cfg = self.cfg
            # accumulate into the smallest bucket-multiple >= n: chunk
            # compile shapes and the shipped payload stay bucketed
            # (bounded compile variants, prompt-proportional transfer),
            # AND a padded final chunk can never overrun the buffer —
            # dynamic_update_slice would CLAMP the start on overrun and
            # silently corrupt earlier chunks' KV
            ship = ((n + big - 1) // big) * big
            shape = (cfg.n_layers, ship, cfg.n_kv_heads, cfg.head_dim)
            acc = {"k": jnp.zeros(shape, dt), "v": jnp.zeros(shape, dt)}
            off = 0
            logits = None
            while off < n:
                part = tokens[off:off + big]
                b = lm.bucket_for(self.buckets, len(part))
                padded = lm.pad_prompt(part, b)
                logits, acc = lm.prefill_chunk(
                    self.params, jnp.asarray(padded),
                    jnp.int32(len(part)), jnp.int32(off), acc, cfg)
                off += len(part)
            # ship the covering blocks (capped at max_len — decode
            # caches span max_len positions; anything past is garbage)
            ship = self._ship_len(n, self.max_len)
            k = acc["k"][:, :ship]
            v = acc["v"][:, :ship]
        if device:
            from ray_tpu.runtime.device_store import put_device
            return {"k": put_device(k.astype(dt)),
                    "v": put_device(v.astype(dt)),
                    "logits": np.asarray(logits),
                    "length": n}
        return {"k": np.asarray(k.astype(dt)),
                "v": np.asarray(v.astype(dt)),
                "logits": np.asarray(logits),
                "length": n}
