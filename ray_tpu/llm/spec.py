"""Speculative decoding: prompt-lookup drafts + rejection-sampling accept.

Draft-and-verify generation (speculative sampling, arxiv 2211.17192)
for the paged engine. Single-token decode leaves the MXU idle between
tiny matmuls — the paged decode kernel made each step cheap, but the
step COUNT is untouched, so TPOT is still bounded by sequential
forwards. Here a model-free drafter guesses up to k tokens, the engine
scores all k+1 positions in ONE batched forward
(kvcache.paged_verify_steps), and the longest agreeing prefix is
accepted — emitted tokens per forward go from exactly 1 to 1..k+1
with the output stream UNCHANGED:

- at ``temperature <= 0`` acceptance is exact greedy match: a draft
  token survives iff it equals the model's argmax at its position, so
  the emitted stream is token-for-token identical to vanilla greedy
  decode (pinned by tests/test_zz_spec_decode.py);
- at ``temperature > 0`` acceptance is rejection sampling against the
  model's (temperature -> top-k -> top-p filtered) distribution: the
  drafter is a point mass, so draft d is accepted with probability
  p(d) and a rejection resamples from p with d zeroed-and-renormalized
  — the classic argument makes each emitted token an exact sample
  from p, so the output DISTRIBUTION is unchanged (the acceptance
  filter is lm.filter_logits, the same transform the device sampler
  runs — one implementation, no drift).

The DRAFTER is prompt-lookup / n-gram matching (reference idiom:
vLLM's ngram speculative config, transformers' prompt_lookup_decoding):
match the longest suffix n-gram of the request's own prompt+output
history against that same history and propose the k tokens that
followed the match. No draft model, no extra weights, no device work —
drafting is pure host bookkeeping, which on agentic/RAG-style
workloads (the answer quotes the prompt) is where most of the
speculative win lives anyway. An accept-rate window backs the drafter
off on adversarial low-hit prompts so verify overhead is bounded.

Rejected drafts need no device rollback: their KV lands beyond the
sequence's logical length — masked out of every attention by exact
zeros and overwritten by the next real write at that position — and
the host block accounting rolls back via kvcache.truncate_seq.
"""

from __future__ import annotations

from collections import deque
from typing import List, Optional, Sequence, Tuple

import numpy as np


def spec_metrics() -> dict:
    """Get-or-create the speculative-decoding series (shared process
    registry, pushed to the head like every llm_* family). Catalog:

      llm_spec_accept_rate    drafted-token accept rate of the most
                              recently finished speculative request
      llm_spec_tokens_total   draft pipeline volume, tagged {kind}:
                              drafted | accepted | rejected
    """
    from ray_tpu.util import metrics as m
    return {
        "accept_rate": m.Gauge(
            "llm_spec_accept_rate",
            "Draft-token accept rate of the most recently finished "
            "speculative request (accepted / drafted)"),
        "tokens": m.Counter(
            "llm_spec_tokens_total",
            "Speculative-decode token volume by kind (drafted = "
            "proposed by the drafter, accepted = survived verify, "
            "rejected = rolled back)",
            tag_keys=("kind",)),
    }


def width_buckets(k_max: int) -> Tuple[int, ...]:
    """Verify-width buckets for up to ``k_max`` draft tokens: the
    verify forward takes (slots, w) token rows and XLA compiles one
    program per distinct w — so w is padded UP to 1+2^j (capped at
    k_max+1), bounding compiles at ~log2(k_max)+1 regardless of how
    accepted lengths vary (k_max=4 -> (2, 3, 5); the compile-
    discipline test counts exactly these)."""
    if k_max < 1:
        raise ValueError(f"k_max must be >= 1, got {k_max}")
    out = set()
    j = 0
    while True:
        w = 1 + (1 << j)
        out.add(min(w, k_max + 1))
        if w >= k_max + 1:
            return tuple(sorted(out))
        j += 1


def bucket_width(buckets: Sequence[int], w: int) -> int:
    """Smallest verify bucket holding w in-flight tokens."""
    for b in buckets:
        if w <= b:
            return b
    return buckets[-1]


class PromptLookupDrafter:
    """Model-free n-gram drafter with accept-rate backoff. Stateless
    over the token HISTORY (the engine passes prompt+output each
    round — no duplicated stream to keep in sync); stateful over the
    accept WINDOW: a sliding window of the last ``window`` drafted
    tokens' verdicts, and when its accept rate drops below
    ``min_rate`` the drafter goes quiet for an exponentially growing
    cooldown (probing again after it), so a low-hit request converges
    to vanilla decode cost instead of paying a useless verify forward
    every round."""

    def __init__(self, *, k: int = 4, ngram_max: int = 3,
                 window: int = 16, min_rate: float = 0.25):
        self.k = int(k)
        self.ngram_max = int(ngram_max)
        self.window = int(window)
        self.min_rate = float(min_rate)
        self._recent: deque = deque(maxlen=self.window)
        self._cooldown = 0          # quiet rounds left before a probe
        self._backoff = 4           # next cooldown length (doubles)
        self.drafted = 0
        self.accepted = 0

    @property
    def accept_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    def propose(self, hist: Sequence[int],
                max_k: Optional[int] = None) -> List[int]:
        """Up to min(k, max_k) draft tokens continuing ``hist``: the
        longest suffix n-gram (ngram_max down to 1) is matched against
        the history itself, preferring the LATEST match that still has
        a full k-token continuation (a match flush against the end of
        history predicts almost nothing — on periodic streams the
        full-continuation preference is the difference between
        drafting 1 token and drafting k). Returns [] when no n-gram
        matches or the drafter is cooling off."""
        if self._cooldown > 0:
            self._cooldown -= 1
            return []
        k = self.k if max_k is None else min(self.k, int(max_k))
        if k < 1:
            return []
        hist = list(hist)
        n_hist = len(hist)
        for n in range(min(self.ngram_max, n_hist - 1), 0, -1):
            suf = hist[-n:]
            best = None
            for s in range(n_hist - n - 1, -1, -1):
                if hist[s:s + n] == suf:
                    if best is None:
                        best = s
                    if s + n + k <= n_hist:
                        best = s
                        break
            if best is not None:
                return hist[best + n:best + n + k]
        return []

    def record(self, n_drafted: int, n_accepted: int) -> None:
        """Feed one verify round's verdict back into the window."""
        self.drafted += n_drafted
        self.accepted += n_accepted
        for i in range(n_drafted):
            self._recent.append(1 if i < n_accepted else 0)
        if len(self._recent) < self.window:
            return
        rate = sum(self._recent) / len(self._recent)
        if rate < self.min_rate:
            self._cooldown = self._backoff
            self._backoff = min(self._backoff * 2, 64)
            self._recent.clear()
        else:
            self._backoff = 4


def host_probs(logits: np.ndarray, temperature: float, top_k: int,
               top_p: float) -> np.ndarray:
    """The model's sampling distribution for ONE position, on the
    host: temperature scale + lm.filter_logits (the SAME transform the
    on-device sampler runs — the rejection-sampling accept must judge
    drafts under exactly the distribution the device would sample
    from) + softmax. Returns float64 probs summing to 1."""
    from ray_tpu.llm.model import filter_logits
    scaled = (np.asarray(logits, np.float32)
              / max(float(temperature), 1e-6))[None]
    masked = filter_logits(
        scaled, np.asarray([top_k], np.int32),
        np.asarray([top_p], np.float32))[0].astype(np.float64)
    e = np.exp(masked - masked.max())
    return e / e.sum()


def accept_tokens(logits: np.ndarray, draft: Sequence[int], *,
                  temperature: float, top_k: int, top_p: float,
                  rng: np.random.Generator) -> Tuple[List[int], int]:
    """Judge one slot's verify round. ``logits``: (len(draft)+1, V)
    f32 — row j is the model's distribution for the position draft[j]
    sits at (row len(draft) is the bonus position past the last
    draft). Returns (emitted tokens, n_accepted):

    - temperature <= 0: draft[j] survives while it equals argmax(row
      j); emission is argmax(row 0..m) — the accepted drafts ARE those
      argmaxes, plus the first disagreeing argmax (or the bonus row's
      when everything agreed), so the stream is exactly vanilla
      greedy's.
    - temperature > 0: rejection sampling against p_j = host_probs(row
      j). The point-mass drafter means draft d is accepted with
      probability p_j(d); on rejection the replacement is drawn from
      p_j with d zeroed and renormalized (the max(0, p-q) residual for
      a point mass q), and a fully accepted draft earns a bonus sample
      from the last row — each emitted token is an exact sample from
      p_j, so the output distribution matches vanilla decode.

    Always emits at least 1 token (the round replaces one decode
    step); with an empty draft this reduces to plain host sampling of
    row 0."""
    draft = [int(t) for t in draft]
    emitted: List[int] = []
    if temperature <= 0:
        targets = np.argmax(np.asarray(logits), axis=-1)
        n_acc = 0
        for j, d in enumerate(draft):
            if int(targets[j]) != d:
                break
            n_acc += 1
        emitted = [int(targets[j]) for j in range(n_acc + 1)]
        return emitted, n_acc
    n_acc = 0
    for j, d in enumerate(draft):
        p = host_probs(logits[j], temperature, top_k, top_p)
        if rng.random() < p[d]:
            n_acc += 1
            emitted.append(d)
            continue
        residual = p.copy()
        residual[d] = 0.0
        s = residual.sum()
        if s <= 0.0:        # p was a point mass ON d (degenerate):
            emitted.append(d)       # the "rejection" can't happen
            n_acc += 1              # under real arithmetic; accept
            continue
        residual /= s
        emitted.append(int(rng.choice(len(residual), p=residual)))
        return emitted, n_acc
    p = host_probs(logits[len(draft)], temperature, top_k, top_p)
    emitted.append(int(rng.choice(len(p), p=p)))
    return emitted, n_acc
