from ray_tpu.models import llama
from ray_tpu.models import moe

__all__ = ["llama", "moe"]
