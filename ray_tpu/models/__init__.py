from ray_tpu.models import llama

__all__ = ["llama"]
