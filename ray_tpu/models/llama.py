"""Llama-family decoder, TPU-first.

Pure-functional JAX: params are a pytree, layers are stacked on a leading
axis and driven by ``lax.scan`` (compile time independent of depth), each
layer rematerialized with ``jax.checkpoint``. Attention dispatches between
the Pallas flash kernel (single-shard seq), ring attention (context-parallel
mesh axis), and the XLA reference (CPU tests).

This is the framework's flagship model family — the analog of what reference
users run through TorchTrainer/vLLM (the reference ships no model code of its
own for this; see SURVEY.md section 3.4 for the JaxTrainer north-star path).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name as _checkpoint_name
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import attention as _attention_op, _on_tpu
from ray_tpu.ops.ring_attention import ring_attention


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    data: str = "data"
    fsdp: str = "fsdp"
    tensor: str = "tensor"
    context: str = "context"
    expert: str = "expert"   # used by the MoE family (models/moe.py)

    @property
    def batch(self):
        return (self.data, self.fsdp)


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    # What the per-layer jax.checkpoint saves for the backward pass:
    #   "full"  — save nothing, recompute the whole layer (min memory,
    #             ~33% extra FLOPs: fwd runs twice);
    #   "dots"  — save weight-matmul outputs (checkpoint_dots_with_no_batch_dims):
    #             backward recomputes only cheap elementwise/norm ops;
    #   "attn"  — save just the attention output (skips re-running the flash
    #             kernel; weight matmuls are recomputed);
    #   "none"  — no remat (same as remat=False).
    remat_policy: str = "full"
    # Dtype of the logits / cross-entropy path. float32 is the numerically
    # conservative default; bfloat16 halves the (b, s, vocab) HBM traffic and
    # runs the exp/logsumexp passes at the faster bf16 VPU rate (loss error
    # ~1e-2 absolute — fine for throughput-oriented runs).
    logits_dtype: str = "float32"
    # Fused cross-entropy: tokens per sequence chunk. 0 = classic path
    # (materialize the full (b, s, vocab) logits). >0 = the loss scans
    # seq chunks, computing each chunk's (b, ce_chunk, vocab) logits,
    # reducing to scalars, and REMATing the chunk on backward — the
    # full logits tensor never exists in HBM (at 7B shapes b4 s4096
    # v32000 that's ~1 GiB bf16 + softmax temporaries, the largest
    # single activation in the step). Costs one extra lm_head matmul
    # per chunk on backward.
    ce_chunk: int = 0
    attn_impl: str = "auto"        # auto | reference | flash | flash_interpret | ring
    attn_block_q: int = 128        # flash kernel tile sizes (MXU-multiple)
    attn_block_k: int = 128

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        h, kvh, hd = self.n_heads, self.n_kv_heads, self.head_dim
        per_layer = d * h * hd + 2 * d * kvh * hd + h * hd * d \
            + 3 * d * f + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token (fwd+bwd ~ 6*N plus attention term)."""
        n_matmul = self.num_params() - self.vocab_size * self.dim  # embed is a gather
        attn = 12 * self.n_layers * self.dim * seq_len  # 2*2*3? qk + pv fwd+bwd
        return 6.0 * n_matmul + attn


def llama2_7b(**kw) -> LlamaConfig:
    """Llama-2-7B dims, set EXPLICITLY (they coincide with
    LlamaConfig's defaults, but "7b" in code must mean 7B even if the
    defaults drift)."""
    defaults = dict(vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
                    n_kv_heads=32, ffn_dim=11008, max_seq_len=4096)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def llama2_13b(**kw) -> LlamaConfig:
    defaults = dict(dim=5120, n_layers=40, n_heads=40, n_kv_heads=40,
                    ffn_dim=13824)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def llama3_8b(**kw) -> LlamaConfig:
    defaults = dict(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                    n_kv_heads=8, ffn_dim=14336, rope_theta=500000.0,
                    max_seq_len=8192)
    defaults.update(kw)
    return LlamaConfig(**defaults)


def tiny(**kw) -> LlamaConfig:
    defaults = dict(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=256, max_seq_len=256)
    defaults.update(kw)
    return LlamaConfig(**defaults)


# --- params ----------------------------------------------------------------

def init_params(rng: jax.Array, cfg: LlamaConfig) -> dict:
    dtype = jnp.dtype(cfg.dtype)
    d, f = cfg.dim, cfg.ffn_dim
    h, kvh, hd, L = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    ks = jax.random.split(rng, 9)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "embed": norm_init(ks[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype),
            "wq": norm_init(ks[1], (L, d, h * hd), d),
            "wk": norm_init(ks[2], (L, d, kvh * hd), d),
            "wv": norm_init(ks[3], (L, d, kvh * hd), d),
            "wo": norm_init(ks[4], (L, h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), dtype),
            "w_gate": norm_init(ks[5], (L, d, f), d),
            "w_up": norm_init(ks[6], (L, d, f), d),
            "w_down": norm_init(ks[7], (L, f, d), f),
        },
        "final_norm": jnp.ones((d,), dtype),
        "lm_head": norm_init(ks[8], (d, cfg.vocab_size), d),
    }


def param_shardings(cfg: LlamaConfig, axes: MeshAxes = MeshAxes()) -> dict:
    """PartitionSpec pytree matching init_params. Megatron-style tensor
    sharding + FSDP on the complementary dim."""
    t, fs = axes.tensor, axes.fsdp
    return {
        "embed": P(t, fs),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fs, t),
            "wk": P(None, fs, t),
            "wv": P(None, fs, t),
            "wo": P(None, t, fs),
            "mlp_norm": P(None, None),
            "w_gate": P(None, fs, t),
            "w_up": P(None, fs, t),
            "w_down": P(None, t, fs),
        },
        "final_norm": P(None),
        "lm_head": P(fs, t),
    }


# --- forward ---------------------------------------------------------------

def _rmsnorm(x, w, eps):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * lax.rsqrt(var + eps)).astype(x.dtype) * w


def _rope_tables(positions, head_dim, theta):
    """cos/sin tables (b, s, half) f32, computed ONCE per forward — the
    sin/cos transcendentals are hoisted out of the per-layer code (they cost
    a full VPU pass per layer otherwise)."""
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # (b, s, half)
    return jnp.cos(angles), jnp.sin(angles)


def _rope(x, cos, sin):
    """x: (b, s, h, d); cos/sin: (b, s, d//2) precomputed tables."""
    half = x.shape[-1] // 2
    c = cos[:, :, None, :].astype(x.dtype)
    s = sin[:, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)


def _attend(q, k, v, cfg: LlamaConfig, mesh: Optional[Mesh],
            axes: MeshAxes):
    impl = cfg.attn_impl
    blocks = dict(block_q=cfg.attn_block_q, block_k=cfg.attn_block_k)

    def named(out):
        # Flash paths name their own residuals (attn_out/attn_lse inside the
        # custom_vjp fwd rule); the XLA paths get a single named output so
        # the "attn" remat policy can save it.
        return _checkpoint_name(out, "attn_res")

    if mesh is None:
        if impl in ("auto", "ring"):
            impl = "flash" if _on_tpu() and q.shape[1] >= 128 \
                else "reference"
        out = _attention_op(q, k, v, causal=True, impl=impl, **blocks)
        return out if impl.startswith("flash") else named(out)

    cp = mesh.shape.get(axes.context, 1)
    bspec = P(axes.batch, axes.context, axes.tensor, None)

    if impl == "ring" or (impl == "auto" and cp > 1):
        def f(q, k, v):
            return ring_attention(q, k, v, axis_name=axes.context)
        from ray_tpu.ops import shard_map as _shard_map
        return named(_shard_map(f, mesh=mesh,
                                in_specs=(bspec, bspec, bspec),
                                out_specs=bspec)(q, k, v))

    if cp > 1:
        # Explicit non-ring impl on a context-sharded mesh: run with global
        # semantics (GSPMD gathers the sequence axis). Only the XLA reference
        # path supports this — the Pallas kernel can't be auto-partitioned.
        if impl != "reference":
            raise ValueError(
                f"attn_impl={impl!r} cannot run under a context-parallel "
                f"mesh (context axis size {cp}); use 'ring' or 'auto'")
        return named(_attention_op(q, k, v, causal=True, impl=impl))

    if impl == "auto":
        impl = "flash" if _on_tpu() and q.shape[1] >= 128 \
            else "reference"

    def f(q, k, v):
        return _attention_op(q, k, v, causal=True, impl=impl, **blocks)
    # check_vma=False: pallas_call outputs carry no vma under shard_map.
    from ray_tpu.ops import shard_map as _shard_map
    out = _shard_map(f, mesh=mesh, in_specs=(bspec, bspec, bspec),
                     out_specs=bspec, check_vma=False)(q, k, v)
    return out if impl.startswith("flash") else named(out)


def _remat(layer, cfg: LlamaConfig):
    if not cfg.remat or cfg.remat_policy == "none":
        return layer
    cp = jax.checkpoint_policies
    if cfg.remat_policy == "full":
        return jax.checkpoint(layer)
    if cfg.remat_policy == "dots":
        policy = cp.save_from_both_policies(
            cp.checkpoint_dots_with_no_batch_dims,
            cp.save_only_these_names("attn_out", "attn_lse"))
    elif cfg.remat_policy == "attn":
        # Saves the flash kernel outputs (o + lse residuals) so backward
        # never re-runs the attention forward; "attn_res" covers the
        # non-flash attention paths (reference/ring).
        policy = cp.save_only_these_names("attn_out", "attn_lse", "attn_res")
    else:
        raise ValueError(f"unknown remat_policy: {cfg.remat_policy!r}")
    return jax.checkpoint(layer, policy=policy)


def forward_hidden(params: dict, tokens: jax.Array, cfg: LlamaConfig,
                   mesh: Optional[Mesh] = None,
                   axes: MeshAxes = MeshAxes()) -> jax.Array:
    """tokens: (batch, seq) int32 -> final NORMED hidden states
    (batch, seq, dim) — the pre-lm_head activations (the fused CE
    consumes these chunk by chunk instead of full logits)."""
    b, s = tokens.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def act_constraint(x, spec):
        if mesh is not None:
            return lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, spec))
        return x

    x = jnp.take(params["embed"], tokens, axis=0)
    x = act_constraint(x, P(axes.batch, axes.context, None))
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    rope_cos, rope_sin = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def layer(x, lp):
        # attention block
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (y @ lp["wq"]).reshape(b, s, h, hd)
        k = (y @ lp["wk"]).reshape(b, s, kvh, hd)
        v = (y @ lp["wv"]).reshape(b, s, kvh, hd)
        q = _rope(q, rope_cos, rope_sin)
        k = _rope(k, rope_cos, rope_sin)
        o = _attend(q, k, v, cfg, mesh, axes).astype(x.dtype)
        x = x + (o.reshape(b, s, h * hd) @ lp["wo"])
        x = act_constraint(x, P(axes.batch, axes.context, None))
        # mlp block
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(y @ lp["w_gate"])
        up = y @ lp["w_up"]
        x = x + ((gate * up) @ lp["w_down"])
        x = act_constraint(x, P(axes.batch, axes.context, None))
        return x, None

    step = _remat(layer, cfg)
    x, _ = lax.scan(step, x, params["layers"])
    return _rmsnorm(x, params["final_norm"], cfg.norm_eps)


def forward(params: dict, tokens: jax.Array, cfg: LlamaConfig,
            mesh: Optional[Mesh] = None,
            axes: MeshAxes = MeshAxes()) -> jax.Array:
    """tokens: (batch, seq) int32 -> logits (batch, seq, vocab)."""
    x = forward_hidden(params, tokens, cfg, mesh, axes)
    return (x @ params["lm_head"]).astype(jnp.dtype(cfg.logits_dtype))


def cross_entropy(logits: jax.Array, batch: dict) -> jax.Array:
    """Masked token cross-entropy, shared by every model family.

    max/exp run in the logits dtype (bf16 when configured — faster VPU
    rate, half the HBM traffic); accumulation and the final log are f32.
    """
    targets = batch["targets"]
    m = jnp.max(logits, axis=-1, keepdims=True)
    sumexp = jnp.sum(jnp.exp(logits - m), axis=-1, dtype=jnp.float32)
    logz = m[..., 0].astype(jnp.float32) + jnp.log(sumexp)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold.astype(jnp.float32)
    mask = batch.get("mask")
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def fused_cross_entropy(x: jax.Array, lm_head: jax.Array, batch: dict,
                        chunk: int, logits_dtype) -> jax.Array:
    """Chunked logits-free cross-entropy: scan seq chunks, projecting
    each (b, chunk, dim) -> (b, chunk, vocab), reducing to the masked
    NLL sums, and dropping the chunk logits. jax.checkpoint on the
    chunk body recomputes them on backward, so the peak live logits
    tensor is (b, chunk, vocab) instead of (b, s, vocab) — the classic
    big-vocab fusion (vocab stays shardable over tensor: the max /
    sumexp reductions cross the vocab axis, GSPMD inserts the psums).
    """
    b, s, d = x.shape
    n = s // chunk
    dt = jnp.dtype(logits_dtype)
    targets = batch["targets"]
    mask = batch.get("mask")
    if mask is None:
        mask = jnp.ones((b, s), jnp.float32)
    # (n, b, chunk, ...) scan layout
    xc = jnp.moveaxis(x.reshape(b, n, chunk, d), 1, 0)
    tc = jnp.moveaxis(targets.reshape(b, n, chunk), 1, 0)
    mc = jnp.moveaxis(mask.astype(jnp.float32).reshape(b, n, chunk),
                      1, 0)

    @jax.checkpoint
    def body(acc, inp):
        xch, tch, mch = inp
        logits = (xch @ lm_head).astype(dt)
        m = jnp.max(logits, axis=-1, keepdims=True)
        sumexp = jnp.sum(jnp.exp(logits - m), axis=-1,
                         dtype=jnp.float32)
        logz = m[..., 0].astype(jnp.float32) + jnp.log(sumexp)
        gold = jnp.take_along_axis(
            logits, tch[..., None], axis=-1)[..., 0]
        nll = logz - gold.astype(jnp.float32)
        tot, cnt = acc
        return (tot + jnp.sum(nll * mch), cnt + jnp.sum(mch)), None

    (tot, cnt), _ = lax.scan(
        body, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xc, tc, mc))
    return tot / jnp.maximum(cnt, 1.0)


def loss_fn(params: dict, batch: dict, cfg: LlamaConfig,
            mesh: Optional[Mesh] = None,
            axes: MeshAxes = MeshAxes()) -> jax.Array:
    """batch: {"tokens": (b, s), "targets": (b, s), "mask": optional}."""
    s = batch["tokens"].shape[1]
    if cfg.ce_chunk > 0:
        if s % cfg.ce_chunk:
            # silently materializing the full logits here would undo
            # the exact memory saving the flag was set for
            raise ValueError(
                f"ce_chunk={cfg.ce_chunk} must divide seq len {s}")
        if s > cfg.ce_chunk:
            x = forward_hidden(params, batch["tokens"], cfg, mesh, axes)
            return fused_cross_entropy(x, params["lm_head"], batch,
                                       cfg.ce_chunk, cfg.logits_dtype)
        # s == ce_chunk: one chunk IS the full logits — classic path
    logits = forward(params, batch["tokens"], cfg, mesh, axes)
    return cross_entropy(logits, batch)
