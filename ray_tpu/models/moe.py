"""Mixture-of-Experts decoder family (Mixtral-style), TPU-first.

Expert parallelism is a *mesh axis* (``MeshAxes.expert``), not a process
group: expert weights are sharded over the ``expert`` axis and the
dispatch/combine einsums carry GSPMD sharding constraints, so XLA inserts
the token all-to-alls over ICI. The reference only passes expert
parallelism through to engine kwargs (reference:
python/ray/llm/_internal/serve/engines/vllm/vllm_models.py, SURVEY.md
section 2.3 "Expert parallelism: delegated"); here it is native.

Routing is GShard/Switch-style top-k with per-row capacity: dispatch and
combine are dense one-hot tensors of shape (batch, seq, experts, capacity)
feeding batched expert matmuls — everything stays static-shape and lands on
the MXU. Tokens past an expert's capacity are dropped (standard
capacity-factor semantics); an auxiliary load-balancing loss keeps the
router near-uniform so drops stay rare.

Attention blocks are shared with the Llama family (ray_tpu.models.llama):
RoPE + GQA + flash/ring kernels, identical remat policies.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.models.llama import MeshAxes, _attend, _rmsnorm, _rope, \
    _rope_tables


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    n_experts: int = 8
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    remat_policy: str = "full"
    logits_dtype: str = "float32"
    attn_impl: str = "auto"
    attn_block_q: int = 128
    attn_block_k: int = 128

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    def capacity(self, seq_len: int) -> int:
        """Per-row expert capacity (tokens per expert per sequence)."""
        c = int(self.capacity_factor * self.experts_per_token * seq_len
                / self.n_experts)
        return max(4, -(-c // 4) * 4)  # round up to a multiple of 4

    def num_params(self) -> int:
        d, f, v = self.dim, self.ffn_dim, self.vocab_size
        h, kvh, hd = self.n_heads, self.n_kv_heads, self.head_dim
        attn = d * h * hd + 2 * d * kvh * hd + h * hd * d
        moe = d * self.n_experts + 3 * self.n_experts * d * f
        per_layer = attn + moe + 2 * d
        return v * d + self.n_layers * per_layer + d + d * v

    def num_active_params(self) -> int:
        """Params touched per token (top-k experts, not all)."""
        d, f = self.dim, self.ffn_dim
        h, kvh, hd = self.n_heads, self.n_kv_heads, self.head_dim
        attn = d * h * hd + 2 * d * kvh * hd + h * hd * d
        moe = d * self.n_experts + 3 * self.experts_per_token * d * f
        per_layer = attn + moe + 2 * d
        return self.vocab_size * d + self.n_layers * per_layer \
            + d + d * self.vocab_size

    def flops_per_token(self, seq_len: int) -> float:
        n_matmul = self.num_active_params() - self.vocab_size * self.dim
        attn = 12 * self.n_layers * self.dim * seq_len
        return 6.0 * n_matmul + attn


def mixtral_8x7b(**kw) -> MoEConfig:
    return MoEConfig(**kw)


def tiny(**kw) -> MoEConfig:
    defaults = dict(vocab_size=512, dim=64, n_layers=2, n_heads=4,
                    n_kv_heads=2, ffn_dim=128, n_experts=4,
                    experts_per_token=2, max_seq_len=128)
    defaults.update(kw)
    return MoEConfig(**defaults)


# --- params ----------------------------------------------------------------

def init_params(rng: jax.Array, cfg: MoEConfig) -> dict:
    dtype = jnp.dtype(cfg.dtype)
    d, f, E = cfg.dim, cfg.ffn_dim, cfg.n_experts
    h, kvh, hd, L = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.n_layers
    ks = jax.random.split(rng, 10)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32)
                * (fan_in ** -0.5)).astype(dtype)

    return {
        "embed": norm_init(ks[0], (cfg.vocab_size, d), d),
        "layers": {
            "attn_norm": jnp.ones((L, d), dtype),
            "wq": norm_init(ks[1], (L, d, h * hd), d),
            "wk": norm_init(ks[2], (L, d, kvh * hd), d),
            "wv": norm_init(ks[3], (L, d, kvh * hd), d),
            "wo": norm_init(ks[4], (L, h * hd, d), h * hd),
            "mlp_norm": jnp.ones((L, d), dtype),
            # router in f32: tiny, and top-k tie-breaks are dtype-sensitive
            "router": (jax.random.normal(ks[5], (L, d, E), jnp.float32)
                       * (d ** -0.5)),
            "w_gate": norm_init(ks[6], (L, E, d, f), d),
            "w_up": norm_init(ks[7], (L, E, d, f), d),
            "w_down": norm_init(ks[8], (L, E, f, d), f),
        },
        "final_norm": jnp.ones((d,), dtype),
        "lm_head": norm_init(ks[9], (d, cfg.vocab_size), d),
    }


def param_shardings(cfg: MoEConfig, axes: MeshAxes = MeshAxes()) -> dict:
    t, fs, ep = axes.tensor, axes.fsdp, axes.expert
    return {
        "embed": P(t, fs),
        "layers": {
            "attn_norm": P(None, None),
            "wq": P(None, fs, t),
            "wk": P(None, fs, t),
            "wv": P(None, fs, t),
            "wo": P(None, t, fs),
            "mlp_norm": P(None, None),
            "router": P(None, fs, None),
            "w_gate": P(None, ep, fs, t),
            "w_up": P(None, ep, fs, t),
            "w_down": P(None, ep, t, fs),
        },
        "final_norm": P(None),
        "lm_head": P(fs, t),
    }


# --- routing ---------------------------------------------------------------

def _route(y, router, cfg: MoEConfig):
    """Top-k routing with per-row capacity.

    y: (b, s, d) -> dispatch (b, s, E, C) bool-as-dtype, combine (b, s, E, C)
    with gate weights, aux load-balance loss (scalar f32).
    """
    b, s, _ = y.shape
    E, k, C = cfg.n_experts, cfg.experts_per_token, cfg.capacity(s)

    logits = (y.astype(jnp.float32) @ router)          # (b, s, E)
    probs = jax.nn.softmax(logits, axis=-1)
    gate_vals, idx = lax.top_k(probs, k)               # (b, s, k)
    gate_vals = gate_vals / jnp.maximum(
        jnp.sum(gate_vals, axis=-1, keepdims=True), 1e-9)

    dispatch = jnp.zeros((b, s, E, C), jnp.float32)
    combine = jnp.zeros((b, s, E, C), jnp.float32)
    used = jnp.zeros((b, 1, E), jnp.float32)           # slots taken per expert
    for j in range(k):                                 # k is small and static
        m = jax.nn.one_hot(idx[..., j], E)             # (b, s, E)
        # position of each token within its expert's queue (row-local,
        # earlier slots have priority)
        pos = jnp.cumsum(m, axis=1) - m + used
        keep = m * (pos < C)
        pos_oh = jax.nn.one_hot(
            jnp.clip(pos, 0, C - 1).astype(jnp.int32), C)  # (b, s, E, C)
        dispatch = dispatch + keep[..., None] * pos_oh
        combine = combine + (gate_vals[..., j, None] * keep)[..., None] * pos_oh
        used = used + jnp.sum(keep, axis=1, keepdims=True)

    # Switch-style aux loss: E * sum_e f_e * p_e (minimized at uniform load)
    f_e = jnp.mean(jax.nn.one_hot(idx, E).sum(axis=2), axis=(0, 1))
    p_e = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(f_e * p_e)
    return dispatch, combine, aux


def _moe_block(y, lp, cfg: MoEConfig, act_constraint, axes: MeshAxes):
    """y: (b, s, d) normed hidden -> expert-mixed output (b, s, d)."""
    dispatch, combine, aux = _route(y, lp["router"], cfg)
    dt = y.dtype
    # (b, s, E, C) x (b, s, d) -> (b, E, C, d): the token all-to-all. The
    # sharding constraint moves the expert dim onto the expert axis; GSPMD
    # emits the all-to-all over ICI.
    xd = jnp.einsum("bsec,bsd->becd", dispatch.astype(dt), y)
    xd = act_constraint(xd, P(axes.batch, axes.expert, None, None))
    gate = jax.nn.silu(jnp.einsum("becd,edf->becf", xd, lp["w_gate"]))
    up = jnp.einsum("becd,edf->becf", xd, lp["w_up"])
    out = jnp.einsum("becf,efd->becd", gate * up, lp["w_down"])
    out = act_constraint(out, P(axes.batch, axes.expert, None, None))
    y_out = jnp.einsum("bsec,becd->bsd", combine.astype(dt), out)
    return y_out, aux


# --- forward ---------------------------------------------------------------

def forward(params: dict, tokens: jax.Array, cfg: MoEConfig,
            mesh: Optional[Mesh] = None,
            axes: MeshAxes = MeshAxes()):
    """tokens (b, s) int32 -> (logits (b, s, vocab), aux_loss scalar)."""
    b, s = tokens.shape
    h, kvh, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    def act_constraint(x, spec):
        if mesh is not None:
            return lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, spec))
        return x

    x = jnp.take(params["embed"], tokens, axis=0)
    x = act_constraint(x, P(axes.batch, axes.context, None))
    positions = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32)[None], (b, s))
    rope_cos, rope_sin = _rope_tables(positions, cfg.head_dim, cfg.rope_theta)

    def layer(x, lp):
        y = _rmsnorm(x, lp["attn_norm"], cfg.norm_eps)
        q = (y @ lp["wq"]).reshape(b, s, h, hd)
        k = (y @ lp["wk"]).reshape(b, s, kvh, hd)
        v = (y @ lp["wv"]).reshape(b, s, kvh, hd)
        q = _rope(q, rope_cos, rope_sin)
        k = _rope(k, rope_cos, rope_sin)
        o = _attend(q, k, v, cfg, mesh, axes).astype(x.dtype)
        x = x + (o.reshape(b, s, h * hd) @ lp["wo"])
        x = act_constraint(x, P(axes.batch, axes.context, None))
        y = _rmsnorm(x, lp["mlp_norm"], cfg.norm_eps)
        moe_out, aux = _moe_block(y, lp, cfg, act_constraint, axes)
        x = x + moe_out
        x = act_constraint(x, P(axes.batch, axes.context, None))
        return x, aux

    step = llama._remat(layer, cfg)
    x, aux = lax.scan(step, x, params["layers"])
    x = _rmsnorm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"]).astype(jnp.dtype(cfg.logits_dtype))
    return logits, jnp.sum(aux)


def loss_fn(params: dict, batch: dict, cfg: MoEConfig,
            mesh: Optional[Mesh] = None,
            axes: MeshAxes = MeshAxes()) -> jax.Array:
    """Cross-entropy + weighted load-balance aux loss."""
    logits, aux = forward(params, batch["tokens"], cfg, mesh, axes)
    return llama.cross_entropy(logits, batch) + cfg.aux_loss_weight * aux
