"""Long-running node process: the deploy unit behind ``ray-tpu start``.

The reference boots a head as a constellation of processes (GCS, raylet,
dashboard...) wired by its node supervisor (reference:
python/ray/_private/node.py:1359, _private/services.py:1497). Here one
process hosts the control service (head only) plus a node agent on a
single asyncio loop — the same topology `cluster_utils.Cluster` builds
in-process, promoted to a real OS process with signal-driven shutdown.

Run directly (`python -m ray_tpu.node --head ...`) or, normally, via the
``ray-tpu start`` CLI which daemonizes it and records a session dir.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import uuid
from typing import Dict, Optional

from ray_tpu.config import Config


def _auto_resources(num_cpus: Optional[float],
                    resources: Optional[Dict[str, float]]) -> Dict[str, float]:
    """CPU count plus every accelerator plugin's detected devices
    (reference: _private/accelerators/ manager registry feeding node
    resources — TPU first-class, NVIDIA GPUs for mixed clusters,
    vendor plugins via accelerators.register)."""
    from ray_tpu.util import accelerators
    res = dict(resources or {})
    res.setdefault("CPU", float(num_cpus if num_cpus is not None
                                else (os.cpu_count() or 1)))
    for k, v in accelerators.detect_resources().items():
        res.setdefault(k, v)
    return res


def _auto_labels(labels: Optional[Dict[str, str]]) -> Dict[str, str]:
    from ray_tpu.util import accelerators
    out = dict(accelerators.detect_labels())
    out.update(labels or {})
    return out


async def _amain(args) -> int:
    cfg = Config.from_env()
    if args.system_config:
        cfg.update(json.loads(args.system_config))
    if args.metrics_port is not None:
        cfg.metrics_port = args.metrics_port
    if not cfg.log_dir and args.info_file:
        # CLI-started nodes log workers beside their session record.
        cfg.log_dir = os.path.join(
            os.path.dirname(args.info_file), "logs",
            os.path.splitext(os.path.basename(args.info_file))[0])

    stop_ev = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGTERM, signal.SIGINT):
        loop.add_signal_handler(sig, stop_ev.set)

    head = None
    if args.head:
        from ray_tpu.runtime.control import ControlService
        head = ControlService(cfg)
        head_addr = await head.start(args.host, args.port)
        session_id = uuid.uuid4().hex[:16]
        await head.pool.call(head_addr, "kv_put", key="__session_id",
                             value=session_id.encode())
    else:
        host, port = args.address.rsplit(":", 1)
        head_addr = (host, int(port))
        from ray_tpu.runtime import rpc
        pool = rpc.ConnectionPool()
        sid = await pool.call(head_addr, "kv_get", key="__session_id")
        await pool.close()
        if not sid:
            print(f"no cluster at {args.address}", file=sys.stderr)
            return 1
        session_id = sid.decode()

    from ray_tpu.runtime.agent import NodeAgent
    agent = NodeAgent(
        head_addr,
        resources=_auto_resources(args.num_cpus,
                                  json.loads(args.resources or "{}")),
        labels=_auto_labels(json.loads(args.labels or "{}")),
        config=cfg, session_id=session_id,
        env_extra={"PYTHONPATH": os.pathsep.join(sys.path)})
    agent_addr = await agent.start(host=args.node_host)

    info = {
        "address": f"{head_addr[0]}:{head_addr[1]}",
        "node_id": agent.node_id.hex(),
        "agent_addr": f"{agent_addr[0]}:{agent_addr[1]}",
        "session_id": session_id,
        "pid": os.getpid(),
        "resources": agent.resources_total,
    }
    ma = getattr(agent, "metrics_addr", None)
    if ma is not None:
        info["metrics_addr"] = f"{ma[0]}:{ma[1]}"
    if cfg.log_dir:
        info["log_dir"] = cfg.log_dir
    if args.info_file:
        tmp = args.info_file + ".tmp"
        with open(tmp, "w") as f:
            json.dump(info, f)
        os.replace(tmp, args.info_file)
    print("RAY_TPU_NODE_READY " + json.dumps(info), flush=True)

    await stop_ev.wait()
    # Graceful drain: tell the head this node is leaving so its objects /
    # actors are handled as a drain, not a death.
    try:
        await agent.pool.call(head_addr, "drain_node",
                              node_id=agent.node_id, timeout=5.0)
    except Exception:
        pass
    try:
        await asyncio.wait_for(agent.stop(), 15)
    except Exception:
        pass
    if head is not None:
        try:
            await asyncio.wait_for(head.stop(), 10)
        except Exception:
            pass
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="ray_tpu.node")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address", help="head host:port (worker nodes)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind host for the head control service "
                        "(0.0.0.0 for real multi-host)")
    p.add_argument("--node-host", default="127.0.0.1",
                   help="bind host for this node's agent/workers")
    p.add_argument("--port", type=int, default=6379)
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--resources", help="JSON dict of extra resources")
    p.add_argument("--labels", help="JSON dict of node labels")
    p.add_argument("--system-config", help="JSON config overrides")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="Prometheus /metrics port (0 = ephemeral)")
    p.add_argument("--info-file", help="write node info JSON here when up")
    args = p.parse_args(argv)
    if not args.head and not args.address:
        p.error("one of --head / --address is required")
    from ray_tpu.runtime.rpc import new_event_loop
    loop = new_event_loop()
    asyncio.set_event_loop(loop)
    return loop.run_until_complete(_amain(args))


if __name__ == "__main__":
    sys.exit(main())
