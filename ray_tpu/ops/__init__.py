from ray_tpu.ops.attention import attention, mha_reference, flash_attention
from ray_tpu.ops.ring_attention import ring_attention


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma=None):
    """jax.shard_map across the API move: new jax exposes it at the
    top level with ``check_vma``; older jax has
    jax.experimental.shard_map.shard_map with ``check_rep``. An
    AttributeError on the old side used to fail every context-parallel
    (ring-attention) caller in this environment."""
    import jax
    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm
    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               **kw)


__all__ = ["attention", "mha_reference", "flash_attention",
           "ring_attention", "shard_map"]
