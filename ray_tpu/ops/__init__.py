from ray_tpu.ops.attention import attention, mha_reference, flash_attention
from ray_tpu.ops.ring_attention import ring_attention

__all__ = ["attention", "mha_reference", "flash_attention", "ring_attention"]
