"""Attention ops: jnp reference + Pallas flash attention with custom VJP.

Public entry point is :func:`attention` which dispatches to the Pallas kernel
on TPU (or interpret mode when forced) and to the XLA reference elsewhere.
Shapes follow (batch, seq, heads, head_dim); GQA is supported by num_kv_heads
dividing num_heads.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from ray_tpu.ops.pallas import flash_attention as _fa


def _repeat_kv(k: jax.Array, num_heads: int) -> jax.Array:
    """(b, s, kv_heads, d) -> (b, s, num_heads, d) for GQA."""
    b, s, kvh, d = k.shape
    if kvh == num_heads:
        return k
    if num_heads % kvh:
        raise ValueError(f"num_heads {num_heads} not divisible by kv_heads {kvh}")
    reps = num_heads // kvh
    return jnp.repeat(k, reps, axis=2)


def mha_reference(q, k, v, *, causal: bool = True,
                  sm_scale: Optional[float] = None,
                  segment_ids: Optional[jax.Array] = None,
                  q_offset: Optional[int] = None) -> jax.Array:
    """Plain XLA attention. (b, s, h, d) layout. O(S^2) memory — the
    correctness oracle and the CPU-test path. ``q_offset`` places the
    causal diagonal (query i attends keys <= i + q_offset; default
    sk - sq: queries are the last rows)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    scale = sm_scale if sm_scale is not None else d ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    keep = jnp.ones((b, 1, sq, sk), dtype=bool)
    if causal:
        diag = (sk - sq) if q_offset is None else q_offset
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=diag)
        keep = keep & mask[None, None]
    if segment_ids is not None:
        seg_mask = segment_ids[:, :, None] == segment_ids[:, None, :]
        keep = keep & seg_mask[:, None]
    logits = jnp.where(keep, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1)
    # Fully-masked rows produce 0, matching the flash-kernel convention.
    probs = jnp.where(keep.any(axis=-1, keepdims=True), probs, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs, v.astype(jnp.float32))
    return out.astype(q.dtype)


# --- flash attention with custom vjp (pallas fwd + pallas bwd) -------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret,
           q_offset=None):
    # Primal (inference) path: skip the lse output entirely.
    o, _ = _fa.flash_attention_fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                                   block_q=block_q, block_k=block_k,
                                   interpret=interpret, with_lse=False,
                                   q_offset=q_offset)
    return o


def _flash_fwd(q, k, v, sm_scale, causal, block_q, block_k, interpret,
               q_offset=None):
    if q_offset is not None:
        raise NotImplementedError(
            "q_offset (chunked-prefill causal placement) is an "
            "inference-only path; the backward kernels assume the "
            "queries are the last rows")
    o, lse = _fa.flash_attention_fwd(q, k, v, sm_scale=sm_scale, causal=causal,
                                     block_q=block_q, block_k=block_k,
                                     interpret=interpret)
    # Under jax.checkpoint with a save_only_these_names policy, naming the
    # kernel outputs lets the backward pass reuse them instead of re-running
    # the forward kernel (q/k/v are cheap weight-matmul recomputes; o/lse
    # are not). The lse residual is stored logically (BH, S, 1) — saving the
    # kernel's lane-broadcast (BH, S, LANES) layout would cost 128x the HBM.
    from jax.ad_checkpoint import checkpoint_name
    o = checkpoint_name(o, "attn_out")
    lse_small = checkpoint_name(lse[:, :, :1], "attn_lse")
    return o, (q, k, v, o, lse_small)


def _flash_bwd(sm_scale, causal, block_q, block_k, interpret, q_offset,
               res, do):
    q, k, v, o, lse_small = res
    lse = jnp.broadcast_to(lse_small, lse_small.shape[:2] + (_fa.LANES,))
    dq, dk, dv = _fa.flash_attention_bwd(
        q, k, v, o, do, lse, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, interpret=interpret)
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False,
                    q_offset: Optional[int] = None) -> jax.Array:
    """Pallas flash attention, (b, s, h, d) layout, differentiable
    (except with q_offset, which is the inference-only chunked-prefill
    causal placement)."""
    b, sq, h, d = q.shape
    k = _repeat_kv(k, h)
    v = _repeat_kv(v, h)
    sk = k.shape[1]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    # (b, s, h, d) -> (b*h, s, d)
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    of = _flash(qf, kf, vf, scale, causal, block_q, block_k, interpret,
                q_offset)
    return of.reshape(b, h, sq, d).transpose(0, 2, 1, 3)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def attention(q, k, v, *, causal: bool = True,
              sm_scale: Optional[float] = None,
              impl: str = "auto",
              block_q: int = 128, block_k: int = 128,
              q_offset: Optional[int] = None) -> jax.Array:
    """Dispatch: 'auto' uses the Pallas kernel on TPU for seq >= 128 and the
    XLA reference otherwise. 'flash' / 'reference' force a path;
    'flash_interpret' runs the kernel in interpret mode (CPU tests)."""
    if impl == "auto":
        impl = "flash" if (_on_tpu() and q.shape[1] >= 128) else "reference"
    if impl == "reference":
        return mha_reference(q, k, v, causal=causal, sm_scale=sm_scale,
                             q_offset=q_offset)
    if impl == "flash":
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               q_offset=q_offset)
    if impl == "flash_interpret":
        return flash_attention(q, k, v, causal=causal, sm_scale=sm_scale,
                               block_q=block_q, block_k=block_k,
                               interpret=True, q_offset=q_offset)
    raise ValueError(f"unknown attention impl: {impl}")
