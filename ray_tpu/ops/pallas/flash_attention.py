"""Pallas TPU flash-attention (forward + backward kernels).

The hot op of the framework's model stack. Online-softmax tiling keeps the
S x S score matrix out of HBM; blocks are sized for the MXU (128 lanes) and
VMEM residency. Used by :mod:`ray_tpu.ops.attention` which wires it into a
``jax.custom_vjp``.

Design notes (measured on v5e):
- Matmul operands stay in the input dtype (bf16) with f32 MXU accumulation;
  upcasting operands to f32 would halve MXU throughput.
- ``sm_scale`` is folded into ``q`` before the kernels run, saving a full
  elementwise pass over the S x S score matrix in every kernel (the VPU, not
  the MXU, is the bottleneck of flash attention at long seq). The dq output
  is rescaled once outside (O(S*D), negligible).
- One masked code path: TPU predication (pl.when) compiles both branches
  into the kernel, so splitting interior/edge tiles doubles VMEM stack for
  no win (measured).

Sequence lengths need not divide the block size: wrappers zero-pad to block
multiples and kernels mask out-of-bounds columns (padded rows are sliced off
and padded inputs are zeros, so gradients through padding vanish).

Capability analog of what the reference delegates to vLLM/FlashAttention CUDA
kernels (reference has no TPU attention kernel; see SURVEY.md section 5.7).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept both so the
# kernel traces on either side of the rename (an AttributeError here
# used to kill every flash-path caller on the older name).
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30
LANES = 128  # m/l scratch are broadcast along the lane dim


def _pad_seq(x, block):
    """Zero-pad (bh, s, d) along s to a multiple of block."""
    s = x.shape[1]
    pad = (-s) % block
    if pad == 0:
        return x
    return jnp.pad(x, ((0, 0), (0, pad), (0, 0)))


def _mask_s(s, qi, ki, block_q, block_k, kv_len, causal, offset):
    """Bounds + causal mask for a (block_q, block_k) score tile.

    ``offset = sk - sq`` aligns the causal diagonal with the END of the kv
    sequence (query i attends keys j <= i + offset), matching mha_reference —
    e.g. a decode step (sq=1) against a longer KV cache attends everything.
    """
    rows = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    keep = cols < kv_len
    if causal:
        keep = jnp.logical_and(keep, rows + offset >= cols)
    return jnp.where(keep, s, NEG_INF), keep


def _last_k_block(qi, block_q, block_k, num_kv_blocks, offset):
    """Last kv block (inclusive) a causal q block attends to, clamped so the
    finalize step always fires even for fully-masked q blocks."""
    last = ((qi + 1) * block_q - 1 + offset) // block_k
    return jnp.clip(last, 0, num_kv_blocks - 1)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *rest,
                causal, block_q, block_k, num_kv_blocks, kv_len,
                offset, with_lse):
    if with_lse:
        lse_ref, m_scr, l_scr, acc_scr = rest
    else:
        lse_ref = None
        m_scr, l_scr, acc_scr = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Last kv block this q block attends to (inclusive).
    if causal:
        last_k = _last_k_block(qi, block_q, block_k, num_kv_blocks, offset)
    else:
        last_k = num_kv_blocks - 1

    @pl.when(ki <= last_k)
    def _compute():
        q = q_ref[0]                                # (block_q, d), pre-scaled
        k = k_ref[0]                                # (block_k, d)
        v = v_ref[0]                                # (block_k, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s, keep = _mask_s(s, qi, ki, block_q, block_k,
                          kv_len, causal, offset)

        m_prev = m_scr[...][:, :1]                  # (block_q, 1)
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # (block_q, 1)
        m_new = jnp.maximum(m_prev, m_cur)
        # exp in the input dtype: bf16 exp is measurably faster on the VPU
        # and p feeds a bf16 MXU matmul anyway; f32 inputs keep f32 exp.
        pdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
        p = jnp.where(keep, jnp.exp((s - m_new).astype(pdt)), pdt(0.0))
        alpha = jnp.exp(m_prev - m_new)             # (block_q, 1)
        l_new = alpha * l_prev + jnp.sum(p.astype(jnp.float32), axis=-1,
                                         keepdims=True)

        acc = acc_scr[...]
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        acc_scr[...] = acc
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == last_k)
    def _finalize():
        m = m_scr[...][:, :1]
        l = l_scr[...][:, :1]
        l = jnp.where(l == 0.0, 1.0, l)             # fully-masked rows
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)
        if with_lse:
            lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), lse_ref[0].shape)


def flash_attention_fwd(q, k, v, *, sm_scale, causal, block_q=128, block_k=128,
                        interpret=False, with_lse=True, q_offset=None):
    """q,k,v: (BH, S, D) -> (o: (BH, S, D), lse: (BH, S, LANES) f32 | None).

    lse is the row logsumexp saved as a backward residual (lane-broadcast
    layout; logically (BH, S)). Inference callers pass with_lse=False to
    skip the extra HBM write (pallas outputs are never DCE'd).

    ``q_offset`` places the causal diagonal: query row i attends keys
    <= i + q_offset. Default (None) = sk - sq, i.e. queries are the
    LAST sq rows of the kv sequence. Chunked prefill passes the chunk's
    absolute start position instead (queries sit mid-sequence, not at
    the end); must be static — one compile per distinct offset."""
    bh, sq, d = q.shape
    _, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    offset = (sk - sq) if q_offset is None else int(q_offset)
    q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)  # fold scale in
    qp, kp, vp = _pad_seq(q, block_q), _pad_seq(k, block_k), _pad_seq(v, block_k)
    nq = qp.shape[1] // block_q
    nk = kp.shape[1] // block_k

    kernel = functools.partial(
        _fwd_kernel, causal=causal,
        block_q=block_q, block_k=block_k, num_kv_blocks=nk, kv_len=sk,
        offset=offset, with_lse=with_lse)

    out_specs = [pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0))]
    out_shape = [jax.ShapeDtypeStruct(qp.shape, q.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, block_q, LANES), lambda b, qi, ki: (b, qi, 0)))
        out_shape.append(
            jax.ShapeDtypeStruct((bh, qp.shape[1], LANES), jnp.float32))

    res = pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    if with_lse:
        out, lse = res
        return out[:, :sq], lse[:, :sq]
    return res[0][:, :sq], None


# ---------------------------------------------------------------------------
# Backward. lse comes from the forward kernel (saved residual — no recompute
# pass). Kernels: (1) dk/dv with grid over kv blocks, inner loop over q
# blocks; (2) dq with grid over q blocks, inner loop over kv blocks. p is
# recomputed per tile from q,k and lse; delta = rowsum(do * o).
# q arrives pre-scaled by sm_scale, so p = exp(q'k - lse) directly and
# ds needs no extra scale for dk; dq is rescaled by the wrapper.
# ---------------------------------------------------------------------------

def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, causal, block_q, block_k, num_q_blocks, kv_len,
                offset):
    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        # First q block whose rows attend this kv block: i + offset >= ki*bk.
        first_q = jnp.maximum(0, ki * block_k - offset) // block_q
        should_run = qi >= first_q
    else:
        should_run = qi >= 0

    @pl.when(should_run)
    def _compute():
        q = q_ref[0]                                # (bq, d), pre-scaled
        k = k_ref[0]                                # (bk, d)
        v = v_ref[0]
        do = do_ref[0]                              # (bq, d)
        lse = lse_ref[0][:, :1]                     # (bq, 1)
        delta = delta_ref[0][:, :1]                 # (bq, 1)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s, keep = _mask_s(s, qi, ki, block_q, block_k, kv_len, causal, offset)
        pdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
        p = jnp.where(keep, jnp.exp((s - lse).astype(pdt)), pdt(0.0))  # (bq, bk)
        # dv += p^T do
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do v^T ; ds = p * (dp - delta)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dk = ds^T q'  (q' = sm_scale*q, so the scale is already included)
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_scr,
               *, causal, block_q, block_k, num_kv_blocks, kv_len,
               offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if causal:
        last_k = _last_k_block(qi, block_q, block_k, num_kv_blocks, offset)
    else:
        last_k = num_kv_blocks - 1

    @pl.when(ki <= last_k)
    def _compute():
        q = q_ref[0]                                # pre-scaled
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]
        delta = delta_ref[0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        s, keep = _mask_s(s, qi, ki, block_q, block_k, kv_len, causal, offset)
        pdt = jnp.bfloat16 if q.dtype == jnp.bfloat16 else jnp.float32
        p = jnp.where(keep, jnp.exp((s - lse).astype(pdt)), pdt(0.0))
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dq' = ds k ; wrapper multiplies by sm_scale once outside.
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == last_k)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def flash_attention_bwd(q, k, v, o, do, lse, *, sm_scale, causal,
                        block_q=128, block_k=128, interpret=False):
    """lse: (BH, S, LANES) f32 from flash_attention_fwd."""
    bh, sq, d = q.shape
    _, sk, _ = k.shape
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    offset = sk - sq
    q = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)  # fold scale in
    qp = _pad_seq(q, block_q)
    kp, vp = _pad_seq(k, block_k), _pad_seq(v, block_k)
    op, dop = _pad_seq(o, block_q), _pad_seq(do, block_q)
    lse = _pad_seq(lse, block_q)
    sqp, skp = qp.shape[1], kp.shape[1]
    nq = sqp // block_q
    nk = skp // block_k

    delta = jnp.sum(dop.astype(jnp.float32) * op.astype(jnp.float32),
                    axis=-1)                                  # (bh, sqp)
    delta = jnp.broadcast_to(delta[:, :, None], (bh, sqp, LANES))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, num_q_blocks=nq,
                          kv_len=sk, offset=offset),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, ki, qi: (b, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, ki, qi: (b, qi, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, ki, qi: (b, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, skp, d), q.dtype),
            jax.ShapeDtypeStruct((bh, skp, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, num_kv_blocks=nk,
                          kv_len=sk, offset=offset),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, qi, ki: (b, ki, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, qi, ki: (b, qi, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, qi, ki: (b, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, qi, ki: (b, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sqp, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lse, delta)

    dq = (dq[:, :sq].astype(jnp.float32) * sm_scale).astype(q.dtype)
    return dq, dk[:, :sk], dv[:, :sk]
