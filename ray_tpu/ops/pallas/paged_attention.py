"""Pallas TPU paged-attention decode kernel.

Fuses the two reference designs the serving stack sits between:
vLLM's PagedAttention (block tables over a fixed KV pool) and the
flash-attention tiling already in ``flash_attention.py`` (online
softmax, VMEM-resident running max/sum). One decode step used to cost
a full ``gather_table`` — an O(slots x max_len x layers) HBM copy
materializing the contiguous ``(slots, max_len)`` attention view —
before any attention math ran. Here the Pallas grid walks each slot's
block table DIRECTLY: the kv index_map reads the scalar-prefetched
table and streams the slot's physical pool blocks into VMEM one at a
time, accumulating online-softmax attention. The gathered view never
exists; ``gather_table`` stays only on the prefix-hit prefill path and
in debug/parity tooling.

Numerics mirror ``ray_tpu.llm.model._gqa_attend_cached`` (the gather
path's attention): f32 score dot, post-dot ``/ sqrt(head_dim)`` scale,
f32 exp, f32 accumulation — online softmax is an exact refactoring of
the masked softmax for the same summation order within a block, so the
two impls agree to f32 rounding (and bitwise on integer-valued
constructions; see tests/test_zz_paged_attn.py).

Grid: ``(slots, kv_heads, table_width)`` with the table-walk dimension
sequential ("arbitrary"). Blocks past a slot's last live block are
clamped to the last live one in the index_map — reads stay inside
blocks the slot owns, and Mosaic's pipeliner elides the duplicate
consecutive fetches, so short slots don't pay for the table width.

Interpret mode (``interpret=True``) runs the same kernel logic through
the Pallas interpreter — tier-1 (JAX_PLATFORMS=cpu) exercises the real
table walk, masking, and online-softmax phases, not a shadow
implementation.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept both (same
# shim as flash_attention.py).
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

NEG_INF = -1e30
LANES = 128  # m/l scratch are broadcast along the lane dim


def _last_block(length, bs):
    """Index of the last live pool block for a slot with ``length``
    valid positions (length >= 1 on the decode path: empty slots carry
    position 0 => length 1, table row = trash)."""
    return jnp.maximum(length, 1) - 1


def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, bs, hd):
    b_ = pl.program_id(0)
    j = pl.program_id(2)
    length = lengths_ref[b_]
    last = _last_block(length, bs) // bs

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    @pl.when(j <= last)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)         # (g, hd)
        k = k_ref[0, :, 0, :].astype(jnp.float32)   # (bs, hd)
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) / jnp.sqrt(
                jnp.float32(hd))                    # (g, bs)
        cols = j * bs + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)
        keep = cols < length
        s = jnp.where(keep, s, NEG_INF)

        m_prev = m_scr[...][:, :1]                  # (g, 1)
        l_prev = l_scr[...][:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.where(keep, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[...] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(j == last)
    def _finalize():
        l = l_scr[...][:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, tables, lengths, *,
                    interpret=False):
    """Single-token decode attention straight through block tables.

    q: (slots, kv_heads, group, head_dim) — grouped queries, one token
    per slot; k_pool/v_pool: (num_blocks, block_size, kv_heads,
    head_dim) — ONE layer of the engine pool; tables: (slots, width)
    int32 physical block ids (trash-padded); lengths: (slots,) int32
    valid positions per slot INCLUDING the current token (>= 1).
    Returns (slots, kv_heads, group, head_dim) float32 — the same
    value ``_gqa_attend_cached`` computes from the gathered view, with
    no gathered view.
    """
    b, kvh, g, hd = q.shape
    nb, bs, kvh_p, hd_p = k_pool.shape
    if (kvh_p, hd_p) != (kvh, hd):
        raise ValueError(
            f"pool heads/dim {(kvh_p, hd_p)} != query {(kvh, hd)}")
    w = tables.shape[1]

    def _qmap(b_, h_, j, t, ln):
        return (b_, h_, 0, 0)

    def _kvmap(b_, h_, j, t, ln):
        # clamp past-the-end walks onto the slot's last live block:
        # reads never leave blocks the slot owns, and the pipeliner
        # skips re-fetching the same block on consecutive steps
        last = _last_block(ln[b_], bs) // bs
        return (t[b_, jnp.minimum(j, last)], 0, h_, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, kvh, w),
        in_specs=[
            pl.BlockSpec((1, 1, g, hd), _qmap),
            pl.BlockSpec((1, bs, 1, hd), _kvmap),
            pl.BlockSpec((1, bs, 1, hd), _kvmap),
        ],
        out_specs=pl.BlockSpec((1, 1, g, hd), _qmap),
        scratch_shapes=[
            pltpu.VMEM((g, LANES), jnp.float32),
            pltpu.VMEM((g, LANES), jnp.float32),
            pltpu.VMEM((g, hd), jnp.float32),
        ],
    )
    kernel = functools.partial(_decode_kernel, bs=bs, hd=hd)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, g, hd), jnp.float32),
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(tables.astype(jnp.int32), lengths.astype(jnp.int32), q, k_pool,
      v_pool)


def paged_attention_reference(q, k_pool, v_pool, tables, lengths):
    """Gather-then-softmax reference (the exact math
    ``_gqa_attend_cached`` runs on the gathered view) — the parity
    target the kernel is tested against, and the debug tool for
    bisecting a kernel/table discrepancy on device."""
    b, kvh, g, hd = q.shape
    _, bs, _, _ = k_pool.shape
    w = tables.shape[1]
    vk = k_pool[tables].reshape(b, w * bs, kvh, hd)
    vv = v_pool[tables].reshape(b, w * bs, kvh, hd)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bkgd,blkd->bkgl", qf,
                        vk.astype(jnp.float32)) / jnp.sqrt(hd)
    mask = jnp.arange(w * bs)[None] < lengths[:, None]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bkgl,blkd->bkgd", probs,
                      vv.astype(jnp.float32))


def paged_attention_verify(q, k_pool, v_pool, tables, lengths):
    """Multi-query verify attention through block tables — the gather
    twin of ``paged_attention`` widened to w in-flight queries per
    slot for speculative decoding: query j attends the cached history
    PLUS the draft tokens written ahead of it this round, under a
    per-query causal mask.

    q: (slots, w, kv_heads, group, head_dim) — the last emitted token
    plus up to w-1 draft tokens per slot; k_pool/v_pool: one layer of
    the engine pool as in ``paged_attention``; tables: (slots, width)
    int32; lengths: (slots, w) int32 valid positions per QUERY
    including that query's own token (column j = cached + j + 1).
    Returns (slots, w, kv_heads, group, head_dim) float32.

    This is a gather-based implementation (materializes the table view
    per layer, like ``paged_attention_reference``): one verify round
    replaces w sequential decode steps, so it pays ONE gather where
    the sequential gather path paid w — the win the spec-decode bench
    measures. Extending the fused one-query-per-block-walk kernel
    above to multi-query rows is future work; exact-zero masking
    (NEG_INF then softmax) keeps pool bytes beyond each query's mask
    bitwise-irrelevant, so verify rows reproduce sequential decode's
    attention exactly."""
    b, wq, kvh, g, hd = q.shape
    _, bs, _, _ = k_pool.shape
    w = tables.shape[1]
    vk = k_pool[tables].reshape(b, w * bs, kvh, hd)
    vv = v_pool[tables].reshape(b, w * bs, kvh, hd)
    qf = q.astype(jnp.float32)
    scores = jnp.einsum("bwkgd,blkd->bwkgl", qf,
                        vk.astype(jnp.float32)) / jnp.sqrt(hd)
    mask = (jnp.arange(w * bs)[None, None]
            < lengths[:, :, None])                  # (b, wq, w*bs)
    scores = jnp.where(mask[:, :, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bwkgl,blkd->bwkgd", probs,
                      vv.astype(jnp.float32))
