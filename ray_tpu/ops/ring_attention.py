"""Ring attention: exact causal attention over a context-parallel mesh axis.

Each device holds a sequence shard of q, k, v. K/V shards rotate around the
ring via ``lax.ppermute`` while each device folds the visiting chunk into an
online-softmax accumulator — communication rides the ICI ring and overlaps
with the chunk matmuls. Memory is O(S_local^2) per step, O(S_local) state.

The reference framework has no sequence/context parallelism at all
(SURVEY.md section 2.3 verifies the absence); this op plus the "context" mesh
axis in ray_tpu.parallel is the TPU-native capability that fills that gap.

Call inside ``jax.shard_map`` with the sequence dim sharded over
``axis_name``. Differentiable via JAX autodiff (ppermute transposes to the
reverse permutation); per-step work is rematerialized with jax.checkpoint.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


def _axis_size(axis_name) -> int:
    """Static mapped-axis size across the jax API move: new jax has
    lax.axis_size; older jax exposes it through core.axis_frame
    (which returns the bare size there)."""
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    from jax import core
    fr = core.axis_frame(axis_name)
    return int(getattr(fr, "size", fr))


def _chunk_attn(q, k, v, q_off, k_off, causal, scale):
    """One ring step: q local block vs one visiting kv chunk.

    q: (b, sq, h, d); k, v: (b, sk, kvh, d) — GQA heads are expanded HERE,
    after the ring transfer, so only kvh heads ride the ICI ring. Offsets are
    global sequence positions of element 0. Returns (o_unnorm f32, m, l) with
    shapes ((b, sq, h, d), (b, h, sq), (b, h, sq)).
    """
    from ray_tpu.ops.attention import _repeat_kv
    k = _repeat_kv(k, q.shape[2])
    v = _repeat_kv(v, q.shape[2])
    sq, sk = q.shape[1], k.shape[1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        rows = q_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        cols = k_off + lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        keep = rows >= cols
        s = jnp.where(keep[None, None], s, _NEG)
    m = jnp.max(s, axis=-1)                              # (b, h, sq)
    p = jnp.exp(s - m[..., None])
    if causal:
        p = jnp.where(keep[None, None], p, 0.0)          # kill exp(0) on -inf rows
    l = jnp.sum(p, axis=-1)                              # (b, h, sq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Exact attention with seq sharded over ``axis_name``; (b, s, h, d).
    GQA k/v keep their kvh heads while rotating (n_heads/kvh less ICI
    traffic); expansion happens per-chunk inside _chunk_attn."""
    b, sq, h, d = q.shape
    scale = sm_scale if sm_scale is not None else d ** -0.5
    n = _axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    sk = k.shape[1]
    q_off = idx * sq

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        o, m, l, k_cur, v_cur = carry
        src = (idx - t) % n               # whose shard is visiting this step
        k_off = src * sk

        def compute(_):
            oc, mc, lc = _chunk_attn(q, k_cur, v_cur, q_off, k_off,
                                     causal, scale)
            m_new = jnp.maximum(m, mc)
            a1 = jnp.exp(m - m_new)                      # (b, h, sq)
            a2 = jnp.exp(mc - m_new)
            a1t = jnp.transpose(a1, (0, 2, 1))[..., None]  # (b, sq, h, 1)
            a2t = jnp.transpose(a2, (0, 2, 1))[..., None]
            o2 = o * a1t + oc * a2t
            return o2, m_new, l * a1 + lc * a2

        def skip(_):
            return o, m, l

        if causal:
            # Chunk entirely in the future of every local row -> no-op.
            fully_masked = k_off > q_off + sq - 1
            o2, m2, l2 = lax.cond(fully_masked, skip, compute, None)
        else:
            o2, m2, l2 = compute(None)

        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (o2, m2, l2, k_nxt, v_nxt), None

    # Derive accumulators from q so they carry q's varying-manual-axes set
    # (shard_map vma tracking; a plain zeros constant would be unvarying and
    # trip lax.cond's branch-type check).
    zeros = q.astype(jnp.float32) * 0.0
    o0 = zeros
    base = jnp.transpose(zeros[..., 0], (0, 2, 1))      # (b, h, sq)
    m0 = base + _NEG
    l0 = base
    zscalar = jnp.sum(zeros) * 0.0  # scalar carrying q's vma
    k = k + zscalar.astype(k.dtype)  # unify kv vma with q's as well
    v = v + zscalar.astype(v.dtype)
    (o, m, l, _, _), _ = lax.scan(
        jax.checkpoint(step), (o0, m0, l0, k, v), jnp.arange(n))
    l = jnp.where(l == 0.0, 1.0, l)
    o = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return o.astype(q.dtype)
