from ray_tpu.parallel.mesh import MeshSpec, make_mesh, make_train_step, TrainState

__all__ = ["MeshSpec", "make_mesh", "make_train_step", "TrainState"]
