"""Device mesh + SPMD train step.

The TPU-native answer to the reference's whole parallelism-strategy table
(SURVEY.md section 2.3): DP/FSDP/TP/CP are axes of ONE ``jax.sharding.Mesh``;
XLA GSPMD inserts the collectives (psum for grads over data/fsdp,
reduce-scatter/all-gather for fsdp params, all-reduce for tensor partials,
ppermute rings for the context axis via ray_tpu.ops.ring_attention).

Where the reference wires NCCL process groups per strategy
(reference: python/ray/util/collective/collective.py:303), here the only
"backend setup" is building the mesh; sharding annotations do the rest.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ray_tpu.models import llama
from ray_tpu.models.llama import LlamaConfig, MeshAxes


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Axis sizes; -1 means "absorb all remaining devices" (at most one)."""
    data: int = 1
    fsdp: int = -1
    tensor: int = 1
    context: int = 1
    expert: int = 1
    axes: MeshAxes = MeshAxes()

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {self.axes.data: self.data, self.axes.fsdp: self.fsdp,
                 self.axes.tensor: self.tensor,
                 self.axes.context: self.context,
                 self.axes.expert: self.expert}
        unknown = [a for a, s in sizes.items() if s == -1]
        known = 1
        for s in sizes.values():
            if s != -1:
                known *= s
        if len(unknown) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if unknown:
            if n_devices % known:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {known}")
            sizes[unknown[0]] = n_devices // known
        total = 1
        for s in sizes.values():
            total *= s
        if total != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


def make_mesh(spec: MeshSpec = MeshSpec(),
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    names = tuple(sizes.keys())
    shape = tuple(sizes.values())
    import numpy as np
    return Mesh(np.asarray(devices).reshape(shape), names)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def make_train_step(cfg, mesh: Mesh,
                    axes: MeshAxes = MeshAxes(),
                    optimizer: Optional[optax.GradientTransformation] = None,
                    loss_fn: Optional[Callable] = None,
                    model=llama):
    """Returns (init_fn(rng) -> TrainState, step_fn(state, batch) ->
    (state, metrics)). Both jitted with GSPMD sharding: params per
    model.param_shardings, batch over (data+fsdp, context), opt state
    sharded like params by propagation.

    ``model`` is any module exposing the model-family protocol
    (init_params / param_shardings / loss_fn) — ray_tpu.models.llama
    (default) or ray_tpu.models.moe."""
    opt = optimizer if optimizer is not None else default_optimizer()
    _loss = loss_fn if loss_fn is not None else (
        lambda p, b: model.loss_fn(p, b, cfg, mesh, axes))
    pspecs = model.param_shardings(cfg, axes)
    pshard = jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs,
                          is_leaf=lambda x: isinstance(x, P))
    batch_spec = NamedSharding(mesh, P(axes.batch, axes.context))

    @jax.jit
    def init_fn(rng) -> TrainState:
        params = jax.lax.with_sharding_constraint(
            model.init_params(rng, cfg), pshard)
        opt_state = opt.init(params)
        return TrainState(params, opt_state, jnp.zeros((), jnp.int32))

    @jax.jit
    def step_fn(state: TrainState, batch: dict):
        batch = {k: jax.lax.with_sharding_constraint(v, batch_spec)
                 for k, v in batch.items()}
        loss, grads = jax.value_and_grad(_loss)(state.params, batch)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        return (TrainState(params, opt_state, state.step + 1),
                {"loss": loss, "grad_norm": gnorm, "step": state.step + 1})

    return init_fn, step_fn


def make_eval_step(cfg, mesh: Mesh,
                   axes: MeshAxes = MeshAxes(), model=llama):
    @jax.jit
    def eval_fn(params, batch):
        return model.loss_fn(params, batch, cfg, mesh, axes)
    return eval_fn
