"""Cloud node providers for the autoscaler.

The pluggable counterpart of the reference's provider tree (reference:
python/ray/autoscaler/_private/{gcp,aws,kuberay}/node_provider.py).
TPU-first, the one that matters is GCP's queued-resources API for TPU
slices: ray_tpu.providers.gcp.
"""

from ray_tpu.providers.gcp import (GCPClient, TPUQueuedResourceProvider,
                                   TPUSliceAutoscaler)

__all__ = ["GCPClient", "TPUQueuedResourceProvider", "TPUSliceAutoscaler"]
