"""GCP TPU node provider: queued-resource slices for the autoscaler.

Reference: python/ray/autoscaler/_private/gcp/node_provider.py (GCE
instances + TPU VMs via googleapiclient) and the KubeRay provider. Here
the provider targets the TPU **queued resources** API — the way real
TPU capacity is obtained (slice-granular, queue-until-available, which
matches the control plane's patient PENDING placement groups) — through
a minimal injectable REST client, so everything is unit-testable
offline with a fake transport and runs against the live API with the
default one.

Shape of the integration:

- ``TPUQueuedResourceProvider`` creates/deletes/lists queued resources
  (one queued resource == one TPU slice == `pod_hosts(pod_type)`
  cluster nodes once the VMs boot and run the startup script that
  joins them to the head).
- ``TPUSliceAutoscaler`` extends the core reconciler with a SLICE pass:
  every PENDING placement group whose bundles are all-TPU (the shape
  ``slice_placement_group`` emits) becomes one queued-resource create
  of the matching topology; the slice is deleted when its motivating
  placement group no longer exists. CPU-shaped demand still flows
  through the base class (a LocalNodeProvider or a second cloud
  provider can serve it).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ray_tpu.autoscaler import Autoscaler, AutoscalerConfig, NodeProvider
from ray_tpu.util import tpu as tpu_util

# GCE accelerator-type naming: v5e pods are "v5litepod-N"; every other
# generation uses its own prefix verbatim.
_ACCEL_NAME = {"v5e": "v5litepod"}


def accelerator_type(pod_type: str) -> str:
    """'v5e-16' -> 'v5litepod-16', 'v4-8' -> 'v4-8'."""
    gen, _, chips = pod_type.partition("-")
    return f"{_ACCEL_NAME.get(gen, gen)}-{chips}"


def pod_type_for(chips: int, chips_per_host: float,
                 generation: str = "v5e") -> str:
    """The pod type a pending slice PG implies: total chips across its
    bundles, named under the configured generation."""
    del chips_per_host  # topology is fully determined by total chips
    return f"{generation}-{int(chips)}"


class TransientAPIError(RuntimeError):
    """A rate-limit (429) / server-blip API failure that outlived the
    client's quick retries: the caller should back off and try again
    later — it is NOT a permanent failure."""


class GCPClient:
    """Minimal REST transport for tpu.googleapis.com.

    ``request(method, url, body) -> (status, dict)`` is injectable —
    tests pass a fake; production uses urllib with a bearer token from
    ``token_supplier`` (defaults to the GCE metadata server, the
    ambient credential on any GCP VM)."""

    API = "https://tpu.googleapis.com/v2"
    METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/"
                          "v1/instance/service-accounts/default/token")

    def __init__(self, project: str, zone: str,
                 request: Optional[Callable] = None,
                 token_supplier: Optional[Callable[[], str]] = None):
        self.project = project
        self.zone = zone
        self._request = request or self._urllib_request
        self._token_supplier = token_supplier or self._metadata_token
        self._token: Tuple[str, float] = ("", 0.0)

    # --- transport -----------------------------------------------------

    def _metadata_token(self) -> str:
        import urllib.request
        tok, exp = self._token
        if tok and time.monotonic() < exp - 60:
            return tok
        req = urllib.request.Request(
            self.METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=10) as r:
            data = json.loads(r.read().decode())
        self._token = (data["access_token"],
                       time.monotonic() + float(data.get("expires_in", 300)))
        return self._token[0]

    def _urllib_request(self, method: str, url: str,
                        body: Optional[dict]) -> Tuple[int, dict]:
        import urllib.error
        import urllib.request
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Authorization": f"Bearer {self._token_supplier()}",
                     "Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                payload = r.read().decode()
                return r.status, (json.loads(payload) if payload else {})
        except urllib.error.HTTPError as e:  # structured API errors
            try:
                return e.code, json.loads(e.read().decode())
            except Exception:
                return e.code, {"error": str(e)}

    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    # Transient API statuses worth an immediate short retry: rate
    # limits and server-side blips. Anything else is surfaced to the
    # reconciler, which applies its own longer, non-blocking backoff.
    RETRYABLE = frozenset({429, 500, 502, 503, 504})

    def _call(self, method: str, url: str,
              body: Optional[dict]) -> Tuple[int, dict]:
        """_request with two quick exponential retries on transient
        statuses/transport errors — absorbs blips without stalling the
        reconcile loop for long (sustained 429s are the RECONCILER's
        problem: it backs off per-PG without blocking)."""
        delay = 0.5
        last_exc: Optional[Exception] = None
        for attempt in range(3):
            try:
                status, resp = self._request(method, url, body)
                last_exc = None
            except Exception as e:  # noqa: BLE001 — network blip
                last_exc = e
                status, resp = 599, {"error": str(e)}
            transient = status in self.RETRYABLE or status == 599
            if not transient or attempt == 2:
                if last_exc is not None:
                    raise TransientAPIError(str(last_exc)) from last_exc
                return status, resp
            time.sleep(delay)
            delay *= 2
        return status, resp  # pragma: no cover — loop always returns

    # --- queued resources ----------------------------------------------

    def create_queued_resource(self, qr_id: str, node: dict) -> dict:
        """POST a queued-resource create; `node` is the TPU node spec
        (acceleratorType, runtimeVersion, metadata with the join
        script, labels)."""
        url = (f"{self.API}/{self._parent()}/queuedResources"
               f"?queued_resource_id={qr_id}")
        body = {"tpu": {"node_spec": [{"parent": self._parent(),
                                       "node_id": qr_id,
                                       "node": node}]}}
        status, resp = self._call("POST", url, body)
        if status in self.RETRYABLE:
            raise TransientAPIError(
                f"create_queued_resource {qr_id}: {status} {resp}")
        if status >= 300:
            raise RuntimeError(f"create_queued_resource {qr_id}: "
                               f"{status} {resp}")
        return resp

    def delete_queued_resource(self, qr_id: str) -> None:
        url = (f"{self.API}/{self._parent()}/queuedResources/{qr_id}"
               f"?force=true")
        status, resp = self._call("DELETE", url, None)
        if status in self.RETRYABLE:
            raise TransientAPIError(
                f"delete_queued_resource {qr_id}: {status} {resp}")
        if status >= 300 and status != 404:
            raise RuntimeError(f"delete_queued_resource {qr_id}: "
                               f"{status} {resp}")

    def list_queued_resources(self) -> List[dict]:
        url = f"{self.API}/{self._parent()}/queuedResources"
        status, resp = self._call("GET", url, None)
        if status >= 300:
            raise RuntimeError(f"list_queued_resources: {status} {resp}")
        return resp.get("queuedResources", [])


_DEAD_QR_STATES = {"FAILED", "SUSPENDED", "SUSPENDING", "DELETING"}

_JOIN_SCRIPT = """#!/bin/bash
# ray_tpu slice bootstrap: every TPU VM host joins the head as a node.
python3 -m ray_tpu.node --address {head_address} \\
    --labels '{labels_json}' >> /var/log/ray_tpu_node.log 2>&1 &
"""


class TPUQueuedResourceProvider(NodeProvider):
    """TPU slices via queued resources. One launch() == one slice; the
    pod type rides labels["tpu_pod_type"] (the slice autoscaler sets
    it) or falls back to ``default_pod_type``."""

    def __init__(self, client: GCPClient, head_address: str,
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 default_pod_type: str = "v5e-8",
                 name_prefix: str = "ray-tpu"):
        self.client = client
        self.head_address = head_address
        self.runtime_version = runtime_version
        self.default_pod_type = default_pod_type
        self.name_prefix = name_prefix
        self._n = 0

    async def launch(self, resources: Dict[str, float],
                     labels: Dict[str, str]) -> str:
        pod_type = labels.get("tpu_pod_type", self.default_pod_type)
        self._n += 1
        qr_id = f"{self.name_prefix}-{pod_type}-{self._n}-" \
                f"{int(time.time()) % 100000}"
        node_labels = {**labels, "autoscaler_handle": qr_id,
                       "ray-tpu-cluster": "true"}
        node = {
            "acceleratorType": accelerator_type(pod_type),
            "runtimeVersion": self.runtime_version,
            "labels": {k.replace("_", "-"): str(v)[:62]
                       for k, v in node_labels.items()},
            "metadata": {
                "startup-script": _JOIN_SCRIPT.format(
                    head_address=self.head_address,
                    labels_json=json.dumps(node_labels)),
            },
        }
        self.client.create_queued_resource(qr_id, node)
        return qr_id

    async def terminate(self, handle: str) -> None:
        self.client.delete_queued_resource(handle)

    async def alive_handles(self) -> List[str]:
        out = []
        for qr in self.client.list_queued_resources():
            state = (qr.get("state") or {}).get("state", "")
            name = qr.get("name", "").rsplit("/", 1)[-1]
            if name.startswith(self.name_prefix) and \
                    state not in _DEAD_QR_STATES:
                out.append(name)
        return out

    def handle_labels(self, handle: str) -> Dict[str, str]:
        """Labels of one live queued resource (slice bookkeeping)."""
        for qr in self.client.list_queued_resources():
            name = qr.get("name", "").rsplit("/", 1)[-1]
            if name == handle:
                specs = ((qr.get("tpu") or {}).get("node_spec")
                         or (qr.get("tpu") or {}).get("nodeSpec") or [])
                if specs:
                    return dict((specs[0].get("node") or {})
                                .get("labels") or {})
        return {}


@dataclass
class SliceScalerConfig(AutoscalerConfig):
    generation: str = "v5e"
    max_slices: int = 4
    # a slice whose motivating PG vanished is deleted after this grace
    slice_idle_timeout_s: float = 60.0


class TPUSliceAutoscaler(Autoscaler):
    """Reconciler with a TPU-slice pass on top of the CPU-shaped base.

    Pending all-TPU STRICT_SPREAD placement groups (the shape
    ``slice_placement_group`` emits — SURVEY §7's "slice reservation
    races autoscaling" hard part) map 1:1 to queued-resource creates of
    the matching topology; slices whose PG is gone are deleted after a
    grace period."""

    def __init__(self, head_address: str,
                 slice_provider: TPUQueuedResourceProvider,
                 config: Optional[SliceScalerConfig] = None,
                 base_provider: Optional[NodeProvider] = None):
        super().__init__(head_address,
                         base_provider or _NullProvider(),
                         config or SliceScalerConfig())
        self.slice_provider = slice_provider
        self._pg_slices: Dict[str, str] = {}     # pg hex -> qr handle
        self._slice_orphaned_at: Dict[str, float] = {}
        # pg hex -> (next_attempt_monotonic, current_delay): create
        # failures (quota 429s, API errors) back off exponentially per
        # PG WITHOUT blocking the reconcile loop — a transient failure
        # must not be indistinguishable from a permanent one, and a
        # sustained quota error must not hammer the API every pass.
        self._create_backoff: Dict[str, Tuple[float, float]] = {}
        self.CREATE_BACKOFF_INITIAL_S = 5.0
        self.CREATE_BACKOFF_MAX_S = 300.0

    async def reconcile_once(self) -> dict:
        actions = await super().reconcile_once()
        actions.update(await self._reconcile_slices())
        return actions

    @staticmethod
    def _slice_pgs(pgs) -> Dict[str, str]:
        """pg hex -> pod-type-determining chip count for PENDING
        all-TPU gangs."""
        out = {}
        for pg in pgs:
            bundles = pg.get("bundles") or []
            if pg.get("state") != "PENDING" or not bundles:
                continue
            if not all(float(b.get("TPU", 0)) > 0 for b in bundles):
                continue
            out[_pg_hex(pg["pg_id"])] = bundles
        return out

    async def _reconcile_slices(self) -> dict:
        cfg: SliceScalerConfig = self.config  # type: ignore[assignment]
        actions = {"slices_created": 0, "slices_deleted": 0}
        pgs = await self.pool.call(self.head_addr, "list_pgs",
                                   timeout=10.0)
        live_pg_ids = {_pg_hex(p["pg_id"]) for p in pgs
                       if p.get("state") != "REMOVED"}
        pending = self._slice_pgs(pgs)

        handles = set(await self.slice_provider.alive_handles())
        self._pg_slices = {pg: h for pg, h in self._pg_slices.items()
                           if h in handles}
        claimed = set(self._pg_slices.values())
        # Re-learn pg->slice claims from cloud labels (restart safety).
        for h in handles - claimed:
            pg = self.slice_provider.handle_labels(h).get("slice-for-pg") \
                or self.slice_provider.handle_labels(h).get("slice_for_pg")
            if pg:
                self._pg_slices.setdefault(pg, h)
        claimed = set(self._pg_slices.values())

        # create: one slice per unclaimed pending slice-PG (failures
        # back off per PG — see _create_backoff)
        now0 = time.monotonic()
        actions["slice_create_errors"] = 0
        for pg_hex, bundles in pending.items():
            if pg_hex in self._pg_slices:
                continue
            if len(handles) >= cfg.max_slices:
                break
            next_try, delay = self._create_backoff.get(pg_hex, (0.0, 0.0))
            if now0 < next_try:
                continue
            chips = int(sum(float(b["TPU"]) for b in bundles))
            pod_type = pod_type_for(chips, 0, cfg.generation)
            per_host = {"TPU": float(max(float(b["TPU"])
                                         for b in bundles))}
            try:
                handle = await self.slice_provider.launch(
                    per_host, {"tpu_pod_type": pod_type,
                               "slice_for_pg": pg_hex})
            except Exception as e:  # noqa: BLE001 — transient OR quota
                new_delay = min(
                    self.CREATE_BACKOFF_MAX_S,
                    max(self.CREATE_BACKOFF_INITIAL_S, delay * 2))
                self._create_backoff[pg_hex] = (now0 + new_delay,
                                                new_delay)
                actions["slice_create_errors"] += 1
                actions.setdefault("slice_create_last_error",
                                   f"{type(e).__name__}: {e}")
                continue
            self._create_backoff.pop(pg_hex, None)
            self._pg_slices[pg_hex] = handle
            handles.add(handle)
            actions["slices_created"] += 1
        # PGs that got a slice (or vanished) drop their backoff record
        for pg_hex in list(self._create_backoff):
            if pg_hex not in pending or pg_hex in self._pg_slices:
                del self._create_backoff[pg_hex]

        # delete: slices whose motivating PG no longer exists
        now = time.monotonic()
        by_handle = {h: pg for pg, h in self._pg_slices.items()}
        for h in list(handles):
            pg = by_handle.get(h)
            if pg is not None and pg in live_pg_ids:
                self._slice_orphaned_at.pop(h, None)
                continue
            since = self._slice_orphaned_at.setdefault(h, now)
            if now - since < cfg.slice_idle_timeout_s:
                continue
            await self.slice_provider.terminate(h)
            self._slice_orphaned_at.pop(h, None)
            if pg is not None:
                self._pg_slices.pop(pg, None)
            actions["slices_deleted"] += 1
        return actions


class _NullProvider(NodeProvider):
    """Base-provider stub when only TPU slices are autoscaled: CPU
    launches are recorded (visible in tests/metrics) but create
    nothing."""

    def __init__(self):
        self.ignored_launches = 0

    async def launch(self, resources, labels) -> str:
        self.ignored_launches += 1
        return f"null-{self.ignored_launches}"

    async def terminate(self, handle: str) -> None:
        pass

    async def alive_handles(self) -> List[str]:
        return []


def _pg_hex(v) -> str:
    return v.hex() if hasattr(v, "hex") else str(v)
