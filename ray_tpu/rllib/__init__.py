"""RL layer: parallel rollout actors + jitted learners.

Analog of the reference's RLlib core loop (reference: python/ray/rllib/
algorithms/algorithm.py train() driving env_runner_group + learner_group)
covering both halves of the algorithm matrix: on-policy (PPO) and
off-policy with a replay-buffer actor (DQN).
"""

from ray_tpu.rllib.bc import BC, BCConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.env import CartPoleVec, PendulumVec, make_env
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = ["BC", "BCConfig", "DQN", "DQNConfig", "IMPALA",
           "IMPALAConfig", "PPO", "PPOConfig", "SAC", "SACConfig",
           "ReplayBuffer", "CartPoleVec", "PendulumVec", "make_env"]
