"""RL layer: parallel rollout actors + jitted learners.

Analog of the reference's RLlib core loop (reference: python/ray/rllib/
algorithms/algorithm.py train() driving env_runner_group + learner_group)
covering both halves of the algorithm matrix: on-policy (PPO) and
off-policy with a replay-buffer actor (DQN).
"""

from ray_tpu.rllib.appo import APPO, APPOConfig
from ray_tpu.rllib.bc import BC, BCConfig
from ray_tpu.rllib.dqn import DQN, DQNConfig, ReplayBuffer
from ray_tpu.rllib.env import (CartPoleVec, MultiCartPoleVec,
                               PendulumVec, make_env)
from ray_tpu.rllib.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.multi_agent import (MultiAgentPPO,
                                       MultiAgentPPOConfig,
                                       make_multi_agent_env)
from ray_tpu.rllib.ppo import PPO, PPOConfig
from ray_tpu.rllib.sac import SAC, SACConfig

__all__ = ["APPO", "APPOConfig", "BC", "BCConfig", "DQN", "DQNConfig",
           "IMPALA", "IMPALAConfig", "MultiAgentPPO",
           "MultiAgentPPOConfig", "PPO", "PPOConfig", "SAC",
           "SACConfig", "ReplayBuffer", "CartPoleVec",
           "MultiCartPoleVec", "PendulumVec", "make_env",
           "make_multi_agent_env"]
