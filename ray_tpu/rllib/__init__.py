"""Minimal RL layer: parallel rollout actors + jitted PPO learner.

Analog of the reference's RLlib core loop (reference: python/ray/rllib/
algorithms/algorithm.py train() driving env_runner_group + learner_group)
at the scale of one algorithm done properly on jax.
"""

from ray_tpu.rllib.env import CartPoleVec, make_env
from ray_tpu.rllib.ppo import PPO, PPOConfig

__all__ = ["PPO", "PPOConfig", "CartPoleVec", "make_env"]
