"""APPO: asynchronous PPO — IMPALA's actor-learner pipeline with the
PPO clipped surrogate on V-trace-corrected advantages.

Reference: python/ray/rllib/algorithms/appo/appo.py (APPO = IMPALA-style
async sampling + V-trace off-policy correction + PPO's ratio clip,
per "IMPACT", Luo et al. 2020). The TPU-idiomatic shape is IMPALA's:
runners sample with the weights they were last handed, the learner
drains ready fragments and re-dispatches — but the policy loss clips
the importance ratio instead of multiplying it in, which tolerates the
staleness a busy pipeline accumulates better than raw V-trace PG.

Deliberate scope cut vs the reference: no separate target network /
KL-coeff adaption — the clip is the stabilizer (the reference's own
default path; target-net mixing is an option there)."""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax

from ray_tpu.rllib.impala import IMPALA, IMPALAConfig, _vtrace
from ray_tpu.rllib.ppo import policy_forward


@partial(jax.jit, static_argnames=("lr", "gamma", "clip"))
def appo_update(params, opt_state, batch, *, lr=3e-4, gamma=0.99,
                clip=0.3, vf_coef=0.5, ent_coef=0.01,
                rho_bar=1.0, c_bar=1.0):
    """One fragment's clipped-surrogate update on V-trace targets.
    batch: obs (T, N, D), actions / behavior_logp / rewards / dones
    (T, N), last_obs (N, D)."""
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)
    T, N = batch["actions"].shape
    obs_flat = batch["obs"].reshape(T * N, -1)

    def loss_fn(p):
        logits, values = policy_forward(p, obs_flat)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        _, last_value = policy_forward(p, batch["last_obs"])
        vs, pg_adv = _vtrace(
            batch["behavior_logp"], target_logp, batch["rewards"],
            batch["dones"], values, last_value, gamma,
            rho_bar=rho_bar, c_bar=c_bar)
        vs = jax.lax.stop_gradient(vs)
        # raw V-trace advantages, like IMPALA: per-fragment mean/std
        # normalization is noisy at (T*N)~512 and washed out the
        # baseline signal in practice
        adv = jax.lax.stop_gradient(pg_adv)
        # PPO surrogate against the BEHAVIOR policy (the off-policy
        # ratio the clip bounds is exactly the staleness ratio)
        ratio = jnp.exp(target_logp - batch["behavior_logp"])
        unclipped = ratio * adv
        clipped = jnp.clip(ratio, 1 - clip, 1 + clip) * adv
        pi_loss = -jnp.minimum(unclipped, clipped).mean()
        v_loss = ((values - vs) ** 2).mean()
        probs = jax.nn.softmax(logits)
        entropy = -(probs * jnp.log(probs + 1e-9)).sum(-1).mean()
        total = pi_loss + vf_coef * v_loss - ent_coef * entropy
        return total, ratio

    (loss, ratio), grads = jax.value_and_grad(
        loss_fn, has_aux=True)(params)
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, ratio.mean()


@dataclass
class APPOConfig(IMPALAConfig):
    lr: float = 1e-3
    clip: float = 0.3


class APPO(IMPALA):
    """Async PPO: IMPALA's pipeline, PPO's objective."""

    def _apply_update(self, batch):
        return appo_update(
            self.params, self.opt_state, batch,
            lr=self.cfg.lr, gamma=self.cfg.gamma, clip=self.cfg.clip,
            vf_coef=self.cfg.vf_coef, ent_coef=self.cfg.ent_coef,
            rho_bar=self.cfg.rho_bar, c_bar=self.cfg.c_bar)
