"""Behavior cloning: offline RL from a logged transition dataset.

The offline column of the reference's algorithm matrix (reference:
python/ray/rllib/algorithms/bc/bc.py — learn a policy by supervised
imitation of a logged dataset, evaluated by rolling the cloned policy
in the env). TPU-idiomatic like the other learners: the dataset rides
ray_tpu.data (any reader — parquet, tfrecord, from_items), and the
whole K-minibatch cross-entropy update runs as ONE jitted ``lax.scan``
per train iteration.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import jax
import numpy as np

from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import init_policy, policy_forward


@partial(jax.jit, static_argnames=("lr",))
def bc_update(params, opt_state, batches, *, lr=1e-3):
    """Cross-entropy imitation over a stack of minibatches in one
    lax.scan. batches: {"obs": (K, B, O), "actions": (K, B)}."""
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    def loss_fn(p, mb):
        logits, _v = policy_forward(p, mb["obs"])
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(
            logp, mb["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
        return nll.mean()

    def step(carry, mb):
        p, os_ = carry
        l, g = jax.value_and_grad(loss_fn)(p, mb)
        updates, os_ = opt.update(g, os_, p)
        p = optax.apply_updates(p, updates)
        return (p, os_), l

    (params, opt_state), losses = jax.lax.scan(
        step, (params, opt_state), batches)
    return params, opt_state, losses.mean()


@dataclass
class BCConfig:
    env: str = "CartPole-v1"          # for evaluation rollouts
    batch_size: int = 256
    updates_per_iter: int = 32
    lr: float = 1e-3
    hidden: tuple = (64, 64)
    eval_episodes: int = 8
    seed: int = 0


class BC:
    """``BC(dataset, config).train()`` — dataset is a ray_tpu.data
    Dataset (or any iterable of blocks) with ``obs`` (row-major float)
    and ``action`` (int) columns."""

    def __init__(self, dataset, config: Optional[BCConfig] = None):
        import optax
        self.cfg = config or BCConfig()
        env = make_env(self.cfg.env, 1, 0)
        self.obs_dim, self.n_actions = env.OBS_DIM, env.N_ACTIONS
        # materialize the logged data once (offline training data is
        # bounded; the reference's BC reads it through ray.data too)
        obs, act = [], []
        seen_cols = set()
        for b in dataset.iter_blocks():
            seen_cols.update(b.keys())
            if len(b.get("action", ())) and "obs" in b:
                obs.append(np.asarray(b["obs"], np.float32))
                act.append(np.asarray(b["action"], np.int64))
        if not obs:
            raise ValueError(
                "BC needs a dataset with 'obs' and 'action' columns; "
                f"got columns {sorted(seen_cols) or '(no rows)'}")
        self._obs = np.concatenate(obs)
        self._act = np.concatenate(act)
        if self._obs.shape[1] != self.obs_dim:
            raise ValueError(
                f"dataset obs dim {self._obs.shape[1]} != env obs dim "
                f"{self.obs_dim}")
        self.params = init_policy(
            jax.random.PRNGKey(self.cfg.seed), self.obs_dim,
            self.n_actions, self.cfg.hidden)
        self.opt_state = optax.adam(self.cfg.lr).init(self.params)
        self._rng = np.random.default_rng(self.cfg.seed)
        self._fwd = jax.jit(policy_forward)
        self._iter = 0

    def train(self) -> dict:
        import jax.numpy as jnp
        c = self.cfg
        self._iter += 1
        n = len(self._obs)
        ids = self._rng.integers(0, n, size=(c.updates_per_iter,
                                             c.batch_size))
        batches = {"obs": jnp.asarray(self._obs[ids]),
                   "actions": jnp.asarray(self._act[ids])}
        self.params, self.opt_state, loss = bc_update(
            self.params, self.opt_state, batches, lr=c.lr)
        ret = self.evaluate(c.eval_episodes)
        return {"training_iteration": self._iter,
                "loss": float(loss),
                "episode_reward_mean": ret,
                "dataset_size": n}

    def evaluate(self, episodes: int) -> float:
        """Greedy rollouts of the cloned policy."""
        env = make_env(self.cfg.env, episodes, self.cfg.seed + 7)
        obs = env.reset_all()
        done_ret = []
        ep_ret = np.zeros(episodes, np.float32)
        for _ in range(env.MAX_STEPS + 1):
            logits, _v = self._fwd(self.params, obs)
            a = np.asarray(logits).argmax(axis=1).astype(np.int32)
            obs, r, done = env.step(a)
            ep_ret += r
            if done.any():
                for i in np.where(done)[0]:
                    done_ret.append(float(ep_ret[i]))
                    ep_ret[i] = 0.0
            if len(done_ret) >= episodes:
                break
        return float(np.mean(done_ret)) if done_ret else 0.0

    def get_policy_params(self):
        return jax.device_get(self.params)
