"""DQN: off-policy learning over a replay-buffer actor.

The off-policy half of the reference's algorithm matrix (reference:
python/ray/rllib/algorithms/dqn/dqn.py + utils/replay_buffers/ — env
runners feed a replay buffer, the learner samples uniformly and applies
double-DQN updates against a periodically-synced target network), built
TPU-idiomatically: the replay buffer is a runtime actor holding numpy
ring storage, and the entire K-minibatch update loop runs as ONE jitted
``lax.scan`` so the learner does a single dispatch per train iteration.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# --- Q network (MLP, same init scheme as ppo.init_policy) ---------------

def init_q(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    from ray_tpu.rllib.nets import head, init_trunk
    sizes = (obs_dim, *hidden)
    keys = jax.random.split(rng, len(sizes))
    params = init_trunk(keys, sizes)
    params["w_q"], params["b_q"] = head(
        keys[-1], sizes[-1], n_actions, 0.01)
    return params


def q_forward(params, obs):
    """obs (B, obs_dim) -> q-values (B, A)."""
    from ray_tpu.rllib.nets import trunk_forward
    return trunk_forward(params, obs) @ params["w_q"] + params["b_q"]


# --- replay buffer actor ------------------------------------------------

@ray_tpu.remote
class ReplayBuffer:
    """Uniform ring replay (reference:
    rllib/utils/replay_buffers/replay_buffer.py). Stores flat numpy
    transition arrays; sampling returns a dict of stacked minibatches so
    the learner can scan over them in one jitted call."""

    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 act_shape: tuple = (), act_dtype: str = "int32"):
        # act_shape/act_dtype generalize the buffer to continuous
        # control (SAC stores float torque vectors; DQN int indices)
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        self.actions = np.zeros((capacity, *act_shape),
                                np.dtype(act_dtype))
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.idx = 0
        self.full = False
        self.rng = np.random.default_rng(seed)

    def add(self, batch: Dict[str, np.ndarray]) -> int:
        n = len(batch["actions"])
        ids = (self.idx + np.arange(n)) % self.capacity
        self.obs[ids] = batch["obs"]
        self.next_obs[ids] = batch["next_obs"]
        self.actions[ids] = batch["actions"]
        self.rewards[ids] = batch["rewards"]
        self.dones[ids] = batch["dones"]
        self.idx = int((self.idx + n) % self.capacity)
        self.full = self.full or self.idx < n or self.idx == 0
        return len(self)

    def __len__(self):
        return self.capacity if self.full else self.idx

    def size(self) -> int:
        return len(self)

    def sample(self, batch_size: int, num_batches: int):
        """(num_batches, batch_size, ...) stacked minibatches, or None
        until the buffer holds at least one batch."""
        n = len(self)
        if n < batch_size:
            return None
        ids = self.rng.integers(0, n, size=(num_batches, batch_size))
        return {"obs": self.obs[ids], "next_obs": self.next_obs[ids],
                "actions": self.actions[ids],
                "rewards": self.rewards[ids], "dones": self.dones[ids]}


# --- exploration actor --------------------------------------------------

@ray_tpu.remote
class DQNRunner:
    """Epsilon-greedy transition collector (reference:
    rllib/env/single_agent_env_runner.py under DQN's config)."""

    def __init__(self, env_name: str, num_envs: int, steps_per_call: int,
                 seed: int):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        self.env = make_env(env_name, num_envs, seed)
        self.steps_per_call = steps_per_call
        self.obs = self.env.reset_all()
        self.rng = np.random.default_rng(seed)
        self.ep_ret = np.zeros(num_envs, np.float32)
        from collections import deque
        self.done_returns = deque(maxlen=100)
        self._q = jax.jit(q_forward)

    def sample(self, params, epsilon: float) -> Dict[str, np.ndarray]:
        from ray_tpu.rllib.rollout import collect

        def act(obs):
            q = np.asarray(self._q(params, obs))
            greedy = q.argmax(axis=1)
            rand = self.rng.integers(0, q.shape[1], size=len(greedy))
            explore = self.rng.random(len(greedy)) < epsilon
            return np.where(explore, rand, greedy).astype(np.int32)

        batch, self.obs = collect(self.env, self.obs,
                                  self.steps_per_call, act,
                                  self.ep_ret, self.done_returns)
        return batch


# --- learner ------------------------------------------------------------

@partial(jax.jit, static_argnames=("gamma", "lr"))
def dqn_update(params, target_params, opt_state, batches, *,
               gamma=0.99, lr=1e-3):
    """Double-DQN over a stack of minibatches in one lax.scan."""
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    def loss_fn(p, mb):
        q = q_forward(p, mb["obs"])
        q_sel = jnp.take_along_axis(
            q, mb["actions"][:, None].astype(jnp.int32), axis=1)[:, 0]
        # double-DQN: online net picks the argmax, target net scores it
        next_a = q_forward(p, mb["next_obs"]).argmax(axis=1)
        next_q = jnp.take_along_axis(
            q_forward(target_params, mb["next_obs"]),
            next_a[:, None], axis=1)[:, 0]
        target = mb["rewards"] + gamma * (1.0 - mb["dones"]) \
            * jax.lax.stop_gradient(next_q)
        return jnp.mean((q_sel - target) ** 2)

    def step(carry, mb):
        p, os_ = carry
        l, g = jax.value_and_grad(loss_fn)(p, mb)
        updates, os_ = opt.update(g, os_, p)
        p = optax.apply_updates(p, updates)
        return (p, os_), l

    (params, opt_state), losses = jax.lax.scan(
        step, (params, opt_state), batches)
    return params, opt_state, losses.mean()


@dataclass
class DQNConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    steps_per_call: int = 32          # env steps per runner per iteration
    buffer_capacity: int = 50_000
    learning_starts: int = 500        # min transitions before updates
    batch_size: int = 64
    updates_per_iter: int = 16
    target_sync_every: int = 4        # iterations between target syncs
    gamma: float = 0.99
    lr: float = 1e-3
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_iters: int = 40
    hidden: tuple = (64, 64)
    seed: int = 0
    runner_options: dict = field(default_factory=dict)


class DQN:
    def __init__(self, config: DQNConfig):
        import optax
        self.cfg = config
        env = make_env(config.env, 1, 0)
        self.obs_dim, self.n_actions = env.OBS_DIM, env.N_ACTIONS
        self.params = init_q(jax.random.PRNGKey(config.seed),
                             self.obs_dim, self.n_actions, config.hidden)
        self.target_params = jax.tree.map(lambda x: x, self.params)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.buffer = ReplayBuffer.remote(
            config.buffer_capacity, self.obs_dim, config.seed)
        self.runners = [
            DQNRunner.options(**config.runner_options).remote(
                config.env, config.num_envs_per_runner,
                config.steps_per_call, config.seed + 100 + i)
            for i in range(config.num_env_runners)]
        self._iter = 0

    def epsilon(self) -> float:
        c = self.cfg
        frac = min(1.0, self._iter / max(c.epsilon_decay_iters, 1))
        return c.epsilon_start + frac * (c.epsilon_end - c.epsilon_start)

    def train(self) -> dict:
        """One iteration: parallel exploration -> buffer add -> K jitted
        double-DQN minibatch updates -> (periodic) target sync."""
        import jax.numpy as jnp
        self._iter += 1
        c = self.cfg
        eps = self.epsilon()
        host_params = jax.device_get(self.params)
        batches = ray_tpu.get(
            [r.sample.remote(host_params, eps) for r in self.runners],
            timeout=300)
        ep_rets = [b.pop("episode_returns") for b in batches]
        sizes = ray_tpu.get(
            [self.buffer.add.remote(b) for b in batches], timeout=300)
        loss = float("nan")
        if sizes[-1] >= max(c.learning_starts, c.batch_size):
            mbs = ray_tpu.get(self.buffer.sample.remote(
                c.batch_size, c.updates_per_iter), timeout=300)
            if mbs is not None:
                mbs = {k: jnp.asarray(v) for k, v in mbs.items()}
                self.params, self.opt_state, l = dqn_update(
                    self.params, self.target_params, self.opt_state,
                    mbs, gamma=c.gamma, lr=c.lr)
                loss = float(l)
        if self._iter % c.target_sync_every == 0:
            self.target_params = jax.tree.map(lambda x: x, self.params)
        ep = np.concatenate([e for e in ep_rets if len(e)]) \
            if any(len(e) for e in ep_rets) else np.array([0.0])
        return {"training_iteration": self._iter,
                "episode_reward_mean": float(ep.mean()),
                "loss": loss, "epsilon": eps,
                "buffer_size": int(sizes[-1]),
                "timesteps_this_iter": int(
                    c.num_env_runners * c.num_envs_per_runner
                    * c.steps_per_call)}

    def get_policy_params(self):
        return jax.device_get(self.params)
