"""Vectorized environments (no gym dependency).

The env interface mirrors the reference's EnvRunner expectations
(reference: python/ray/rllib/env/single_agent_env_runner.py): numpy
in/out, batch-first, auto-reset on termination — the shape that keeps
the policy's forward pass one batched matmul per step.
"""

from __future__ import annotations

import numpy as np


class CartPoleVec:
    """Classic cart-pole dynamics, vectorized over `num_envs`.

    Physics per OpenAI's cartpole (public constants); termination at
    |x|>2.4 or |theta|>12deg or 500 steps; reward 1 per step.
    """

    OBS_DIM = 4
    N_ACTIONS = 2
    MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float32)
        self.steps = np.zeros(num_envs, np.int32)
        self.reset_all()

    def reset_all(self) -> np.ndarray:
        self.state = self.rng.uniform(
            -0.05, 0.05, size=(self.num_envs, 4)).astype(np.float32)
        self.steps[:] = 0
        return self.state.copy()

    def step(self, actions: np.ndarray):
        """actions: (n,) in {0,1}. Returns (obs, reward, done) with
        auto-reset: `obs` is the NEXT episode's start where done."""
        g, mc, mp, length = 9.8, 1.0, 0.1, 0.5
        force_mag, tau = 10.0, 0.02
        total_m, pml = mc + mp, mp * length

        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, force_mag, -force_mag)
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pml * th_dot ** 2 * sin) / total_m
        th_acc = (g * sin - cos * temp) / (
            length * (4.0 / 3.0 - mp * cos ** 2 / total_m))
        x_acc = temp - pml * th_acc * cos / total_m
        x = x + tau * x_dot
        x_dot = x_dot + tau * x_acc
        th = th + tau * th_dot
        th_dot = th_dot + tau * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1) \
            .astype(np.float32)
        self.steps += 1

        done = (np.abs(x) > 2.4) | (np.abs(th) > 12 * np.pi / 180) \
            | (self.steps >= self.MAX_STEPS)
        reward = np.ones(self.num_envs, np.float32)
        if done.any():
            idx = np.where(done)[0]
            self.state[idx] = self.rng.uniform(
                -0.05, 0.05, size=(len(idx), 4)).astype(np.float32)
            self.steps[idx] = 0
        return self.state.copy(), reward, done


ENVS = {"CartPole-v1": CartPoleVec}


def make_env(name: str, num_envs: int, seed: int = 0):
    try:
        return ENVS[name](num_envs, seed)
    except KeyError:
        raise ValueError(
            f"unknown env {name!r}; register it in ray_tpu.rllib.env.ENVS")
