"""Vectorized environments (no gym dependency).

The env interface mirrors the reference's EnvRunner expectations
(reference: python/ray/rllib/env/single_agent_env_runner.py): numpy
in/out, batch-first, auto-reset on termination — the shape that keeps
the policy's forward pass one batched matmul per step.
"""

from __future__ import annotations

import numpy as np


class CartPoleVec:
    """Classic cart-pole dynamics, vectorized over `num_envs`.

    Physics per OpenAI's cartpole (public constants); termination at
    |x|>2.4 or |theta|>12deg or 500 steps; reward 1 per step.
    """

    OBS_DIM = 4
    N_ACTIONS = 2
    MAX_STEPS = 500

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.rng = np.random.default_rng(seed)
        self.state = np.zeros((num_envs, 4), np.float32)
        self.steps = np.zeros(num_envs, np.int32)
        self.reset_all()

    def reset_all(self) -> np.ndarray:
        self.state = self.rng.uniform(
            -0.05, 0.05, size=(self.num_envs, 4)).astype(np.float32)
        self.steps[:] = 0
        return self.state.copy()

    def step(self, actions: np.ndarray):
        """actions: (n,) in {0,1}. Returns (obs, reward, done) with
        auto-reset: `obs` is the NEXT episode's start where done."""
        g, mc, mp, length = 9.8, 1.0, 0.1, 0.5
        force_mag, tau = 10.0, 0.02
        total_m, pml = mc + mp, mp * length

        x, x_dot, th, th_dot = self.state.T
        force = np.where(actions == 1, force_mag, -force_mag)
        cos, sin = np.cos(th), np.sin(th)
        temp = (force + pml * th_dot ** 2 * sin) / total_m
        th_acc = (g * sin - cos * temp) / (
            length * (4.0 / 3.0 - mp * cos ** 2 / total_m))
        x_acc = temp - pml * th_acc * cos / total_m
        x = x + tau * x_dot
        x_dot = x_dot + tau * x_acc
        th = th + tau * th_dot
        th_dot = th_dot + tau * th_acc
        self.state = np.stack([x, x_dot, th, th_dot], axis=1) \
            .astype(np.float32)
        self.steps += 1

        # truncated vs terminated matters for TD bootstrapping: a
        # time-limit cut is NOT a real terminal (the value of the next
        # state is not 0) — learners mask bootstrap with
        # done & ~truncated
        self.truncated = self.steps >= self.MAX_STEPS
        done = (np.abs(x) > 2.4) | (np.abs(th) > 12 * np.pi / 180) \
            | self.truncated
        reward = np.ones(self.num_envs, np.float32)
        if done.any():
            idx = np.where(done)[0]
            self.state[idx] = self.rng.uniform(
                -0.05, 0.05, size=(len(idx), 4)).astype(np.float32)
            self.steps[idx] = 0
        return self.state.copy(), reward, done


class PendulumVec:
    """Classic torque-controlled pendulum swing-up, vectorized —
    the continuous-action counterpart of CartPoleVec (dynamics per the
    public Pendulum-v1 spec: obs [cos th, sin th, thdot], action torque
    in [-2, 2], reward -(th^2 + 0.1 thdot^2 + 0.001 a^2), 200-step
    episodes, auto-reset)."""

    OBS_DIM = 3
    ACTION_DIM = 1
    ACTION_HIGH = 2.0
    CONTINUOUS = True
    MAX_STEPS = 200

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self.rng = np.random.default_rng(seed)
        self.th = np.zeros(num_envs, np.float32)
        self.thdot = np.zeros(num_envs, np.float32)
        self.steps = np.zeros(num_envs, np.int32)
        self.reset_all()

    def _obs(self) -> np.ndarray:
        return np.stack([np.cos(self.th), np.sin(self.th), self.thdot],
                        axis=1).astype(np.float32)

    def _reset_idx(self, idx) -> None:
        self.th[idx] = self.rng.uniform(-np.pi, np.pi, size=len(idx))
        self.thdot[idx] = self.rng.uniform(-1.0, 1.0, size=len(idx))
        self.steps[idx] = 0

    def reset_all(self) -> np.ndarray:
        self._reset_idx(np.arange(self.num_envs))
        return self._obs()

    def step(self, actions: np.ndarray):
        """actions: (n, 1) float torque. Returns (obs, reward, done);
        auto-resets at the 200-step horizon (time-limit done)."""
        g, m, length, dt = 10.0, 1.0, 1.0, 0.05
        u = np.clip(np.asarray(actions, np.float32).reshape(-1),
                    -self.ACTION_HIGH, self.ACTION_HIGH)
        th_norm = ((self.th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm ** 2 + 0.1 * self.thdot ** 2 + 0.001 * u ** 2
        self.thdot = np.clip(
            self.thdot + (3 * g / (2 * length) * np.sin(self.th)
                          + 3.0 / (m * length ** 2) * u) * dt,
            -8.0, 8.0).astype(np.float32)
        self.th = (self.th + self.thdot * dt).astype(np.float32)
        self.steps += 1
        done = self.steps >= self.MAX_STEPS
        # every pendulum "done" is a time-limit truncation, never a
        # true terminal — learners must keep bootstrapping through it
        self.truncated = done.copy()
        if done.any():
            self._reset_idx(np.where(done)[0])
        return self._obs(), (-cost).astype(np.float32), done


class MultiCartPoleVec:
    """Two-agent cart-pole: each agent balances its OWN pole, with
    per-agent obs/action/reward/done DICTS — the multi-agent env
    contract (reference: rllib/env/multi_agent_env.py; the reference's
    own MultiAgentCartPole example is likewise N independent poles).
    Vectorized over num_envs per agent."""

    AGENTS = ("agent_0", "agent_1")
    OBS_DIM = 4
    N_ACTIONS = 2

    def __init__(self, num_envs: int, seed: int = 0):
        self.num_envs = num_envs
        self._envs = {a: CartPoleVec(num_envs, seed + 17 * i)
                      for i, a in enumerate(self.AGENTS)}

    @property
    def agents(self):
        return self.AGENTS

    def reset_all(self):
        return {a: e.reset_all() for a, e in self._envs.items()}

    def step(self, actions):
        """actions: {agent: (n,)}. Returns ({agent: obs}, {agent: r},
        {agent: done}) — each agent's envs auto-reset independently."""
        obs, rew, done = {}, {}, {}
        for a, e in self._envs.items():
            obs[a], rew[a], done[a] = e.step(actions[a])
        return obs, rew, done


ENVS = {"CartPole-v1": CartPoleVec, "Pendulum-v1": PendulumVec}
MULTI_AGENT_ENVS = {"MultiCartPole-v0": MultiCartPoleVec}


def make_env(name: str, num_envs: int, seed: int = 0):
    try:
        return ENVS[name](num_envs, seed)
    except KeyError:
        raise ValueError(
            f"unknown env {name!r}; register it in ray_tpu.rllib.env.ENVS")
