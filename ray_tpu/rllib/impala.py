"""IMPALA: asynchronous actor-learner with V-trace off-policy correction.

Reference: python/ray/rllib/algorithms/impala/impala.py (async
EnvRunner sampling pipelined against the learner, importance-weighted
V-trace targets per Espeholt et al. 2018). The TPU-idiomatic shape:

- rollout actors (the same EnvRunner PPO uses) sample with whatever
  params they were LAST handed — the learner never blocks on a full
  round of fragments,
- the learner drains whichever fragments are ready (`ray_tpu.wait`),
  applies one jitted V-trace update per fragment, and immediately
  re-dispatches that runner with fresh weights,
- staleness is therefore bounded by the pipeline depth (one in-flight
  fragment per runner), and the V-trace rho/c clips correct for it —
  the defining IMPALA trade.

The whole V-trace recursion is a `lax.scan` (reverse) inside one jit:
no per-step host work, static shapes (T, N) per fragment.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env
from ray_tpu.rllib.ppo import EnvRunner, init_policy, policy_forward


@partial(jax.jit, static_argnames=("gamma",))
def _vtrace(behavior_logp, target_logp, rewards, dones, values,
            last_value, gamma, rho_bar=1.0, c_bar=1.0):
    """V-trace targets (Espeholt et al. 2018, eqs. 1-2). All inputs
    (T, N); values under the TARGET policy. Returns (vs (T, N),
    pg_advantages (T, N))."""
    import jax.numpy as jnp
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    v_next = jnp.concatenate([values[1:], last_value[None]], axis=0)
    not_done = 1.0 - dones
    deltas = rho * (rewards + gamma * v_next * not_done - values)

    def step(acc, xs):
        delta, c_t, nd = xs
        acc = delta + gamma * c_t * nd * acc
        return acc, acc

    _, corrections = jax.lax.scan(
        step, jnp.zeros_like(last_value),
        (deltas, c, not_done), reverse=True)
    vs = values + corrections
    vs_next = jnp.concatenate([vs[1:], last_value[None]], axis=0)
    pg_adv = rho * (rewards + gamma * vs_next * not_done - values)
    return vs, pg_adv


@partial(jax.jit, static_argnames=("lr", "gamma"))
def impala_update(params, opt_state, batch, *, lr=6e-4, gamma=0.99,
                  vf_coef=0.5, ent_coef=0.01, rho_bar=1.0, c_bar=1.0):
    """One fragment's V-trace update. batch: obs (T, N, D), actions /
    behavior_logp / rewards / dones (T, N), last_obs (N, D)."""
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)
    T, N = batch["actions"].shape
    obs_flat = batch["obs"].reshape(T * N, -1)

    def loss_fn(p):
        logits, values = policy_forward(p, obs_flat)
        logits = logits.reshape(T, N, -1)
        values = values.reshape(T, N)
        logp_all = jax.nn.log_softmax(logits)
        target_logp = jnp.take_along_axis(
            logp_all, batch["actions"][..., None], axis=-1)[..., 0]
        _, last_value = policy_forward(p, batch["last_obs"])
        vs, pg_adv = _vtrace(
            batch["behavior_logp"], target_logp, batch["rewards"],
            batch["dones"], values, last_value, gamma,
            rho_bar=rho_bar, c_bar=c_bar)
        # targets don't backprop into the value baseline
        vs = jax.lax.stop_gradient(vs)
        pg_adv = jax.lax.stop_gradient(pg_adv)
        pi_loss = -(target_logp * pg_adv).mean()
        v_loss = ((values - vs) ** 2).mean()
        probs = jax.nn.softmax(logits)
        entropy = -(probs * jnp.log(probs + 1e-9)).sum(-1).mean()
        total = pi_loss + vf_coef * v_loss - ent_coef * entropy
        return total, (pi_loss, v_loss, entropy,
                       jnp.exp(target_logp - batch["behavior_logp"]))

    (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)
    return params, opt_state, loss, aux[3].mean()


@dataclass
class IMPALAConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_len: int = 64
    lr: float = 6e-4
    gamma: float = 0.99
    vf_coef: float = 0.5
    ent_coef: float = 0.01
    rho_bar: float = 1.0        # V-trace importance clips
    c_bar: float = 1.0
    # fragments consumed per train() call; runners keep sampling
    # regardless (async pipeline)
    fragments_per_iter: int = 2
    hidden: tuple = (64, 64)
    seed: int = 0
    runner_options: dict = field(default_factory=dict)


class IMPALA:
    """Async actor-learner. `train()` consumes whatever fragments are
    ready (never a barrier over all runners) and re-dispatches each
    producer with fresh weights."""

    def __init__(self, config: IMPALAConfig):
        import optax
        self.cfg = config
        env = make_env(config.env, 1, 0)
        self.obs_dim, self.n_actions = env.OBS_DIM, env.N_ACTIONS
        self.params = init_policy(
            jax.random.PRNGKey(config.seed), self.obs_dim,
            self.n_actions, config.hidden)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.runners: List = [
            EnvRunner.options(**config.runner_options).remote(
                config.env, config.num_envs_per_runner,
                config.rollout_len, config.seed + 100 + i)
            for i in range(config.num_env_runners)]
        # ref -> runner index; every runner always has one fragment
        # in flight (sampled with the weights it was last handed)
        self._inflight: Dict = {}
        host_params = jax.device_get(self.params)
        for i, r in enumerate(self.runners):
            self._inflight[r.sample.remote(host_params)] = i
        self._iter = 0
        self._returns = []

    def _apply_update(self, batch):
        """One fragment's learner step — the hook APPO swaps for the
        clipped-surrogate objective (same async pipeline)."""
        return impala_update(
            self.params, self.opt_state, batch,
            lr=self.cfg.lr, gamma=self.cfg.gamma,
            vf_coef=self.cfg.vf_coef, ent_coef=self.cfg.ent_coef,
            rho_bar=self.cfg.rho_bar, c_bar=self.cfg.c_bar)

    def train(self) -> dict:
        import jax.numpy as jnp
        self._iter += 1
        consumed = 0
        losses, rhos = [], []
        while consumed < self.cfg.fragments_per_iter:
            ready, _ = ray_tpu.wait(
                list(self._inflight), num_returns=1, timeout=300)
            if not ready:
                raise TimeoutError("no rollout fragment within 300s")
            for ref in ready:
                idx = self._inflight.pop(ref)
                frag = ray_tpu.get(ref, timeout=60)
                batch = {
                    "obs": jnp.asarray(frag["obs"]),
                    "actions": jnp.asarray(frag["actions"]),
                    "behavior_logp": jnp.asarray(frag["logp"]),
                    "rewards": jnp.asarray(frag["rewards"]),
                    "dones": jnp.asarray(frag["dones"]),
                    # bootstrap from the runner's final observation,
                    # evaluated under the CURRENT params in-update
                    "last_obs": jnp.asarray(frag["last_obs"]),
                }
                self.params, self.opt_state, loss, rho = \
                    self._apply_update(batch)
                losses.append(float(loss))
                rhos.append(float(rho))
                if len(frag["episode_returns"]):
                    self._returns.extend(
                        frag["episode_returns"].tolist())
                    self._returns = self._returns[-100:]
                # re-dispatch the SAME runner with fresh weights —
                # the other runners' in-flight fragments stay stale
                # (V-trace corrects them on arrival)
                host_params = jax.device_get(self.params)
                self._inflight[
                    self.runners[idx].sample.remote(host_params)] = idx
                consumed += 1
        return {
            "training_iteration": self._iter,
            "episode_reward_mean": float(np.mean(self._returns))
            if self._returns else 0.0,
            "loss": float(np.mean(losses)),
            "mean_rho": float(np.mean(rhos)),
            "timesteps_this_iter": consumed
            * self.cfg.num_envs_per_runner * self.cfg.rollout_len,
        }

    def get_policy_params(self):
        return jax.device_get(self.params)

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
