"""Multi-agent training: per-agent dict envs through a shared rollout
collector, one policy per policy id with an agent->policy mapping.

Reference: python/ray/rllib/env/multi_agent_env.py (per-agent
obs/action/reward dicts) + the multi-agent config surface
(policies + policy_mapping_fn on AlgorithmConfig.multi_agent). The
TPU-idiomatic shape: each policy's update stays ONE jitted ppo_update
over (T, N_agents_mapped * num_envs) — agents sharing a policy batch
into the same matmul, they don't loop."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import MULTI_AGENT_ENVS
from ray_tpu.rllib.ppo import (_gae, init_policy, policy_forward,
                               ppo_update)


def make_multi_agent_env(name: str, num_envs: int, seed: int = 0):
    try:
        return MULTI_AGENT_ENVS[name](num_envs, seed)
    except KeyError:
        raise ValueError(
            f"unknown multi-agent env {name!r}; register it in "
            f"ray_tpu.rllib.env.MULTI_AGENT_ENVS")


@ray_tpu.remote
class MultiAgentEnvRunner:
    """Shared rollout collector: ONE env step advances every agent;
    actions come from each agent's mapped policy (reference:
    rllib/env/multi_agent_env_runner.py sample())."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 seed: int, mapping: Dict[str, str]):
        try:
            jax.config.update("jax_platforms", "cpu")  # tiny MLP steps
        except Exception:
            pass
        self.env = make_multi_agent_env(env_name, num_envs, seed)
        self.rollout_len = rollout_len
        self.mapping = mapping
        self.obs = self.env.reset_all()
        self.key = jax.random.PRNGKey(seed)
        self.ep_ret = {a: np.zeros(num_envs, np.float32)
                       for a in self.env.agents}
        self.done_returns = {a: [] for a in self.env.agents}

        @jax.jit
        def act(params, obs, key):
            logits, value = policy_forward(params, obs)
            a = jax.random.categorical(key, logits)
            logp = jax.nn.log_softmax(logits)[
                np.arange(obs.shape[0]), a]
            return a, logp, value
        self._act = act
        self._forward = jax.jit(policy_forward)

    def sample(self, params_by_policy: Dict[str, dict]
               ) -> Dict[str, Dict[str, np.ndarray]]:
        """One fragment per agent: {agent: {obs (T,N,D), actions, logp,
        values, rewards, dones (T,N), last_value, last_obs,
        episode_returns}}."""
        agents = self.env.agents
        out = {a: {k: [] for k in ("obs", "actions", "logp", "values",
                                   "rewards", "dones")}
               for a in agents}
        for _ in range(self.rollout_len):
            actions = {}
            for a in agents:
                self.key, k = jax.random.split(self.key)
                act, logp, v = self._act(
                    params_by_policy[self.mapping[a]], self.obs[a], k)
                actions[a] = np.asarray(act)
                out[a]["obs"].append(self.obs[a])
                out[a]["actions"].append(actions[a])
                out[a]["logp"].append(np.asarray(logp))
                out[a]["values"].append(np.asarray(v))
            obs2, rew, done = self.env.step(actions)
            for a in agents:
                out[a]["rewards"].append(rew[a])
                out[a]["dones"].append(done[a].astype(np.float32))
                self.ep_ret[a] += rew[a]
                if done[a].any():
                    for i in np.where(done[a])[0]:
                        self.done_returns[a].append(
                            float(self.ep_ret[a][i]))
                        self.ep_ret[a][i] = 0.0
                    self.done_returns[a] = self.done_returns[a][-100:]
            self.obs = obs2
        frags = {}
        for a in agents:
            _, last_v = map(np.asarray, self._forward(
                params_by_policy[self.mapping[a]], self.obs[a]))
            frag = {k: np.stack(v) for k, v in out[a].items()}
            frag["last_value"] = last_v
            frag["last_obs"] = np.asarray(self.obs[a])
            frag["episode_returns"] = np.array(
                self.done_returns[a], np.float32)
            frags[a] = frag
        return frags


@dataclass
class MultiAgentPPOConfig:
    env: str = "MultiCartPole-v0"
    num_env_runners: int = 1
    num_envs_per_runner: int = 8
    rollout_len: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip: float = 0.2
    epochs: int = 4
    minibatches: int = 4
    hidden: Tuple[int, ...] = (64, 64)
    seed: int = 0
    # agent id -> policy id; None = one INDEPENDENT policy per agent.
    # Mapping several agents onto one id trains a SHARED policy on
    # their pooled experience (reference: policy_mapping_fn).
    policy_mapping: Optional[Dict[str, str]] = None
    runner_options: dict = field(default_factory=dict)


class MultiAgentPPO:
    """Independent/shared-policy PPO over a multi-agent env."""

    def __init__(self, config: MultiAgentPPOConfig):
        import optax
        self.cfg = config
        env = make_multi_agent_env(config.env, 1, 0)
        self.agents = tuple(env.agents)
        self.mapping = dict(config.policy_mapping or
                            {a: a for a in self.agents})
        missing = [a for a in self.agents if a not in self.mapping]
        if missing:
            raise ValueError(f"policy_mapping lacks agents: {missing}")
        unknown = [a for a in self.mapping if a not in self.agents]
        if unknown:
            raise ValueError(
                f"policy_mapping names unknown agents {unknown}; env "
                f"{config.env!r} has {list(self.agents)}")
        self.policies = tuple(sorted(set(self.mapping.values())))
        self.params: Dict[str, dict] = {}
        self.opt_state: Dict[str, object] = {}
        self._opt = optax.adam(config.lr)
        for i, pid in enumerate(self.policies):
            self.params[pid] = init_policy(
                jax.random.PRNGKey(config.seed + i), env.OBS_DIM,
                env.N_ACTIONS, config.hidden)
            self.opt_state[pid] = self._opt.init(self.params[pid])
        self.key = jax.random.PRNGKey(config.seed + 1)
        self.runners = [
            MultiAgentEnvRunner.options(**config.runner_options).remote(
                config.env, config.num_envs_per_runner,
                config.rollout_len, config.seed + 100 + i, self.mapping)
            for i in range(config.num_env_runners)]
        self._iter = 0

    def train(self) -> dict:
        import jax.numpy as jnp
        self._iter += 1
        host = {pid: jax.device_get(p)
                for pid, p in self.params.items()}
        results = ray_tpu.get(
            [r.sample.remote(host) for r in self.runners], timeout=300)
        rewards = {}
        losses = {}
        for pid in self.policies:
            # pool every fragment of every agent mapped to this policy
            # along the env axis -> ONE (T, N_total) update
            frags = [res[a] for res in results for a in self.agents
                     if self.mapping[a] == pid]
            cat = {k: np.concatenate([f[k] for f in frags], axis=1)
                   for k in ("obs", "actions", "logp", "rewards",
                             "dones", "values")}
            last_v = np.concatenate([f["last_value"] for f in frags])
            advs, rets = _gae(jnp.asarray(cat["rewards"]),
                              jnp.asarray(cat["values"]),
                              jnp.asarray(cat["dones"]),
                              jnp.asarray(last_v),
                              self.cfg.gamma, self.cfg.lam)
            batch = {"obs": jnp.asarray(cat["obs"]),
                     "actions": jnp.asarray(cat["actions"]),
                     "logp": jnp.asarray(cat["logp"]),
                     "advantages": advs, "returns": rets}
            self.key, k = jax.random.split(self.key)
            self.params[pid], self.opt_state[pid], loss = ppo_update(
                self.params[pid], self.opt_state[pid], batch, k,
                lr=self.cfg.lr, clip=self.cfg.clip,
                epochs=self.cfg.epochs,
                minibatches=self.cfg.minibatches)
            losses[pid] = float(loss)
        for a in self.agents:
            ep = np.concatenate(
                [res[a]["episode_returns"] for res in results
                 if len(res[a]["episode_returns"])]) \
                if any(len(res[a]["episode_returns"])
                       for res in results) else np.array([0.0])
            rewards[a] = float(ep.mean())
        return {
            "training_iteration": self._iter,
            "episode_reward_mean": float(np.mean(list(rewards.values()))),
            "agent_reward_mean": rewards,
            "policy_loss": losses,
            "timesteps_this_iter": int(
                self.cfg.num_env_runners * self.cfg.num_envs_per_runner
                * self.cfg.rollout_len * len(self.agents)),
        }

    def get_policy_params(self, policy_id: Optional[str] = None):
        if policy_id is None and len(self.policies) == 1:
            policy_id = self.policies[0]
        return jax.device_get(self.params[policy_id])

    def stop(self):
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
