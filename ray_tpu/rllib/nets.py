"""Shared policy/value network building blocks.

One MLP trunk (He-init hidden layers, tanh activations) reused by every
algorithm head — the minimal analog of the reference's RLModule catalog
(reference: rllib/core/rl_module/ + models/catalog.py deduplicate network
construction the same way).
"""

from __future__ import annotations

import jax
import numpy as np


def init_trunk(keys, sizes) -> dict:
    """Hidden layers w0/b0..wn/bn for sizes=(in, h1, ..., hn)."""
    import jax.numpy as jnp
    params = {}
    for i in range(len(sizes) - 1):
        params[f"w{i}"] = jnp.asarray(
            jax.random.normal(keys[i], (sizes[i], sizes[i + 1]))
            * np.sqrt(2 / sizes[i]), jnp.float32)
        params[f"b{i}"] = jnp.zeros(sizes[i + 1], jnp.float32)
    return params


def trunk_forward(params, obs):
    """obs (B, obs_dim) -> features (B, hidden[-1])."""
    import jax.numpy as jnp
    x = obs
    i = 0
    while f"w{i}" in params:
        x = jnp.tanh(x @ params[f"w{i}"] + params[f"b{i}"])
        i += 1
    return x


def head(key, in_dim: int, out_dim: int, scale: float):
    import jax.numpy as jnp
    return jnp.asarray(jax.random.normal(key, (in_dim, out_dim)) * scale,
                       jnp.float32), jnp.zeros(out_dim, jnp.float32)
