"""PPO on jax/optax over runtime rollout actors.

The minimal algorithm slice of the reference's RL layer (reference:
python/ray/rllib/algorithms/ppo/ppo.py + env_runner_group: N rollout
workers as actors collect batches in parallel, a learner applies
clipped-surrogate updates, weights broadcast each iteration), built
TPU-idiomatically: the policy is a pure-function MLP, GAE and the PPO
epoch loop are jitted (lax.scan over minibatches), and rollout actors
run the same jitted policy on their CPUs.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field
from functools import partial
from typing import Dict

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.env import make_env


# --- pure-jax policy ----------------------------------------------------

def init_policy(rng, obs_dim: int, n_actions: int, hidden=(64, 64)):
    from ray_tpu.rllib.nets import head, init_trunk
    sizes = (obs_dim, *hidden)
    keys = jax.random.split(rng, len(sizes) + 1)
    params = init_trunk(keys, sizes)
    params["w_pi"], params["b_pi"] = head(
        keys[-2], sizes[-1], n_actions, 0.01)
    params["w_v"], params["b_v"] = head(keys[-1], sizes[-1], 1, 1.0)
    return params


def policy_forward(params, obs):
    """obs (B, obs_dim) -> (logits (B, A), value (B,))."""
    from ray_tpu.rllib.nets import trunk_forward
    x = trunk_forward(params, obs)
    logits = x @ params["w_pi"] + params["b_pi"]
    value = (x @ params["w_v"] + params["b_v"])[:, 0]
    return logits, value


# --- rollout actor ------------------------------------------------------

def make_act_fns():
    """CPU-pinned jitted (act, forward) pair shared by every rollout
    collector (single- and multi-agent). Rollout policy steps are tiny
    MLP batches issued one at a time — accelerator dispatch latency
    dominates any compute win, so runners pin to the host CPU (the
    reference's env runners are CPU-placed for the same reason)."""
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:   # backend already initialized in this worker
        pass

    @jax.jit
    def act(params, obs, key):
        logits, value = policy_forward(params, obs)
        a = jax.random.categorical(key, logits)
        logp = jax.nn.log_softmax(logits)[
            np.arange(obs.shape[0]), a]
        return a, logp, value

    return act, jax.jit(policy_forward)


@ray_tpu.remote
class EnvRunner:
    """Collects fixed-length rollout fragments with the current policy
    (reference: rllib/env/single_agent_env_runner.py sample())."""

    def __init__(self, env_name: str, num_envs: int, rollout_len: int,
                 seed: int):
        self.env = make_env(env_name, num_envs, seed)
        self.rollout_len = rollout_len
        self.obs = self.env.reset_all()
        self.key = jax.random.PRNGKey(seed)
        self.ep_ret = np.zeros(num_envs, np.float32)
        self.done_returns = deque(maxlen=100)
        self._act, self._forward = make_act_fns()

    def sample(self, params) -> Dict[str, np.ndarray]:
        T, N = self.rollout_len, self.env.num_envs
        out = {k: [] for k in
               ("obs", "actions", "logp", "rewards", "dones", "values")}
        for _ in range(T):
            self.key, k = jax.random.split(self.key)
            a, logp, v = self._act(params, self.obs, k)
            a = np.asarray(a)
            obs2, r, done = self.env.step(a)
            out["obs"].append(self.obs)
            out["actions"].append(a)
            out["logp"].append(np.asarray(logp))
            out["values"].append(np.asarray(v))
            out["rewards"].append(r)
            out["dones"].append(done.astype(np.float32))
            self.ep_ret += r
            if done.any():
                for i in np.where(done)[0]:
                    self.done_returns.append(float(self.ep_ret[i]))
                    self.ep_ret[i] = 0.0
            self.obs = obs2
        _, last_v = map(np.asarray, self._forward(params, self.obs))
        batch = {k: np.stack(v) for k, v in out.items()}  # (T, N, ...)
        batch["last_value"] = np.asarray(last_v)          # (N,)
        # final observation: off-policy learners (IMPALA) bootstrap
        # from it under the CURRENT params instead of trusting last_value
        batch["last_obs"] = np.asarray(self.obs)          # (N, D)
        batch["episode_returns"] = np.array(
            self.done_returns, np.float32)
        return batch


# --- learner ------------------------------------------------------------

@partial(jax.jit, static_argnames=("gamma", "lam"))
def _gae(rewards, values, dones, last_value, gamma, lam):
    import jax.numpy as jnp

    def step(carry, xs):
        adv = carry
        r, v, v_next, d = xs
        delta = r + gamma * v_next * (1 - d) - v
        adv = delta + gamma * lam * (1 - d) * adv
        return adv, adv

    v_next = jnp.concatenate([values[1:], last_value[None]], axis=0)
    _, advs = jax.lax.scan(
        step, jnp.zeros_like(last_value),
        (rewards, values, v_next, dones), reverse=True)
    return advs, advs + values


@partial(jax.jit, static_argnames=("clip", "epochs",
                                   "minibatches", "lr"))
def ppo_update(params, opt_state, batch, key, *, lr=3e-4, clip=0.2,
               epochs=4, minibatches=4, vf_coef=0.5, ent_coef=0.01):
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    obs = batch["obs"].reshape(-1, batch["obs"].shape[-1])
    acts = batch["actions"].reshape(-1)
    logp_old = batch["logp"].reshape(-1)
    advs = batch["advantages"].reshape(-1)
    rets = batch["returns"].reshape(-1)
    advs = (advs - advs.mean()) / (advs.std() + 1e-8)
    n = obs.shape[0]
    mb = n // minibatches

    def loss_fn(p, idx):
        logits, value = policy_forward(p, obs[idx])
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(idx.shape[0]), acts[idx]]
        ratio = jnp.exp(logp - logp_old[idx])
        unclipped = ratio * advs[idx]
        clipped = jnp.clip(ratio, 1 - clip, 1 + clip) * advs[idx]
        pi_loss = -jnp.minimum(unclipped, clipped).mean()
        v_loss = ((value - rets[idx]) ** 2).mean()
        probs = jax.nn.softmax(logits)
        entropy = -(probs * jnp.log(probs + 1e-9)).sum(-1).mean()
        return pi_loss + vf_coef * v_loss - ent_coef * entropy, \
            (pi_loss, v_loss, entropy)

    def epoch(carry, k):
        p, os_ = carry
        perm = jax.random.permutation(k, n)

        def mini(carry, i):
            p, os_ = carry
            idx = jax.lax.dynamic_slice_in_dim(perm, i * mb, mb)
            (l, aux), g = jax.value_and_grad(
                loss_fn, has_aux=True)(p, idx)
            updates, os_ = opt.update(g, os_, p)
            p = optax.apply_updates(p, updates)
            return (p, os_), l

        (p, os_), losses = jax.lax.scan(
            mini, (p, os_), jnp.arange(minibatches))
        return (p, os_), losses.mean()

    keys = jax.random.split(key, epochs)
    (params, opt_state), losses = jax.lax.scan(
        epoch, (params, opt_state), keys)
    return params, opt_state, losses.mean()


@dataclass
class PPOConfig:
    env: str = "CartPole-v1"
    num_env_runners: int = 2
    num_envs_per_runner: int = 8
    rollout_len: int = 128
    lr: float = 3e-4
    gamma: float = 0.99
    lam: float = 0.95
    clip: float = 0.2
    epochs: int = 4
    minibatches: int = 4
    hidden: tuple = (64, 64)
    seed: int = 0
    runner_options: dict = field(default_factory=dict)


class PPO:
    def __init__(self, config: PPOConfig):
        import optax
        self.cfg = config
        env = make_env(config.env, 1, 0)
        self.obs_dim, self.n_actions = env.OBS_DIM, env.N_ACTIONS
        self.params = init_policy(
            jax.random.PRNGKey(config.seed), self.obs_dim,
            self.n_actions, config.hidden)
        self.opt_state = optax.adam(config.lr).init(self.params)
        self.key = jax.random.PRNGKey(config.seed + 1)
        self.runners = [
            EnvRunner.options(**config.runner_options).remote(
                config.env, config.num_envs_per_runner,
                config.rollout_len, config.seed + 100 + i)
            for i in range(config.num_env_runners)]
        self._iter = 0

    def train(self) -> dict:
        """One iteration: parallel rollouts -> GAE -> PPO epochs."""
        import jax.numpy as jnp
        self._iter += 1
        host_params = jax.device_get(self.params)
        batches = ray_tpu.get(
            [r.sample.remote(host_params) for r in self.runners],
            timeout=300)
        cat = {k: np.concatenate([b[k] for b in batches], axis=1)
               for k in ("obs", "actions", "logp", "rewards", "dones",
                         "values")}
        last_v = np.concatenate([b["last_value"] for b in batches])
        advs, rets = _gae(jnp.asarray(cat["rewards"]),
                          jnp.asarray(cat["values"]),
                          jnp.asarray(cat["dones"]),
                          jnp.asarray(last_v),
                          self.cfg.gamma, self.cfg.lam)
        batch = {"obs": jnp.asarray(cat["obs"]),
                 "actions": jnp.asarray(cat["actions"]),
                 "logp": jnp.asarray(cat["logp"]),
                 "advantages": advs, "returns": rets}
        self.key, k = jax.random.split(self.key)
        self.params, self.opt_state, loss = ppo_update(
            self.params, self.opt_state, batch, k,
            lr=self.cfg.lr, clip=self.cfg.clip, epochs=self.cfg.epochs,
            minibatches=self.cfg.minibatches)
        ep = np.concatenate([b["episode_returns"] for b in batches]) \
            if any(len(b["episode_returns"]) for b in batches) \
            else np.array([0.0])
        return {"training_iteration": self._iter,
                "episode_reward_mean": float(ep.mean()),
                "loss": float(loss),
                "timesteps_this_iter": int(
                    self.cfg.num_env_runners
                    * self.cfg.num_envs_per_runner
                    * self.cfg.rollout_len)}

    def get_policy_params(self):
        return jax.device_get(self.params)
