"""Shared transition collector for off-policy env runners.

One loop used by DQN and SAC runners (reference: the common
EnvRunner._sample machinery under rllib/env/single_agent_env_runner.py)
— action selection is the only per-algorithm piece, passed as a
callback. Stored ``dones`` are TERMINALS ONLY (``done & ~truncated``):
a time-limit truncation is not a real terminal, so the TD target keeps
bootstrapping through it; episode-return accounting uses the raw done.
"""

from __future__ import annotations

from typing import Callable, Dict, Tuple

import numpy as np


def collect(env, obs: np.ndarray, steps: int,
            act: Callable[[np.ndarray], np.ndarray],
            ep_ret: np.ndarray, done_returns
            ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
    """Run `steps` vectorized env steps; returns (batch, next_obs)."""
    out: Dict[str, list] = {k: [] for k in
                            ("obs", "next_obs", "actions", "rewards",
                             "dones")}
    for _ in range(steps):
        a = act(obs)
        obs2, r, done = env.step(a)
        truncated = getattr(env, "truncated", None)
        terminal = done if truncated is None else (done & ~truncated)
        out["obs"].append(obs)
        # env auto-resets on done: obs2 rows where done are the NEXT
        # episode's start; the terminal mask (not raw done) zeroes the
        # bootstrap only where the episode truly ended
        out["next_obs"].append(obs2)
        out["actions"].append(a)
        out["rewards"].append(r)
        out["dones"].append(terminal.astype(np.float32))
        ep_ret += r
        if done.any():
            for i in np.where(done)[0]:
                done_returns.append(float(ep_ret[i]))
                ep_ret[i] = 0.0
        obs = obs2
    batch = {k: np.concatenate(v) for k, v in out.items()}
    batch["episode_returns"] = np.array(done_returns, np.float32)
    return batch, obs
