"""SAC: continuous-action off-policy learning (squashed-Gaussian actor,
twin critics, learned temperature).

The continuous-control column of the reference's algorithm matrix
(reference: python/ray/rllib/algorithms/sac/sac.py +
sac_learner/torch/sac_torch_learner.py — env runners feed a replay
buffer; the learner does twin-Q TD against polyak target critics, a
reparameterized squashed-Gaussian policy update through min(Q1,Q2), and
dual-descent temperature toward a target entropy), built
TPU-idiomatically like dqn.py: the whole K-minibatch update loop —
critic, actor, alpha, AND soft target sync — runs as ONE jitted
``lax.scan`` so the learner does a single dispatch per train iteration.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Dict

import jax
import numpy as np

import ray_tpu
from ray_tpu.rllib.dqn import ReplayBuffer
from ray_tpu.rllib.env import make_env

LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0


# --- networks -----------------------------------------------------------

def init_actor(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    from ray_tpu.rllib.nets import head, init_trunk
    sizes = (obs_dim, *hidden)
    keys = jax.random.split(rng, len(sizes) + 1)
    params = init_trunk(keys[:-1], sizes)
    params["w_mu"], params["b_mu"] = head(keys[-2], sizes[-1], act_dim,
                                          0.01)
    params["w_ls"], params["b_ls"] = head(keys[-1], sizes[-1], act_dim,
                                          0.01)
    return params


def init_critic(rng, obs_dim: int, act_dim: int, hidden=(64, 64)):
    """Twin Q networks under one param tree (q1/q2 prefixes)."""
    from ray_tpu.rllib.nets import head, init_trunk
    sizes = (obs_dim + act_dim, *hidden)
    params = {}
    for name, key in zip(("q1", "q2"), jax.random.split(rng, 2)):
        keys = jax.random.split(key, len(sizes))
        sub = init_trunk(keys, sizes)
        sub["w_out"], sub["b_out"] = head(keys[-1], sizes[-1], 1, 1.0)
        params[name] = sub
    return params


def actor_dist(params, obs):
    """obs (B, O) -> (mu, log_std) of the pre-squash Gaussian."""
    import jax.numpy as jnp

    from ray_tpu.rllib.nets import trunk_forward
    h = trunk_forward(params, obs)
    mu = h @ params["w_mu"] + params["b_mu"]
    log_std = jnp.clip(h @ params["w_ls"] + params["b_ls"],
                       LOG_STD_MIN, LOG_STD_MAX)
    return mu, log_std


def sample_action(params, obs, key, action_high: float):
    """Reparameterized squashed-Gaussian sample -> (action, log_prob)."""
    import jax.numpy as jnp
    mu, log_std = actor_dist(params, obs)
    std = jnp.exp(log_std)
    u = mu + std * jax.random.normal(key, mu.shape)
    a = jnp.tanh(u)
    # log prob with tanh change-of-variables (numerically-stable form)
    logp = (-0.5 * (((u - mu) / std) ** 2 + 2 * log_std
                    + jnp.log(2 * jnp.pi))).sum(-1)
    logp -= (2 * (jnp.log(2.0) - u - jax.nn.softplus(-2 * u))).sum(-1)
    return a * action_high, logp


def q_values(params, obs, act):
    """-> (q1, q2), each (B,)."""
    import jax.numpy as jnp

    from ray_tpu.rllib.nets import trunk_forward
    x = jnp.concatenate([obs, act], axis=-1)
    out = []
    for name in ("q1", "q2"):
        sub = params[name]
        h = trunk_forward(sub, x)
        out.append((h @ sub["w_out"] + sub["b_out"])[:, 0])
    return out[0], out[1]


# --- exploration actor --------------------------------------------------

@ray_tpu.remote
class SACRunner:
    """Stochastic-policy transition collector (exploration comes from
    the squashed-Gaussian itself; before learning starts, uniform
    random torque seeds the buffer — reference: sac.py
    num_steps_sampled_before_learning_starts)."""

    def __init__(self, env_name: str, num_envs: int, steps_per_call: int,
                 seed: int):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
        self.env = make_env(env_name, num_envs, seed)
        self.steps_per_call = steps_per_call
        self.obs = self.env.reset_all()
        self.key = jax.random.PRNGKey(seed)
        self.rng = np.random.default_rng(seed)
        self.ep_ret = np.zeros(num_envs, np.float32)
        from collections import deque
        self.done_returns = deque(maxlen=100)
        self._sample = jax.jit(partial(sample_action,
                                       action_high=self.env.ACTION_HIGH))

    def sample(self, params, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        from ray_tpu.rllib.rollout import collect
        hi = self.env.ACTION_HIGH

        def act(obs):
            if random_actions:
                return self.rng.uniform(
                    -hi, hi, size=(self.env.num_envs,
                                   self.env.ACTION_DIM)
                ).astype(np.float32)
            self.key, sub = jax.random.split(self.key)
            a, _ = self._sample(params, obs, sub)
            return np.asarray(a)

        batch, self.obs = collect(self.env, self.obs,
                                  self.steps_per_call, act,
                                  self.ep_ret, self.done_returns)
        return batch


# --- learner ------------------------------------------------------------

@partial(jax.jit, static_argnames=(
    "gamma", "tau", "lr", "action_high", "target_entropy"))
def sac_update(actor, critic, target_critic, log_alpha, opt_states,
               batches, keys, *, gamma=0.99, tau=0.005, lr=3e-4,
               action_high=1.0, target_entropy=-1.0):
    """One lax.scan over minibatches; each step = critic TD update,
    reparameterized actor update through min(Q1,Q2), temperature
    dual-descent, polyak target sync."""
    import jax.numpy as jnp
    import optax

    opt = optax.adam(lr)

    def critic_loss(c, a, tc, la, mb, key):
        next_a, next_logp = sample_action(a, mb["next_obs"], key,
                                          action_high)
        tq1, tq2 = q_values(tc, mb["next_obs"], next_a)
        alpha = jnp.exp(la)
        backup = mb["rewards"] + gamma * (1.0 - mb["dones"]) * (
            jnp.minimum(tq1, tq2) - alpha * next_logp)
        backup = jax.lax.stop_gradient(backup)
        q1, q2 = q_values(c, mb["obs"], mb["actions"])
        return jnp.mean((q1 - backup) ** 2 + (q2 - backup) ** 2)

    def actor_loss(a, c, la, mb, key):
        act, logp = sample_action(a, mb["obs"], key, action_high)
        q1, q2 = q_values(c, mb["obs"], act)
        return jnp.mean(jnp.exp(la) * logp - jnp.minimum(q1, q2)), logp

    def alpha_loss(la, logp):
        # dual descent: alpha rises while entropy < target
        return -jnp.mean(la * jax.lax.stop_gradient(
            logp + target_entropy))

    def step(carry, inp):
        a, c, tc, la, (os_a, os_c, os_al) = carry
        mb, key = inp
        k1, k2 = jax.random.split(key)
        cl, gc = jax.value_and_grad(critic_loss)(c, a, tc, la, mb, k1)
        up, os_c = opt.update(gc, os_c, c)
        c = optax.apply_updates(c, up)
        (al, logp), ga = jax.value_and_grad(
            actor_loss, has_aux=True)(a, c, la, mb, k2)
        up, os_a = opt.update(ga, os_a, a)
        a = optax.apply_updates(a, up)
        all_, gal = jax.value_and_grad(alpha_loss)(la, logp)
        up, os_al = opt.update(gal, os_al, la)
        la = optax.apply_updates(la, up)
        tc = jax.tree.map(lambda t, o: (1 - tau) * t + tau * o, tc, c)
        return (a, c, tc, la, (os_a, os_c, os_al)), \
            jnp.stack([cl, al, all_])

    (actor, critic, target_critic, log_alpha, opt_states), losses = \
        jax.lax.scan(step,
                     (actor, critic, target_critic, log_alpha,
                      opt_states), (batches, keys))
    return actor, critic, target_critic, log_alpha, opt_states, \
        losses.mean(axis=0)


@dataclass
class SACConfig:
    env: str = "Pendulum-v1"
    num_env_runners: int = 1
    num_envs_per_runner: int = 8
    steps_per_call: int = 64          # env steps per runner per iteration
    buffer_capacity: int = 100_000
    learning_starts: int = 512        # min transitions before updates
    batch_size: int = 128
    updates_per_iter: int = 32
    gamma: float = 0.99
    tau: float = 0.005
    lr: float = 3e-4
    init_alpha: float = 0.1
    target_entropy: float = None      # default: -action_dim
    hidden: tuple = (64, 64)
    seed: int = 0
    runner_options: dict = field(default_factory=dict)


class SAC:
    def __init__(self, config: SACConfig):
        import jax.numpy as jnp
        import optax
        self.cfg = config
        env = make_env(config.env, 1, 0)
        if not getattr(env, "CONTINUOUS", False):
            raise ValueError(
                f"SAC needs a continuous-action env; {config.env!r} "
                "is discrete (use DQN/PPO/IMPALA)")
        self.obs_dim, self.act_dim = env.OBS_DIM, env.ACTION_DIM
        self.action_high = float(env.ACTION_HIGH)
        self.target_entropy = (config.target_entropy
                               if config.target_entropy is not None
                               else -float(self.act_dim))
        k = jax.random.PRNGKey(config.seed)
        ka, kc = jax.random.split(k)
        self.actor = init_actor(ka, self.obs_dim, self.act_dim,
                                config.hidden)
        self.critic = init_critic(kc, self.obs_dim, self.act_dim,
                                  config.hidden)
        self.target_critic = jax.tree.map(lambda x: x, self.critic)
        self.log_alpha = jnp.asarray(np.log(config.init_alpha),
                                     jnp.float32)
        opt = optax.adam(config.lr)
        self.opt_states = (opt.init(self.actor), opt.init(self.critic),
                           opt.init(self.log_alpha))
        self.buffer = ReplayBuffer.remote(
            config.buffer_capacity, self.obs_dim, config.seed,
            act_shape=(self.act_dim,), act_dtype="float32")
        self.runners = [
            SACRunner.options(**config.runner_options).remote(
                config.env, config.num_envs_per_runner,
                config.steps_per_call, config.seed + 100 + i)
            for i in range(config.num_env_runners)]
        self._iter = 0
        self._key = jax.random.PRNGKey(config.seed + 1)

    def train(self) -> dict:
        """One iteration: parallel exploration -> buffer add -> K jitted
        SAC minibatch updates (critic+actor+alpha+polyak in one scan)."""
        import jax.numpy as jnp
        self._iter += 1
        c = self.cfg
        host_actor = jax.device_get(self.actor)
        warmup = (self._iter * c.num_env_runners
                  * c.num_envs_per_runner * c.steps_per_call
                  <= c.learning_starts)
        batches = ray_tpu.get(
            [r.sample.remote(host_actor, warmup) for r in self.runners],
            timeout=300)
        ep_rets = [b.pop("episode_returns") for b in batches]
        sizes = ray_tpu.get(
            [self.buffer.add.remote(b) for b in batches], timeout=300)
        losses = (float("nan"),) * 3
        alpha = float(np.exp(jax.device_get(self.log_alpha)))
        if sizes[-1] >= max(c.learning_starts, c.batch_size):
            mbs = ray_tpu.get(self.buffer.sample.remote(
                c.batch_size, c.updates_per_iter), timeout=300)
            if mbs is not None:
                mbs = {k: jnp.asarray(v) for k, v in mbs.items()}
                self._key, sub = jax.random.split(self._key)
                keys = jax.random.split(sub, c.updates_per_iter)
                (self.actor, self.critic, self.target_critic,
                 self.log_alpha, self.opt_states, ls) = sac_update(
                    self.actor, self.critic, self.target_critic,
                    self.log_alpha, self.opt_states, mbs, keys,
                    gamma=c.gamma, tau=c.tau, lr=c.lr,
                    action_high=self.action_high,
                    target_entropy=self.target_entropy)
                losses = tuple(float(x) for x in ls)
        ep = np.concatenate([e for e in ep_rets if len(e)]) \
            if any(len(e) for e in ep_rets) else np.array([0.0])
        return {"training_iteration": self._iter,
                "episode_reward_mean": float(ep.mean()),
                "critic_loss": losses[0], "actor_loss": losses[1],
                "alpha": alpha, "buffer_size": int(sizes[-1]),
                "timesteps_this_iter": int(
                    c.num_env_runners * c.num_envs_per_runner
                    * c.steps_per_call)}

    def get_policy_params(self):
        return jax.device_get(self.actor)
