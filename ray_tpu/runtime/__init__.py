"""Cluster runtime: control service, node agents, workers, object plane.

The TPU-native re-design of the reference's C++ two-plane runtime
(reference: src/ray/gcs, src/ray/raylet, src/ray/core_worker — see
SURVEY.md §1): a head control service + per-host node agents + worker
processes, built for the TPU regime — few, homogeneous, gang-scheduled
hosts where XLA owns intra-slice communication — rather than for
millions of tiny heterogeneous tasks.
"""

from ray_tpu.runtime.ids import ActorID, NodeID, ObjectID, TaskID
