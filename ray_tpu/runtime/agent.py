"""Node agent: per-host worker pool, lease scheduler, object plane owner.

The raylet analog (reference: src/ray/raylet/node_manager.h, worker_pool.h,
scheduling/cluster_lease_manager.h, local_lease_manager.h,
local_object_manager.h, object_manager/object_manager.h). One agent runs per
host; it spawns worker processes, grants worker leases with
HYBRID/SPREAD/affinity policies (spilling back to peer agents using the
cluster view gossiped via heartbeats), reserves placement-group bundles in
the 2-phase protocol, owns the node's shared-memory object store, and serves
chunked object pulls to peer agents.
"""

from __future__ import annotations

import asyncio
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ray_tpu.config import Config
from ray_tpu.runtime import rpc
from ray_tpu.runtime.ids import (ActorID, NodeID, ObjectID,
                                 PlacementGroupID, WorkerID)
from ray_tpu.runtime.object_store import SharedObjectStore, _attach

IDLE, LEASED, ACTOR, STARTING, DEAD = (
    "idle", "leased", "actor", "starting", "dead")


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    proc: Optional[asyncio.subprocess.Process]
    addr: Optional[Tuple[str, int]] = None
    state: str = STARTING
    actor_id: Optional[ActorID] = None
    actor_resources: Optional[dict] = None
    actor_pg: Optional[tuple] = None           # (pg_id, bundle_index)
    lease_id: Optional[str] = None
    env_hash: str = ""                         # runtime-env pool key
    cgroup: Optional[object] = None            # WorkerCgroup when caged
    ready: asyncio.Event = field(default_factory=asyncio.Event)


@dataclass
class _Lease:
    lease_id: str
    worker: WorkerHandle
    resources: Dict[str, float]
    pg_id: Optional[PlacementGroupID] = None
    bundle_index: Optional[int] = None
    acked: bool = False                      # client confirmed receipt
    granted_at: float = field(default_factory=time.monotonic)
    # Tokens of blocking get()/wait() episodes parked on this lease:
    # pipelined tasks share one lease, so two may block concurrently;
    # resources release on empty->nonempty and re-acquire on
    # nonempty->empty. A SET (not a counter) so that RPC retries of
    # worker_blocked/worker_unblocked are idempotent — the ConnectionPool
    # retries on timeout, and a double-applied counter mutation would
    # leave the node's resources permanently inflated.
    blocked: set = field(default_factory=set)


class NodeAgent:
    def __init__(self, head_addr: Tuple[str, int],
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 config: Optional[Config] = None,
                 session_id: str = "default0",
                 node_id: Optional[NodeID] = None,
                 env_extra: Optional[Dict[str, str]] = None):
        self.config = config or Config.from_env()
        self.env_extra = dict(env_extra or {})
        self.head_addr = tuple(head_addr)
        self.node_id = node_id or NodeID.generate()
        self.session_id = session_id
        self.labels = dict(labels or {})
        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        self.resources_total = dict(resources)
        self.available = dict(resources)
        # pg_id -> bundle_index -> (resources, committed)
        self.bundles: Dict[PlacementGroupID, Dict[int, Tuple[dict, bool]]] = {}
        self.bundle_avail: Dict[Tuple[PlacementGroupID, int], dict] = {}
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self.leases: Dict[str, _Lease] = {}
        # env_hash -> last venv setup failure (surfaced in lease errors)
        self._venv_errors: Dict[str, str] = {}
        self._lease_seq = 0
        self._worker_claims: Dict[str, int] = {}  # env_hash -> claims
        self._wait_queue: List[Tuple[dict, asyncio.Future]] = []
        from ray_tpu.util.events import CategoryBuffer
        # spans pushed by this node's workers (report_events);
        # per-category budgets so a chunk-level collective flood can't
        # evict task exec spans at this aggregation point either
        self._worker_events = CategoryBuffer(
            maxlen=self.config.event_buffer_size)
        self.cluster_view: Dict[NodeID, dict] = {}
        self._view_version = 0
        self._known_cluster_view = -1   # last view version applied
        self._pulls: Dict[ObjectID, asyncio.Future] = {}
        self.store = SharedObjectStore(
            session_id,
            capacity_bytes=self.config.shm_store_bytes,
            spill_dir=self.config.object_spill_dir or None,
            node_uid=self.node_id.hex(),
            head_addr=self.head_addr)
        self.pool = rpc.ConnectionPool()
        self.server = rpc.RpcServer(
            self._handlers(),
            chaos=rpc.ChaosPlan(self.config.testing_rpc_failure))
        self.addr: Optional[Tuple[str, int]] = None
        self._hb_task: Optional[asyncio.Task] = None
        self._stopping = False

    def _handlers(self):
        return {
            "request_lease": self.request_lease,
            "ack_lease": self.ack_lease,
            "release_lease": self.release_lease,
            "worker_blocked": self.worker_blocked,
            "worker_unblocked": self.worker_unblocked,
            "start_actor": self.start_actor,
            "kill_actor_worker": self.kill_actor_worker,
            "prepare_bundle": self.prepare_bundle,
            "commit_bundle": self.commit_bundle,
            "return_bundle": self.return_bundle,
            "worker_ready": self.worker_ready,
            "alloc_object": self.alloc_object,
            "seal_object": self.seal_object,
            "abort_object": self.abort_object,
            "resolve_object": self.resolve_object,
            "fetch_chunk": self.fetch_chunk,
            "free_objects": self.free_objects,
            "node_stats": self.node_stats,
            "node_timeline": self.node_timeline,
            "clock_probe": self.clock_probe,
            "report_events": self.report_events,
            "profile_worker": self.profile_worker,
            "node_forensics": self.node_forensics,
            "ping": self.ping,
        }

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        self.addr = await self.server.start(host, port)
        r = await self.pool.call(
            self.head_addr, "register_node", node_id=self.node_id,
            addr=self.addr, resources_total=self.resources_total,
            labels=self.labels)
        assert r.get("ok"), r
        self._hb_task = asyncio.ensure_future(self._heartbeat_loop())
        from ray_tpu.util import metrics as _m
        self._collector = self._render_metrics

        async def _dash_fetch(method, **kw):
            # dashboard pages proxy control RPCs to the head
            return await self.pool.call(self.head_addr, method,
                                        timeout=10.0, **kw)

        self._dash_fetch = _dash_fetch
        _m.register_collector(self._collector)
        _m.register_state_fetcher(self._dash_fetch)
        if self.config.metrics_port >= 0:
            self.metrics_addr = await _m.acquire_shared_server(
                host, self.config.metrics_port)
            self._metrics_held = True
        for _ in range(self.config.num_workers_prestart):
            asyncio.ensure_future(self._spawn_worker())
        if self.config.memory_monitor_interval_s > 0:
            self._mem_task = asyncio.ensure_future(
                self._memory_monitor_loop())
        if self.config.worker_cgroup_memory_bytes > 0:
            from ray_tpu.runtime.cgroup import detect, sweep_stale
            self._cgroup_version = detect()  # once; spawns reuse it
            sweep_stale(self._cgroup_version)
        return self.addr

    async def stop(self):
        self._stopping = True
        try:
            # archive this node's spans at the head so the cluster
            # timeline survives the node (e.g. a driver session ending);
            # node_timeline turns empty afterwards so a concurrent
            # collect_timeline can't double-count this node
            tl = await self.node_timeline()
            self._events_archived = True
            if tl["events"]:
                try:
                    await self.pool.call(
                        self.head_addr, "report_node_events",
                        events=tl["events"], timeout=5.0)
                except Exception:
                    # head didn't ack (briefly down?): keep serving the
                    # local buffers so the spans aren't silently dropped
                    # from future collect_timeline calls — a possible
                    # applied-but-unacked duplicate beats losing them
                    self._events_archived = False
        except Exception:
            pass
        if self._hb_task:
            self._hb_task.cancel()
        if getattr(self, "_mem_task", None):
            self._mem_task.cancel()
        from ray_tpu.util import metrics as _m
        if getattr(self, "_collector", None) is not None:
            _m.unregister_collector(self._collector)
        if getattr(self, "_dash_fetch", None) is not None:
            _m.unregister_state_fetcher(self._dash_fetch)
        if getattr(self, "_metrics_held", False):
            self._metrics_held = False
            await _m.release_shared_server()
        for w in list(self.workers.values()):
            await self._kill_worker(w)
        caged = [w for w in self.workers.values()
                 if w.cgroup is not None and w.proc is not None]
        if caged:
            # rmdir fails while the dying process is still a member;
            # reap them first (the _reap_worker tasks may be cancelled
            # when the loop closes right after this). One shared bound,
            # not 5s per worker.
            await asyncio.gather(
                *[asyncio.wait_for(w.proc.wait(), 5) for w in caged],
                return_exceptions=True)
            for w in caged:
                w.cgroup.remove()
        await self.server.stop()
        await self.pool.close()
        self.store.shutdown()

    def _render_metrics(self) -> str:
        """Scrape-time node gauges in Prometheus text (reference exports
        the raylet's equivalents via stats/metric_defs.h)."""
        from ray_tpu.util.metrics import _fmt_labels, _labels_key
        nid = self.node_id.hex()[:12]
        out = []

        def g(name, val, **labels):
            labels["node"] = nid
            out.append(f"ray_tpu_{name}"
                       f"{_fmt_labels(_labels_key(labels))} {val:g}")

        for k, v in self.resources_total.items():
            g("node_resource_total", v, resource=k)
        for k, v in self.available.items():
            g("node_resource_available", v, resource=k)
        by_state: Dict[str, int] = {}
        for w in self.workers.values():
            by_state[w.state] = by_state.get(w.state, 0) + 1
        for st, n in by_state.items():
            g("node_workers", n, state=st)
        g("node_lease_queue_depth", len(self._wait_queue))
        st = self.store.stats()
        g("object_store_objects", st["objects"])
        g("object_store_bytes_used", st["used_bytes"])
        g("object_store_bytes_capacity", st["capacity_bytes"])
        return "\n".join(out)

    # --- memory monitor (OOM killer) ------------------------------------
    # Analog of the reference's memory_monitor + worker killing policy
    # (reference: src/ray/common/memory_monitor.h,
    # raylet/worker_killing_policy.cc): sample worker RSS from /proc;
    # enforce an optional per-worker cap, and under node-wide memory
    # pressure kill the largest retriable consumer instead of letting
    # the kernel OOM-killer take down the agent.

    @staticmethod
    def _rss_bytes(pid: int) -> int:
        """Private resident memory: statm resident minus shared pages,
        so zero-copy reads of the shared object store don't count
        against the worker (the reference's killing policy likewise
        excludes shm, memory_monitor.h)."""
        try:
            with open(f"/proc/{pid}/statm") as f:
                parts = f.read().split()
            return (int(parts[1]) - int(parts[2])) * \
                os.sysconf("SC_PAGE_SIZE")
        except (OSError, IndexError, ValueError):
            return 0

    @staticmethod
    def _node_memory_usage() -> float:
        """Usage fraction against the tighter of the host and the
        cgroup limit — inside a memory-limited container the host
        numbers never trip while the cgroup OOM killer would (the
        reference reads cgroup limits first for the same reason)."""
        best = 0.0
        try:
            info = {}
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0]) * 1024
            best = 1.0 - info["MemAvailable"] / info["MemTotal"]
        except (OSError, KeyError, ValueError, ZeroDivisionError):
            pass
        for cur_p, max_p in (
                ("/sys/fs/cgroup/memory.current",
                 "/sys/fs/cgroup/memory.max"),
                ("/sys/fs/cgroup/memory/memory.usage_in_bytes",
                 "/sys/fs/cgroup/memory/memory.limit_in_bytes")):
            try:
                with open(max_p) as f:
                    raw = f.read().strip()
                if raw == "max":
                    continue
                limit = int(raw)
                with open(cur_p) as f:
                    cur = int(f.read().strip())
                if 0 < limit < (1 << 60):
                    best = max(best, cur / limit)
                break
            except (OSError, ValueError, ZeroDivisionError):
                continue
        return best

    async def _memory_monitor_loop(self):
        from ray_tpu.util import events
        while not self._stopping:
            await asyncio.sleep(self.config.memory_monitor_interval_s)
            try:
                victims = []
                cap = self.config.worker_rss_limit_bytes
                live = [(w, self._rss_bytes(w.proc.pid))
                        for w in self.workers.values()
                        if w.proc is not None and w.state != DEAD]
                if cap > 0:
                    victims += [(w, r) for w, r in live if r > cap]
                thr = self.config.memory_usage_threshold
                if not victims and 0 < thr < 1 \
                        and self._node_memory_usage() > thr:
                    # Prefer killing LEASED task workers (retriable)
                    # over actors; largest RSS first.
                    ranked = sorted(
                        (x for x in live if x[0].state in (LEASED, IDLE)),
                        key=lambda x: -x[1]) or sorted(
                        live, key=lambda x: -x[1])
                    if ranked:
                        victims = ranked[:1]
                for w, rss in victims:
                    events.record(
                        "memory", "oom_kill", worker=w.worker_id.hex(),
                        rss=rss, node=self.node_id.hex())
                    await self._kill_worker(w)
            except Exception:
                pass

    async def ping(self):
        return "pong"

    async def profile_worker(self, pid=None, worker_id=None,
                             op: str = "profile", duration_s: float = 2.0,
                             hz: int = 100):
        """Profile one of this node's processes by pid or worker id —
        the head fans a pid-targeted profile_target out here (it only
        knows actors' addresses; agents own the pid -> worker mapping).
        The agent process itself is profilable by its own pid (where a
        stuck lease queue or object pull would show up)."""
        from ray_tpu.util import profiling
        if op not in ("profile", "dump_stacks"):
            # defense in depth with the head's check: op is forwarded
            # as the worker RPC method name
            return {"found": False, "error": f"unknown profile op {op!r}"}
        if pid is not None and int(pid) == os.getpid():
            if op == "dump_stacks":
                return {"found": True, "pid": os.getpid(),
                        "stacks": profiling.dump_stacks()}
            loop = asyncio.get_running_loop()
            res = await loop.run_in_executor(
                None, lambda: profiling.profile(duration_s, hz))
            return {"found": True, "pid": os.getpid(), **res}
        w = None
        for cand in self.workers.values():
            if cand.state == DEAD or cand.addr is None:
                continue
            if pid is not None and cand.proc is not None \
                    and cand.proc.pid == int(pid):
                w = cand
                break
            if worker_id is not None and \
                    cand.worker_id.hex().startswith(str(worker_id)):
                w = cand
                break
        if w is None:
            return {"found": False}
        kw = {} if op == "dump_stacks" else \
            {"duration_s": duration_s, "hz": hz}
        try:
            r = await self.pool.call(w.addr, op,
                                     timeout=float(duration_s) + 30.0,
                                     **kw)
        except Exception as e:  # noqa: BLE001 — report, don't crash
            return {"found": True, "error": f"profile RPC failed: {e}"}
        r["found"] = True
        r["worker_id"] = w.worker_id.hex()
        r["node_id"] = self.node_id.hex()
        return r

    async def node_forensics(self, timeout_s: float = 10.0):
        """The autopsy fan-out's node leg: this agent's own forensics
        dump plus one ``forensics_dump`` pull per live worker process
        on this node (concurrently — one wedged worker must not
        serialize the others). Per-worker failures degrade to error
        rows: on a hung node the absence of an answer is itself
        evidence."""
        from ray_tpu.util import forensics
        out = {"node_id": self.node_id.hex(),
               "agent": forensics.local_dump(), "workers": {}}
        live = [(wid.hex(), w) for wid, w in self.workers.items()
                if w.state != DEAD and w.addr is not None]

        async def pull(wid, w):
            try:
                r = await self.pool.call(w.addr, "forensics_dump",
                                         timeout=float(timeout_s))
            except Exception as e:  # noqa: BLE001 — evidence, not fatal
                r = {"error": f"{type(e).__name__}: {e}",
                     "pid": w.proc.pid if w.proc is not None else None}
            out["workers"][wid] = r

        if live:
            await asyncio.gather(*(pull(wid, w) for wid, w in live))
        return out

    async def node_stats(self):
        return {"node_id": self.node_id,
                "resources_total": self.resources_total,
                "available": self.available,
                "workers": len([w for w in self.workers.values()
                                if w.state != DEAD]),
                "store": self.store.stats()}

    async def report_events(self, events: list) -> dict:
        """Workers push their span buffers here every second and at
        shutdown (worker.py flush_events), so spans survive worker exit
        — the reference's TaskEventBuffer -> GCS push, node-local."""
        self._worker_events.extend(events)
        return {"ok": True, "count": len(events)}

    async def clock_probe(self):
        """This node's wall clock, read inside the RPC handler: the
        head brackets the call with its own clock and estimates the
        per-node offset as remote - midpoint (NTP-style; the probe
        with the smallest RTT wins). collect_timeline ships the
        offsets with the events so to_chrome can de-skew cross-node
        lanes — workers share their node's clock, so node granularity
        covers their spans too."""
        return {"t": time.time()}

    async def node_timeline(self):
        """This node's event/span buffers: the agent's own plus
        everything its workers pushed (util/tracing.py; the control
        service fans out to all agents for the cluster view)."""
        if getattr(self, "_events_archived", False):
            return {"events": []}  # already handed to the head (stop())
        from ray_tpu.util import events
        nid = self.node_id.hex()
        out = [{**e, "node": nid} for e in events.dump()]
        out.extend(self._worker_events.dump())
        return {"events": out}

    # --- heartbeats / cluster view ------------------------------------------

    async def _heartbeat_loop(self):
        period = self.config.health_check_period_s
        while not self._stopping:
            # Local reaping must run even when the head is unreachable —
            # partitions are exactly when orphaned grants/allocations
            # appear.
            try:
                self.store.sweep_unsealed(ttl_s=60.0)
                self._reap_unacked_leases()
            except Exception:
                pass
            try:
                self._view_version += 1
                r = await self.pool.call(
                    self.head_addr, "heartbeat", node_id=self.node_id,
                    resources_available=self.available,
                    version=self._view_version,
                    pending_demand=[req["resources"]
                                    for req, _ in self._wait_queue],
                    known_view=self._known_cluster_view,
                    timeout=10.0)
                if r.get("drained"):
                    # deliberately removed: stop beating — the node is
                    # mid-teardown and must not be resurrected
                    return
                if r.get("unknown"):
                    # Control service restarted (or we were GC'd): rejoin
                    # with the same node id and rebuild what the head lost
                    # — the reference's NotifyGCSRestart flow inverted
                    # (node_manager.proto:457); here the head's "unknown"
                    # reply is the restart signal.
                    await self._rejoin_head()
                elif r.get("view_blob") is not None:
                    # view rides pre-pickled (control caches one blob
                    # per version instead of re-encoding per node)
                    import pickle
                    self.cluster_view = pickle.loads(r["view_blob"])
                    self._known_cluster_view = r.get("view_version", -1)
            except Exception:
                pass
            await asyncio.sleep(period)

    async def _rejoin_head(self):
        # a restarted control has fresh view versions: re-fetch
        self._known_cluster_view = -1
        r = await self.pool.call(
            self.head_addr, "register_node", node_id=self.node_id,
            addr=self.addr, resources_total=self.resources_total,
            labels=self.labels)
        if not r.get("ok"):
            return  # drained across the restart: stay out
        # re-confirm hosted actors (their table rows survived in the
        # persisted store; the addr refresh makes them routable again)
        for w in list(self.workers.values()):
            if w.actor_id is not None:
                try:
                    r = await self.pool.call(
                        self.head_addr, "actor_started",
                        actor_id=w.actor_id, addr=w.addr,
                        node_id=self.node_id)
                    if r.get("dead"):
                        # the table says this actor was killed (the kill
                        # RPC may have been lost): reap the orphan
                        w.actor_id = None
                        await self._kill_worker(w)
                except Exception:
                    pass
        # re-publish the object directory in one bulk RPC
        objs = self.store.sealed_objects()
        if objs:
            try:
                await self.pool.call(self.head_addr, "report_objects",
                                     node_id=self.node_id, objects=objs)
            except Exception:
                pass

    # --- worker pool ---------------------------------------------------------

    async def _resolve_env_packages(self, runtime_env: dict) -> dict:
        """Swap pkg:// working_dir/py_modules uris for locally-extracted
        paths: uncached package zips are fetched from the control KV
        (async, off the spawn path's critical RPCs), extraction runs in
        an executor (reference: runtime env agent downloading packages
        per node before worker start)."""
        from ray_tpu.runtime import runtime_env as rt
        uris = []
        wd = runtime_env.get("working_dir")
        if wd and wd.startswith(rt.PKG_PREFIX):
            uris.append(wd)
        uris += [m for m in runtime_env.get("py_modules") or []
                 if m.startswith(rt.PKG_PREFIX)]
        if not uris:
            return runtime_env
        blobs = {}
        for uri in uris:
            key = rt.PKG_KV_PREFIX + rt.pkg_digest(uri)
            if key not in blobs and not rt.pkg_is_cached(uri):
                # only uncached digests hit the head — spawn churn on a
                # warm node must not re-download multi-MB zips
                blobs[key] = await self.pool.call(
                    self.head_addr, "kv_get", key=key, timeout=60.0)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, rt.resolve_packages, runtime_env, blobs.get)

    def _no_worker_error(self, env_hash: str) -> str:
        """'no worker available' is kept as the transient-retry marker
        (core._lease_err_transient matches on it); a venv setup failure
        for this env is appended so the user can tell a broken
        runtime_env from cluster saturation."""
        ve = self._venv_errors.get(env_hash)
        if ve:
            return f"no worker available (runtime_env setup failed: {ve})"
        return "no worker available"

    async def _spawn_worker(self, runtime_env: Optional[dict] = None,
                            env_hash: str = "") -> Optional[WorkerHandle]:
        from ray_tpu.runtime.runtime_env import apply_to_env, venv_python
        wid = WorkerID.generate()
        orig_runtime_env = runtime_env   # pkg:// form — what children
        if runtime_env:                  # must inherit (local paths are
            try:                         # only valid on THIS node)
                runtime_env = await self._resolve_env_packages(
                    runtime_env)
            except Exception as e:  # noqa: BLE001 — env broken
                from ray_tpu.util import events
                events.record("worker", "pkg_failed", error=str(e))
                self._venv_errors[env_hash] = f"package fetch: {e}"[:500]
                return None
        env = dict(os.environ)
        env.update(self.env_extra)
        env = apply_to_env(runtime_env, env)
        python = sys.executable
        if runtime_env and (runtime_env.get("pip")
                            or runtime_env.get("uv")):
            # cached per-requirements venv; creation (first use only)
            # runs off-loop — it may pip-install for minutes
            loop = asyncio.get_running_loop()
            try:
                python = await loop.run_in_executor(
                    None, venv_python, runtime_env) or sys.executable
            except Exception as e:  # noqa: BLE001 — env broken, not agent
                from ray_tpu.util import events
                events.record("worker", "venv_failed", error=str(e))
                # remembered per env so the lease reply can tell the
                # caller WHY no worker appeared (vs mere saturation)
                self._venv_errors[env_hash] = str(e)[:500]
                return None
        if runtime_env:
            # Nested tasks submitted FROM this worker inherit its env
            # (reference: runtime_env inheritance parent -> child) —
            # in pkg:// form, portable to whatever node runs the child.
            import json as _json
            env["RAY_TPU_RT_ENV"] = _json.dumps(orig_runtime_env)
        env.update({
            "RAY_TPU_AGENT_HOST": self.addr[0],
            "RAY_TPU_AGENT_PORT": str(self.addr[1]),
            "RAY_TPU_HEAD_HOST": self.head_addr[0],
            "RAY_TPU_HEAD_PORT": str(self.head_addr[1]),
            "RAY_TPU_WORKER_ID": wid.hex(),
            "RAY_TPU_NODE_ID": self.node_id.hex(),
            "RAY_TPU_SESSION": self.session_id,
        })
        stdout = stderr = None
        if self.config.log_dir:
            # Worker stdio goes to per-worker files (reference: workers
            # log under the session dir, tailed by log_monitor.py). The
            # fd is handed to the child and closed here after spawn.
            os.makedirs(self.config.log_dir, exist_ok=True)
            logpath = os.path.join(self.config.log_dir,
                                   f"worker-{wid.hex()[:12]}.log")
            stdout = stderr = open(logpath, "ab", buffering=0)
        try:
            proc = await asyncio.create_subprocess_exec(
                python, "-m", "ray_tpu.runtime.worker", env=env,
                stdout=stdout, stderr=stderr)
        finally:
            if stdout is not None:
                stdout.close()
        # env materialized fine: clear any stale setup-failure note so
        # later saturation isn't misreported as a broken runtime_env
        self._venv_errors.pop(env_hash, None)
        w = WorkerHandle(worker_id=wid, proc=proc, env_hash=env_hash)
        if self.config.worker_cgroup_memory_bytes > 0:
            from ray_tpu.runtime.cgroup import WorkerCgroup
            from ray_tpu.util import events
            w.cgroup = WorkerCgroup.create(
                f"{self.session_id[:8]}-{wid.hex()[:12]}",
                self.config.worker_cgroup_memory_bytes,
                getattr(self, "_cgroup_version", None))
            if w.cgroup is None:
                events.record("cgroup", "unavailable", worker=wid.hex())
            elif not w.cgroup.add_pid(proc.pid):
                # worker runs UNCONFINED — surface it, don't hide it
                events.record("cgroup", "attach_failed",
                              worker=wid.hex(), path=w.cgroup.path)
                w.cgroup.remove()
                w.cgroup = None
        self.workers[wid] = w
        asyncio.ensure_future(self._reap_worker(w))
        try:
            await asyncio.wait_for(
                w.ready.wait(), self.config.worker_start_timeout_s)
        except asyncio.TimeoutError:
            await self._kill_worker(w)
            return None
        return w

    async def _reap_worker(self, w: WorkerHandle):
        if w.proc is None:
            return
        await w.proc.wait()
        if w.cgroup is not None:
            w.cgroup.remove()
        dead_actor = w.actor_id
        was = w.state
        w.state = DEAD
        self.workers.pop(w.worker_id, None)
        if w.lease_id:
            await self.release_lease(w.lease_id, worker_died=True)
        if w.actor_resources is not None:
            pg = w.actor_pg or (None, None)
            self._release_res(w.actor_resources, pg[0], pg[1])
            w.actor_resources = None
            self._drain_queue()
        if dead_actor is not None and not self._stopping:
            try:
                await self.pool.call(
                    self.head_addr, "actor_failed", actor_id=dead_actor,
                    reason=f"worker process exited (rc="
                           f"{w.proc.returncode}, state={was})")
            except Exception:
                pass

    async def _kill_worker(self, w: WorkerHandle):
        w.state = DEAD
        if w.proc is not None and w.proc.returncode is None:
            try:
                w.proc.terminate()
            except ProcessLookupError:
                pass

    async def worker_ready(self, worker_id: WorkerID, addr):
        w = self.workers.get(worker_id)
        if w is None:
            return {"ok": False}
        w.addr = tuple(addr)
        if w.state == STARTING:
            w.state = IDLE
        w.ready.set()
        return {"ok": True}

    def _pop_idle(self, env_hash: str = "") -> Optional[WorkerHandle]:
        for w in self.workers.values():
            if w.state == IDLE and w.addr is not None \
                    and w.env_hash == env_hash:
                return w
        return None

    async def _get_worker(self, runtime_env: Optional[dict] = None,
                          env_hash: str = "") -> Optional[WorkerHandle]:
        """Pop an idle worker (from this runtime env's pool), else spawn
        — but claim a worker already mid-boot before spawning an (n+1)th:
        process startup pays a ~2s interpreter+plugin import, and
        concurrent spawns contend on CPU (the reference's worker pool
        likewise prefers its starting workers and keys pools by
        runtime_env_hash, raylet/worker_pool.cc PopWorker)."""
        self._worker_claims[env_hash] = \
            self._worker_claims.get(env_hash, 0) + 1
        try:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + self.config.worker_start_timeout_s
            while True:
                w = self._pop_idle(env_hash)
                if w is not None:
                    return w
                live = [x for x in self.workers.values()
                        if x.state != DEAD]
                if len(live) >= self.config.max_workers_per_node:
                    # Pool saturated: evict an idle worker of ANOTHER
                    # runtime env — otherwise an env-A-full node could
                    # never serve env-B work (the reference's pool kills
                    # idle workers to make room the same way).
                    victim = next(
                        (x for x in live if x.state == IDLE
                         and x.env_hash != env_hash), None)
                    if victim is None:
                        return None
                    await self._kill_worker(victim)
                    continue
                starting = sum(1 for x in live if x.state == STARTING
                               and x.env_hash == env_hash)
                if starting < self._worker_claims.get(env_hash, 0):
                    return await self._spawn_worker(runtime_env, env_hash)
                if loop.time() > deadline:
                    return None
                await asyncio.sleep(0.02)
        finally:
            self._worker_claims[env_hash] -= 1

    # --- leases (task scheduling) --------------------------------------------

    def _avail_for(self, pg_id, bundle_index) -> dict:
        if pg_id is not None:
            key = (pg_id, bundle_index)
            return self.bundle_avail.get(key, {})
        return self.available

    def _try_acquire(self, resources: dict, pg_id, bundle_index) -> bool:
        pool = self._avail_for(pg_id, bundle_index)
        if not _fits(resources, pool):
            return False
        for k, v in resources.items():
            pool[k] = pool.get(k, 0.0) - v
        return True

    def _release_res(self, resources: dict, pg_id, bundle_index):
        pool = self._avail_for(pg_id, bundle_index)
        for k, v in resources.items():
            pool[k] = pool.get(k, 0.0) + v

    _spread_counter = 0

    def _spread_target(self, resources: dict) -> Optional[Tuple[str, int]]:
        """Round-robin over capacity-feasible nodes (self included)."""
        nodes = []
        if _fits(resources, self.resources_total):
            nodes.append((self.node_id.hex(), tuple(self.addr)))
        for nid, info in self.cluster_view.items():
            if nid == self.node_id or not info.get("alive"):
                continue
            if _fits(resources, info.get("total", {})):
                nodes.append((nid.hex(), tuple(info["addr"])))
        if not nodes:
            return None
        nodes.sort()
        NodeAgent._spread_counter += 1
        return nodes[NodeAgent._spread_counter % len(nodes)][1]

    def _capacity_target(self, resources: dict) -> Optional[Tuple[str, int]]:
        for nid, info in self.cluster_view.items():
            if nid == self.node_id or not info.get("alive"):
                continue
            if _fits(resources, info.get("total", {})):
                return tuple(info["addr"])
        return None

    def _spillback_target(self, resources: dict) -> Optional[Tuple[str, int]]:
        """Pick a peer whose AVAILABLE resources fit, preferring the most
        loaded feasible node under HYBRID (pack) or least loaded under
        SPREAD (reference: hybrid_scheduling_policy.h)."""
        cands = []
        for nid, info in self.cluster_view.items():
            if nid == self.node_id or not info.get("alive"):
                continue
            if _fits(resources, info.get("available", {})):
                free = sum(info["available"].values())
                cands.append((free, tuple(info["addr"])))
        if not cands:
            return None
        if self.config.scheduler_policy == "spread":
            return max(cands)[1]
        return min(cands)[1]

    async def request_lease(self, resources: dict, pg_id=None,
                            bundle_index=None, policy: str = "default",
                            allow_spillback: bool = True,
                            timeout: Optional[float] = None,
                            runtime_env: Optional[dict] = None):
        """Grant a worker lease (reference: NodeManager::
        HandleRequestWorkerLease -> ClusterLeaseManager). Reply is one of
        {granted, spillback, error}."""
        resources = dict(resources or {})
        # SPREAD: rotate leases round-robin over all capacity-feasible nodes
        # regardless of local room (reference: SPREAD policy in
        # scheduling/policy/scheduling_options.h).
        if pg_id is None and allow_spillback and policy == "spread":
            target = self._spread_target(resources)
            if target is not None and tuple(target) != tuple(self.addr):
                return {"spillback": target}
        local_ok = self._try_acquire(resources, pg_id, bundle_index)
        if not local_ok:
            if pg_id is None and allow_spillback \
                    and not _fits(resources, self.resources_total):
                # Never feasible here. Prefer a peer with room now; else a
                # peer whose total capacity fits (request queues there).
                # An empty view may just be membership lag (fresh node, or
                # a peer about to join) — poll briefly before declaring the
                # demand infeasible cluster-wide.
                target = (self._spillback_target(resources)
                          or self._capacity_target(resources))
                if target is None:
                    target = await self._await_feasible_peer(resources)
                if target is not None:
                    return {"spillback": target}
                return {"error": f"infeasible resources {resources}"}
            # queue until resources free up locally
            fut = asyncio.get_running_loop().create_future()
            self._wait_queue.append(
                ({"resources": resources, "pg_id": pg_id,
                  "bundle_index": bundle_index}, fut))
            try:
                await asyncio.wait_for(
                    fut, timeout or self.config.lease_timeout_s)
            except asyncio.TimeoutError:
                return {"error": "lease timeout"}
        from ray_tpu.runtime.runtime_env import env_hash as _ehash
        eh = _ehash(runtime_env)
        w = await self._get_worker(runtime_env, eh)
        if w is None:
            self._release_res(resources, pg_id, bundle_index)
            self._drain_queue()
            return {"error": self._no_worker_error(eh)}
        self._lease_seq += 1
        lease_id = f"{self.node_id.hex()[:8]}:{self._lease_seq}"
        w.state = LEASED
        w.lease_id = lease_id
        self.leases[lease_id] = _Lease(
            lease_id=lease_id, worker=w, resources=resources,
            pg_id=pg_id, bundle_index=bundle_index)
        return {"granted": {"lease_id": lease_id, "worker_addr": w.addr,
                            "worker_id": w.worker_id}}

    async def _await_feasible_peer(self, resources: dict,
                                   window_s: Optional[float] = None):
        """Poll the synced cluster view for a capacity-feasible peer; the
        view refreshes via heartbeat piggyback, so a fresh node sees peers
        within one heartbeat period. While polling, the shape rides the
        heartbeat's pending_demand so an autoscaler can see demand no
        current node can fit and launch capacity into the window."""
        entry = ({"resources": resources}, None)
        self._wait_queue.append(entry)
        try:
            if window_s is None:
                window_s = self.config.infeasible_wait_window_s
            deadline = asyncio.get_running_loop().time() + min(
                window_s, self.config.lease_timeout_s)
            while asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.2)
                target = (self._spillback_target(resources)
                          or self._capacity_target(resources))
                if target is not None:
                    return target
            return None
        finally:
            try:
                self._wait_queue.remove(entry)
            except ValueError:
                pass

    async def ack_lease(self, lease_id: str):
        """Client confirms it received the grant. Un-acked leases are
        reaped: if the grant REPLY is lost in transit (connection drop,
        injected chaos), the client retries and takes a fresh lease — the
        orphaned grant would otherwise pin its resources forever
        (reference: raylet reclaims leases when the owning client
        disconnects; the RPC plane here has no per-client connection
        identity, so an explicit ack carries the same information)."""
        lease = self.leases.get(lease_id)
        if lease is None:
            return {"ok": False}  # already reaped — caller re-leases
        lease.acked = True
        return {"ok": True}

    def _reap_unacked_leases(self, grace_s: float = 60.0):
        """grace_s must exceed the client's worst-case ack envelope
        (5s timeout x 5 transport retries + backoff ~= 30s) so only
        truly orphaned grants are reaped. Reaped workers are KILLED,
        not returned to the pool: the client may believe it owns the
        lease and be mid-dispatch — termination is the fence."""
        now = time.monotonic()
        stale = [l for l in self.leases.values()
                 if not l.acked and now - l.granted_at > grace_s]
        for l in stale:
            async def _fence(lease=l):
                await self.release_lease(lease.lease_id,
                                         worker_died=True)
                await self._kill_worker(lease.worker)
            asyncio.ensure_future(_fence())

    async def release_lease(self, lease_id: str, worker_died: bool = False):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return {"ok": False}
        if not lease.blocked:  # blocked leases already gave back resources
            self._release_res(lease.resources, lease.pg_id,
                              lease.bundle_index)
        w = lease.worker
        if not worker_died and w.state == LEASED:
            w.state = IDLE
            w.lease_id = None
        self._drain_queue()
        return {"ok": True}

    async def worker_blocked(self, worker_id: WorkerID, token: str = ""):
        """The worker is parked in a blocking get()/wait() inside its
        task: release the lease's resources so the tasks it is waiting ON
        can take leases here — without this, a parent task on a saturated
        node deadlocks against its own children (the reference releases a
        blocked worker's CPU the same way, raylet/node_manager.cc
        HandleWorkerBlocked). `token` identifies one blocking episode so
        that RPC-level retries are idempotent (re-adding a present token
        is a no-op)."""
        for lease in self.leases.values():
            if lease.worker.worker_id == worker_id:
                if token in lease.blocked:  # retried RPC — already applied
                    return {"ok": True}
                was_empty = not lease.blocked
                lease.blocked.add(token)
                if was_empty:
                    self._release_res(lease.resources, lease.pg_id,
                                      lease.bundle_index)
                    self._drain_queue()
                return {"ok": True}
        return {"ok": False}

    async def worker_unblocked(self, worker_id: WorkerID, token: str = ""):
        for lease in self.leases.values():
            if lease.worker.worker_id == worker_id \
                    and token in lease.blocked:
                lease.blocked.discard(token)
                if not lease.blocked and not self._try_acquire(
                        lease.resources, lease.pg_id, lease.bundle_index):
                    # the freed capacity went to children while we were
                    # blocked: run temporarily oversubscribed (available
                    # goes negative) rather than deadlock on re-acquire —
                    # it self-corrects as leases release
                    pool = self._avail_for(lease.pg_id, lease.bundle_index)
                    for k, v in lease.resources.items():
                        pool[k] = pool.get(k, 0.0) - v
                return {"ok": True}
        # Unknown token: either the matching worker_blocked never applied
        # (request lost before reaching us) or the lease already released.
        # Both are safe no-ops — callers send unblock unconditionally after
        # an *attempted* block precisely so an applied-but-unacked block
        # can't leak.
        return {"ok": False}

    def _drain_queue(self):
        still = []
        for req, fut in self._wait_queue:
            if fut is None:  # demand marker (feasibility poll), not a waiter
                still.append((req, fut))
                continue
            if fut.done():
                continue
            if self._try_acquire(req["resources"], req["pg_id"],
                                 req["bundle_index"]):
                fut.set_result(True)
            else:
                still.append((req, fut))
        self._wait_queue = still

    # --- actors ---------------------------------------------------------------

    async def start_actor(self, actor_id: ActorID, creation_spec: bytes,
                          resources: dict,
                          runtime_env: Optional[dict] = None):
        resources = dict(resources or {})
        pg_id = None
        bundle_index = None
        # placement-group constraint rides inside resources as pseudo-keys
        if "_pg" in resources:
            pg_id = resources.pop("_pg")
            bundle_index = resources.pop("_pg_bundle", None)
        if not self._try_acquire(resources, pg_id, bundle_index):
            # queue until capacity frees (the reference keeps actor creation
            # pending in the GCS scheduler; here we park on the agent)
            fut = asyncio.get_running_loop().create_future()
            self._wait_queue.append(
                ({"resources": resources, "pg_id": pg_id,
                  "bundle_index": bundle_index}, fut))
            try:
                await asyncio.wait_for(fut, self.config.lease_timeout_s)
            except asyncio.TimeoutError:
                return {"ok": False,
                        "error": f"insufficient resources for actor "
                                 f"{resources} (timed out queued)"}
        from ray_tpu.runtime.runtime_env import env_hash as _ehash
        eh = _ehash(runtime_env)
        w = await self._get_worker(runtime_env, eh)
        if w is None:
            self._release_res(resources, pg_id, bundle_index)
            return {"ok": False, "error": self._no_worker_error(eh)}
        w.state = ACTOR
        w.actor_id = actor_id
        w.actor_resources = dict(resources)
        w.actor_pg = (pg_id, bundle_index) if pg_id is not None else None
        try:
            r = await self.pool.call(
                w.addr, "host_actor", actor_id=actor_id,
                creation_spec=creation_spec,
                timeout=self.config.actor_init_timeout_s)
            if not r.get("ok"):
                raise RuntimeError(r.get("error", "host_actor failed"))
        except Exception as e:  # noqa: BLE001
            self._release_res(resources, pg_id, bundle_index)
            await self._kill_worker(w)
            return {"ok": False, "error": f"{e}"}
        await self.pool.call(
            self.head_addr, "actor_started", actor_id=actor_id,
            addr=w.addr, node_id=self.node_id)
        return {"ok": True, "addr": w.addr}

    async def kill_actor_worker(self, actor_id: ActorID):
        for w in list(self.workers.values()):
            if w.actor_id == actor_id:
                w.actor_id = None  # suppress actor_failed report
                await self._kill_worker(w)  # _reap_worker frees resources
                return {"ok": True}
        return {"ok": False}

    # --- placement group bundles ----------------------------------------------

    async def prepare_bundle(self, pg_id: PlacementGroupID, bundle_index: int,
                             resources: dict):
        resources = dict(resources)
        if not self._try_acquire(resources, None, None):
            return {"ok": False, "error": "insufficient resources"}
        self.bundles.setdefault(pg_id, {})[bundle_index] = (resources, False)
        return {"ok": True}

    async def commit_bundle(self, pg_id: PlacementGroupID, bundle_index: int):
        ent = self.bundles.get(pg_id, {}).get(bundle_index)
        if ent is None:
            return {"ok": False}
        resources, _ = ent
        self.bundles[pg_id][bundle_index] = (resources, True)
        self.bundle_avail[(pg_id, bundle_index)] = dict(resources)
        return {"ok": True}

    async def return_bundle(self, pg_id: PlacementGroupID, bundle_index: int):
        ent = self.bundles.get(pg_id, {}).pop(bundle_index, None)
        if ent is None:
            return {"ok": False}
        resources, _ = ent
        self.bundle_avail.pop((pg_id, bundle_index), None)
        self._release_res(resources, None, None)
        self._drain_queue()
        return {"ok": True}

    # --- object plane -----------------------------------------------------------

    async def alloc_object(self, oid: ObjectID, size: int):
        """Reserve store space for a local producer; it writes the frame
        into (segname, offset) then calls seal_object (plasma's
        Create/Seal split, reference: plasma/store.h)."""
        segname, offset = self.store.allocate(oid, size)
        return {"segname": segname, "offset": offset}

    async def seal_object(self, oid: ObjectID):
        self.store.seal(oid)
        size = self.store.size_of(oid)
        await self.pool.call(self.head_addr, "add_object_location",
                             oid=oid, node_id=self.node_id, size=size)
        return {"ok": True}

    async def abort_object(self, oid: ObjectID):
        self.store.abort(oid)
        return {"ok": True}

    async def resolve_object(self, oid: ObjectID, pull: bool = True):
        """Local (segname, offset) for oid, pulling from a remote node if
        needed (reference: PullManager + ObjectManager chunked transfer)."""
        loc = self.store.location(oid)
        if loc is not None:
            return {"segname": loc[0], "offset": loc[1], "size": loc[2]}
        if not pull:
            return {"segname": None}
        # Dedup concurrent pulls of the same object (reference:
        # pull_manager.h tracks active pulls per object).
        inflight = self._pulls.get(oid)
        if inflight is None:
            inflight = asyncio.ensure_future(self._pull_from_any(oid))
            self._pulls[oid] = inflight
            inflight.add_done_callback(
                lambda _f: self._pulls.pop(oid, None))
        ok = await asyncio.shield(inflight)
        if not ok:
            return {"segname": None}
        loc = self.store.location(oid)
        if loc is None:
            return {"segname": None}
        return {"segname": loc[0], "offset": loc[1], "size": loc[2]}

    async def _pull_from_any(self, oid: ObjectID) -> bool:
        locs = await self.pool.call(self.head_addr, "get_object_locations",
                                    oid=oid)
        for loc in locs:
            if loc["node_id"] == self.node_id:
                continue
            try:
                await self._pull(oid, tuple(loc["addr"]), loc["size"])
                return True
            except Exception:
                continue
        return False

    async def _pull(self, oid: ObjectID, addr: Tuple[str, int], size: int):
        chunk = self.config.object_transfer_chunk_bytes
        mv = self.store.create(oid, size)
        try:
            off = 0
            while off < size:
                n = min(chunk, size - off)
                data = await self.pool.call(
                    addr, "fetch_chunk", oid=oid, offset=off, size=n)
                if data is None:
                    raise IOError(f"chunk fetch failed for {oid}")
                mv[off:off + len(data)] = data
                off += len(data)
        except Exception:
            self.store.delete(oid)
            raise
        self.store.seal(oid)
        await self.pool.call(self.head_addr, "add_object_location",
                             oid=oid, node_id=self.node_id, size=size)

    async def fetch_chunk(self, oid: ObjectID, offset: int, size: int):
        mv = self.store.get(oid)
        if mv is None:
            return None
        return bytes(mv[offset:offset + size])

    async def free_objects(self, oids: List[ObjectID]):
        for oid in oids:
            self.store.delete(oid)
            try:
                await self.pool.call(self.head_addr, "remove_object_location",
                                     oid=oid, node_id=self.node_id)
            except Exception:
                pass
        return {"ok": True}


def _fits(demand: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())
