"""Worker cgroup isolation: kernel-enforced per-worker memory limits.

Analog of the reference's cgroup setup for workers (reference:
src/ray/common/cgroup2/* — cgroup manager the raylet uses to cage
worker processes): each spawned worker lands in its own cgroup with
`memory.max` (v2) / `memory.limit_in_bytes` (v1) set, so a runaway
worker is OOM-killed by the KERNEL at its own cap instead of dragging
the node to the global OOM killer. Complements the userspace memory
monitor in agent.py (which acts on softer thresholds and can choose
victims by policy).

Everything degrades gracefully: no root / no controller -> no cgroups,
workers run unconfined (a one-line event records that).
"""

from __future__ import annotations

import os
from typing import Optional

_V2_ROOT = "/sys/fs/cgroup"
_V1_MEM_ROOT = "/sys/fs/cgroup/memory"


def _write(path: str, value: str) -> None:
    with open(path, "w") as f:
        f.write(value)


def detect() -> Optional[str]:
    """'v2', 'v1', or None if memory limits can't be enforced here.
    Probe dirs are per-pid so concurrent agents can't race each other
    into a false negative."""
    probe_name = f".raytpu-probe-{os.getpid()}"
    try:
        ctrl = os.path.join(_V2_ROOT, "cgroup.controllers")
        if os.path.exists(ctrl):
            with open(ctrl) as f:
                has_mem = "memory" in f.read().split()
            if has_mem:
                probe = os.path.join(_V2_ROOT, probe_name)
                os.makedirs(probe, exist_ok=True)
                try:
                    # memory.max only exists in the child when the
                    # controller is enabled via subtree_control —
                    # cgroup.controllers alone doesn't prove that.
                    if os.path.exists(os.path.join(probe, "memory.max")):
                        return "v2"
                finally:
                    os.rmdir(probe)
        if os.path.isdir(_V1_MEM_ROOT):
            probe = os.path.join(_V1_MEM_ROOT, probe_name)
            os.makedirs(probe, exist_ok=True)
            os.rmdir(probe)
            return "v1"
    except OSError:
        pass
    return None


class WorkerCgroup:
    """One cgroup confining one worker process."""

    def __init__(self, path: str, version: str):
        self.path = path
        self.version = version

    @classmethod
    def create(cls, name: str, memory_bytes: int,
               version: Optional[str] = None) -> Optional["WorkerCgroup"]:
        version = version or detect()
        if version is None or memory_bytes <= 0:
            return None
        try:
            if version == "v2":
                path = os.path.join(_V2_ROOT, f"raytpu-{name}")
                os.makedirs(path, exist_ok=True)
                _write(os.path.join(path, "memory.max"),
                       str(memory_bytes))
            else:
                path = os.path.join(_V1_MEM_ROOT, f"raytpu-{name}")
                os.makedirs(path, exist_ok=True)
                _write(os.path.join(path, "memory.limit_in_bytes"),
                       str(memory_bytes))
                # no swap escape hatch where the knob exists
                try:
                    _write(os.path.join(
                        path, "memory.memsw.limit_in_bytes"),
                        str(memory_bytes))
                except OSError:
                    pass
            return cls(path, version)
        except OSError:
            return None

    def add_pid(self, pid: int) -> bool:
        try:
            _write(os.path.join(self.path, "cgroup.procs"), str(pid))
            return True
        except OSError:
            return False

    def remove(self) -> None:
        """Best-effort teardown (the worker must already be dead — a
        cgroup with live members can't be removed)."""
        try:
            os.rmdir(self.path)
        except OSError:
            pass


def sweep_stale(version: Optional[str] = None) -> int:
    """Remove empty leftover raytpu-* cgroups (agents that stopped
    before their reap tasks ran leave them behind). Only empty groups
    can be rmdir'd, so this can never touch a live worker."""
    version = version or detect()
    if version is None:
        return 0
    root = _V2_ROOT if version == "v2" else _V1_MEM_ROOT
    n = 0
    try:
        names = os.listdir(root)
    except OSError:
        return 0
    import time
    for name in names:
        if not name.startswith("raytpu-"):
            continue
        full = os.path.join(root, name)
        try:
            # Age gate: a concurrent agent may be between create() and
            # add_pid() — only reap dirs old enough to be true leftovers.
            if time.time() - os.stat(full).st_mtime < 60:
                continue
            os.rmdir(full)
            n += 1
        except OSError:
            pass  # still has members or already gone
    return n
