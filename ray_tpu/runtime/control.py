"""Control service: cluster-global state on the head node.

The GCS analog (reference: src/ray/gcs/gcs_server.h, gcs_node_manager.h,
gcs/actor/gcs_actor_manager.h, gcs_placement_group_manager.h,
gcs_kv_manager.h, gcs_health_check_manager.h, pubsub/publisher.h). Holds:
node membership + health, the actor directory (with restart FSM), the
object-location directory, a KV store, the job table, placement groups
(2-phase reserve across agents), and a long-poll pubsub used to broadcast
node/actor events.

Storage is in-memory (the reference's default; its Redis persistence is a
pluggable StoreClient — same seam exists here via `self._tables`).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.config import Config
from ray_tpu.runtime import rpc
from ray_tpu.runtime.ids import (ActorID, JobID, NodeID, ObjectID,
                                 PlacementGroupID)

# Actor lifecycle states (reference: gcs/actor/gcs_actor_manager.h FSM).
PENDING, ALIVE, RESTARTING, DEAD = "PENDING", "ALIVE", "RESTARTING", "DEAD"


@dataclass
class NodeInfo:
    node_id: NodeID
    addr: Tuple[str, int]              # agent RPC address
    resources_total: Dict[str, float]
    resources_available: Dict[str, float]
    labels: Dict[str, str] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    version: int = 0                   # resource-view version (syncer)
    pending_demand: List[dict] = field(default_factory=list)
    drained: bool = False              # deliberate removal: never resurrect


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: Optional[str]
    state: str = PENDING
    addr: Optional[Tuple[str, int]] = None     # hosting worker RPC addr
    node_id: Optional[NodeID] = None
    max_restarts: int = 0
    num_restarts: int = 0
    class_name: str = ""
    resources: Dict[str, float] = field(default_factory=dict)
    creation_spec: Optional[bytes] = None      # re-spawn payload for restart
    death_cause: Optional[str] = None
    namespace: str = "default"
    pg: Optional[tuple] = None                 # (pg_id, bundle_index)
    max_concurrency: int = 1                   # callers batch iff == 1
    runtime_env: Optional[dict] = None


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str
    state: str = "PENDING"             # PENDING | CREATED | REMOVED
    bundle_nodes: List[Optional[NodeID]] = field(default_factory=list)
    name: Optional[str] = None


class Pubsub:
    """Per-channel event logs consumed by long-poll (reference:
    pubsub/publisher.h long-poll protocol)."""

    def __init__(self, maxlen: int = 65536):
        self._events: Dict[str, List[Tuple[int, Any]]] = {}
        self._next: Dict[str, int] = {}
        self._cond = asyncio.Condition()
        self._maxlen = maxlen

    async def publish(self, channel: str, event: Any) -> None:
        async with self._cond:
            seq = self._next.get(channel, 0)
            self._next[channel] = seq + 1
            log = self._events.setdefault(channel, [])
            log.append((seq, event))
            if len(log) > self._maxlen:
                del log[: len(log) // 2]
            self._cond.notify_all()

    async def poll(self, channel: str, cursor: int,
                   timeout: float = 30.0) -> Tuple[int, List[Any]]:
        deadline = time.monotonic() + timeout
        async with self._cond:
            while True:
                log = self._events.get(channel, [])
                fresh = [e for seq, e in log if seq >= cursor]
                if fresh:
                    return self._next.get(channel, 0), fresh
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return self._next.get(channel, 0), []
                try:
                    await asyncio.wait_for(self._cond.wait(), remaining)
                except asyncio.TimeoutError:
                    return self._next.get(channel, 0), []


class ControlService:
    def __init__(self, config: Optional[Config] = None,
                 persist_dir: Optional[str] = None):
        self.config = config or Config.from_env()
        # Durable tables (GCS-persistence analog, see runtime/persistence.py):
        # set RAY_TPU_CONTROL_PERSIST_DIR or pass persist_dir to survive
        # control-service restarts; nodes reconnect via heartbeats.
        self._store = None
        persist_dir = persist_dir or self.config.control_persist_dir
        if persist_dir:
            from ray_tpu.runtime.persistence import FileStore
            self._store = FileStore(persist_dir)
        self._recover_deadline = 0.0
        self._drained: set = set()         # node ids removed for good
        from ray_tpu.util.events import CategoryBuffer
        # span buffers archived by departing nodes (collect_timeline);
        # per-category budgets, same rule as the node-local buffers
        self._archived_events = CategoryBuffer(
            maxlen=self.config.event_buffer_size)
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}
        self.kv: Dict[str, bytes] = {}
        self.jobs: Dict[JobID, dict] = {}
        self.submitted_jobs: Dict[str, dict] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        # object directory: oid -> {node_id: size}
        self.object_locations: Dict[ObjectID, Dict[NodeID, int]] = {}
        self.pubsub = Pubsub()
        # Epoch-seeded so a restarted control never hands out a version
        # an old incarnation already used (agents gate view refresh on
        # equality; rejoin also resets, this is belt-and-braces).
        self._view_version = int(time.time() * 1000) << 8
        self._view_blob_cache = (0, 0.0, None)   # (version, built_at, blob)
        self.pool = rpc.ConnectionPool()
        self.server = rpc.RpcServer(
            self._handlers(),
            chaos=rpc.ChaosPlan(self.config.testing_rpc_failure))
        self.addr: Optional[Tuple[str, int]] = None
        self._health_task: Optional[asyncio.Task] = None

    def _handlers(self):
        return {
            "register_node": self.register_node,
            "heartbeat": self.heartbeat,
            "drain_node": self.drain_node,
            "get_nodes": self.get_nodes,
            "kv_put": self.kv_put, "kv_get": self.kv_get,
            "kv_del": self.kv_del, "kv_keys": self.kv_keys,
            "register_actor": self.register_actor,
            "actor_started": self.actor_started,
            "actor_failed": self.actor_failed,
            "kill_actor": self.kill_actor,
            "get_actor": self.get_actor,
            "wait_actor_alive": self.wait_actor_alive,
            "get_named_actor": self.get_named_actor,
            "list_actors": self.list_actors,
            "register_job": self.register_job,
            "finish_job": self.finish_job,
            "list_jobs": self.list_jobs,
            "submit_job": self.submit_job,
            "get_submitted_job": self.get_submitted_job,
            "list_submitted_jobs": self.list_submitted_jobs,
            "stop_submitted_job": self.stop_submitted_job,
            "submitted_job_logs": self.submitted_job_logs,
            "create_pg": self.create_pg,
            "remove_pg": self.remove_pg,
            "get_pg": self.get_pg,
            "list_pgs": self.list_pgs,
            "add_object_location": self.add_object_location,
            "report_objects": self.report_objects,
            "collect_timeline": self.collect_timeline,
            "report_node_events": self.report_node_events,
            "remove_object_location": self.remove_object_location,
            "get_object_locations": self.get_object_locations,
            "poll_events": self.poll_events,
            "cluster_view": self.cluster_view,
            "report_metrics": self.report_metrics,
            "profile_target": self.profile_target,
            "autopsy": self.autopsy,
            "health_state": self.health_state,
            "query_series": self.query_series,
            "ping": self.ping,
        }

    # --- persistence --------------------------------------------------------

    def _persist(self, table: str, key, value) -> None:
        if self._store is not None:
            self._store.put(table, key, value)
            self._maybe_compact(table)

    def _persist_del(self, table: str, key) -> None:
        if self._store is not None:
            self._store.delete(table, key)
            self._maybe_compact(table)

    def _live_table(self, table: str):
        """The authoritative in-memory state for a persisted table, used
        to rewrite its log during online compaction."""
        if table == "kv":
            return self.kv
        if table == "actors":
            return self.actors
        if table == "jobs":
            return self.jobs
        if table == "submitted_jobs":
            return self.submitted_jobs
        if table == "pgs":
            # REMOVED pgs stay in self.pgs for status queries but have a
            # "del" record in the log — compacting them back in as "put"s
            # would resurrect them across a restart
            return {pid: info for pid, info in self.pgs.items()
                    if getattr(info, "state", None) != "REMOVED"}
        if table == "drained":
            return {nid: True for nid in self._drained}
        return None

    def _maybe_compact(self, table: str) -> None:
        """Online compaction: rewrite a log that outgrew its live state
        by FileStore.COMPACT_GROWTH_FACTOR (without this, logs only
        compact on restart and grow unboundedly in long-lived clusters)."""
        if not self._store.should_compact(table):
            return
        state = self._live_table(table)
        if state is not None:
            self._store.compact(table, state)

    def _persist_actor(self, a: ActorInfo) -> None:
        self._persist("actors", a.actor_id, a)

    def _recover(self) -> None:
        """Replay persisted tables (reference: gcs/gcs_init_data.h rebuilds
        GCS state from the store on restart). Nodes are NOT persisted —
        agents re-register on their next heartbeat ("unknown" reply) and
        re-confirm hosted actors + object locations."""
        t = self._store.load_all()
        self.kv = t.get("kv", {})
        self.actors = t.get("actors", {})
        for a in self.actors.values():
            if a.name and a.state != DEAD:
                self.named_actors[(a.namespace, a.name)] = a.actor_id
        self.jobs = t.get("jobs", {})
        self.submitted_jobs = t.get("submitted_jobs", {})
        for j in self.submitted_jobs.values():
            if j.get("status") in ("PENDING", "RUNNING"):
                # the watcher subprocess handle died with the old control
                # process; the job may still run but is no longer tracked
                j["status"] = "FAILED"
                j["error"] = "control service restarted; job untracked"
        self.pgs = t.get("pgs", {})
        self._drained = set(t.get("drained", {}))
        for table, state in t.items():
            self._store.compact(table, state)
        # Give agents a grace window to reconnect before declaring their
        # actors dead (they heartbeat every health_check_period_s).
        grace = self.config.health_check_period_s * \
            self.config.health_check_failure_threshold * 2
        self._recover_deadline = time.monotonic() + max(grace, 5.0)

    def _after_recovery_sweep(self) -> None:
        """One-shot: actors whose node never re-registered are dead."""
        self._recover_deadline = 0.0
        lost = [a for a in self.actors.values()
                if a.state in (ALIVE, PENDING, RESTARTING)
                and (a.node_id is None or a.node_id not in self.nodes
                     or not self.nodes[a.node_id].alive)]
        for a in lost:
            asyncio.ensure_future(self._on_actor_death(
                a, "node lost across control-service restart"))

    async def start(self, host: str = "127.0.0.1", port: int = 0):
        if self._store is not None:
            self._recover()
        self.addr = await self.server.start(host, port)
        self._health_task = asyncio.ensure_future(self._health_loop())
        # Cluster health plane (util/health.py): the head-side metrics
        # time-series store + SLO burn-rate evaluation loop. Gated by
        # RAY_TPU_HEALTH / Config.health_enabled; report_metrics feeds
        # the store from the same pushes merge_remote keeps.
        from ray_tpu.util import health as _health
        self._healthplane_task = None
        if _health.enabled() and self.config.health_enabled:
            _health.activate(self.config)
            self._healthplane_task = asyncio.ensure_future(
                _health.head_loop(self.config))
        from ray_tpu.util import metrics as _m
        self._collector = self._render_metrics
        _m.register_collector(self._collector)
        if self.config.metrics_port >= 0:
            self.metrics_addr = await _m.acquire_shared_server(
                host, self.config.metrics_port)
            self._metrics_held = True
        return self.addr

    async def stop(self):
        if self._health_task:
            self._health_task.cancel()
        if getattr(self, "_healthplane_task", None) is not None:
            self._healthplane_task.cancel()
            self._healthplane_task = None
            from ray_tpu.util import health as _health
            _health.deactivate()   # a later cluster in this process
            # must not inherit this one's series or alert state
        from ray_tpu.util import metrics as _m
        if getattr(self, "_collector", None) is not None:
            _m.unregister_collector(self._collector)
        if getattr(self, "_metrics_held", False):
            self._metrics_held = False
            await _m.release_shared_server()
        await self.server.stop()
        await self.pool.close()
        if self._store is not None:
            self._store.close()

    def _render_metrics(self) -> str:
        """Cluster-level gauges (reference: gcs metrics in
        stats/metric_defs.h, surfaced on the dashboard)."""
        from ray_tpu.util.metrics import _fmt_labels, _labels_key
        out = []
        alive = sum(1 for n in self.nodes.values() if n.alive)
        out.append(f"ray_tpu_cluster_nodes_alive {alive}")
        out.append(f"ray_tpu_cluster_nodes_total {len(self.nodes)}")
        by_state: Dict[str, int] = {}
        for a in self.actors.values():
            by_state[a.state] = by_state.get(a.state, 0) + 1
        for st, n in by_state.items():
            lbl = _fmt_labels(_labels_key({"state": st}))
            out.append(f"ray_tpu_cluster_actors{lbl} {n}")
        out.append(f"ray_tpu_cluster_placement_groups {len(self.pgs)}")
        running = sum(1 for j in self.jobs.values()
                      if j.get("state") == "RUNNING")
        out.append(f"ray_tpu_cluster_jobs_running {running}")
        return "\n".join(out)

    async def ping(self):
        return "pong"

    # --- nodes / health ----------------------------------------------------

    async def register_node(self, node_id: NodeID, addr, resources_total,
                            labels=None):
        if node_id in self._drained:
            # deliberately removed; a re-register (e.g. rejoin after a
            # control restart) must not resurrect it
            return {"ok": False, "drained": True}
        self.nodes[node_id] = NodeInfo(
            node_id=node_id, addr=tuple(addr),
            resources_total=dict(resources_total),
            resources_available=dict(resources_total),
            labels=dict(labels or {}))
        self._bump_view()
        await self.pubsub.publish(
            "nodes", {"event": "node_added", "node_id": node_id,
                      "addr": tuple(addr)})
        return {"ok": True}

    async def heartbeat(self, node_id: NodeID, resources_available=None,
                        version: int = 0, pending_demand=None,
                        known_view: int = -1):
        """Liveness + resource-view sync in one beat (reference splits these
        across GcsHealthCheckManager and ray_syncer; one RPC suffices at
        TPU-pod node counts). The reply carries the cluster resource view
        (for local spillback decisions) ONLY when the agent's copy is
        stale: a naive view-per-beat reply is O(nodes^2)/s cluster-wide
        and measurably collapses the control core near 1,000 nodes
        (SCALE_BENCH_STRETCH.json) — the reference's ray_syncer exists
        for the same reason."""
        if node_id in self._drained:
            # covers the restart case too: the node isn't in self.nodes
            # (nodes aren't persisted) but the drain intent is — reply
            # "drained", not "unknown", so the agent stands down instead
            # of retrying _rejoin_head every period
            return {"ok": False, "drained": True}
        n = self.nodes.get(node_id)
        if n is None:
            return {"ok": False, "unknown": True}
        if n.drained:
            # Deliberately removed (scale-down / remove_node): a late
            # heartbeat from the dying process must not resurrect it.
            return {"ok": False, "drained": True}
        n.last_heartbeat = time.monotonic()
        if not n.alive:
            n.alive = True  # node came back before we GC'd it
            self._bump_view()
        if resources_available is not None:
            if resources_available != n.resources_available:
                n.resources_available = dict(resources_available)
                self._bump_view()
            n.version = version
        # pending_demand feeds the autoscaler via get_nodes, NOT _view():
        # no bump — it would only churn the snapshot cache.
        n.pending_demand = list(pending_demand or [])
        # Gate on the SNAPSHOT's version (what agents can actually hold),
        # not the live counter: under churn the live counter always leads
        # the throttled snapshot, and gating on it would re-ship the same
        # blob to every agent every beat — the O(nodes^2)/s this exists
        # to kill.
        ver, blob = self._view_snapshot()
        reply = {"ok": True, "view_version": ver}
        if known_view != ver:
            reply["view_blob"] = blob
        return reply

    def _bump_view(self) -> None:
        self._view_version += 1

    def _view_snapshot(self):
        """(version, pickled view), rebuilt at most every
        view_snapshot_interval_s: under churn every beat would otherwise
        rebuild + re-pickle an O(nodes) view per node per second. Agents
        tolerate sub-second staleness by design (they already act on
        views one heartbeat period old)."""
        import pickle
        ver, t, blob = self._view_blob_cache
        now = time.monotonic()
        if blob is None or (
                ver != self._view_version and
                now - t >= self.config.view_snapshot_interval_s):
            ver = self._view_version
            blob = pickle.dumps(self._view(), protocol=5)
            self._view_blob_cache = (ver, now, blob)
        return self._view_blob_cache[0], self._view_blob_cache[2]

    def _view(self):
        return {
            n.node_id: {
                "addr": n.addr, "alive": n.alive,
                "total": n.resources_total,
                "available": n.resources_available,
                "labels": n.labels,
            } for n in self.nodes.values() if n.alive
        }

    async def cluster_view(self):
        return self._view()

    async def get_nodes(self):
        return [
            {"node_id": n.node_id, "addr": n.addr, "alive": n.alive,
             "resources_total": n.resources_total,
             "resources_available": n.resources_available,
             "pending_demand": n.pending_demand,
             "labels": n.labels}
            for n in self.nodes.values()
        ]

    async def drain_node(self, node_id: NodeID):
        n = self.nodes.get(node_id)
        if n is not None:
            n.drained = True
        self._drained.add(node_id)
        # drain intent must survive a control restart, or the dying
        # node's agent would rejoin as a fresh healthy node
        self._persist("drained", node_id, True)
        await self._mark_node_dead(node_id, "drained")
        return {"ok": True}

    async def _health_loop(self):
        period = self.config.health_check_period_s
        threshold = period * self.config.health_check_failure_threshold
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            if self._recover_deadline and now > self._recover_deadline:
                self._after_recovery_sweep()
            for n in list(self.nodes.values()):
                if n.alive and now - n.last_heartbeat > threshold:
                    await self._mark_node_dead(n.node_id, "heartbeat timeout")
            if self._store is not None:
                self._store.flush()   # bound the fsync-batching window

    async def _mark_node_dead(self, node_id: NodeID, reason: str):
        n = self.nodes.get(node_id)
        if n is None or not n.alive:
            return
        n.alive = False
        self._bump_view()
        await self.pubsub.publish(
            "nodes", {"event": "node_dead", "node_id": node_id,
                      "reason": reason})
        # Objects on the dead node are gone.
        for oid, locs in list(self.object_locations.items()):
            locs.pop(node_id, None)
            if not locs:
                del self.object_locations[oid]
        # Actors hosted there die (and maybe restart).
        for a in list(self.actors.values()):
            if a.node_id == node_id and a.state in (ALIVE, PENDING,
                                                    RESTARTING):
                await self._on_actor_death(a, f"node {node_id} died: {reason}")

    # --- kv ----------------------------------------------------------------

    # runtime_env package blobs (__rtpkg:*) are capped: without
    # eviction, every distinct working_dir version ever submitted lives
    # in head memory forever. LRU by insertion order (dict order, with
    # re-put moving a hit to the back); agents cache extractions
    # locally, and a driver's publish re-checks existence and
    # re-uploads an evicted package before use.
    PKG_KV_CAP_BYTES = 1024 * 1024 * 1024

    async def kv_put(self, key: str, value: bytes, overwrite: bool = True):
        if not overwrite and key in self.kv:
            if key.startswith("__rtpkg:"):
                self.kv[key] = self.kv.pop(key)    # LRU touch
            return {"ok": False, "exists": True}
        self.kv[key] = value
        self._persist("kv", key, value)
        if key.startswith("__rtpkg:"):
            pkgs = [(k, len(v)) for k, v in self.kv.items()
                    if k.startswith("__rtpkg:")]
            total = sum(n for _, n in pkgs)
            for k, n in pkgs:
                if total <= self.PKG_KV_CAP_BYTES or k == key:
                    break
                del self.kv[k]
                self._persist_del("kv", k)
                total -= n
        return {"ok": True}

    async def kv_get(self, key: str):
        return self.kv.get(key)

    async def kv_del(self, key: str):
        deleted = self.kv.pop(key, None) is not None
        if deleted:
            self._persist_del("kv", key)
        return {"deleted": deleted}

    async def kv_keys(self, prefix: str = ""):
        return [k for k in self.kv if k.startswith(prefix)]

    # --- actors ------------------------------------------------------------

    async def register_actor(self, actor_id: ActorID, name, class_name,
                             resources, max_restarts: int,
                             creation_spec: bytes, namespace: str = "default",
                             scheduling: Optional[dict] = None,
                             pg: Optional[tuple] = None,
                             max_concurrency: int = 1,
                             runtime_env: Optional[dict] = None):
        if name:
            key = (namespace, name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing and existing.state != DEAD:
                    return {"ok": False,
                            "error": f"actor name {name!r} taken"}
            self.named_actors[key] = actor_id
        info = ActorInfo(actor_id=actor_id, name=name, class_name=class_name,
                         resources=dict(resources),
                         max_restarts=max_restarts,
                         creation_spec=creation_spec, namespace=namespace,
                         pg=tuple(pg) if pg else None,
                         max_concurrency=int(max_concurrency),
                         runtime_env=runtime_env)
        self.actors[actor_id] = info
        node = await self._schedule_actor(info, scheduling or {})
        if node is None:
            info.state = DEAD
            info.death_cause = "no feasible node"
            self._persist_actor(info)
            return {"ok": False, "error": "no feasible node for actor"}
        self._persist_actor(info)
        return {"ok": True, "node_id": node.node_id}

    async def _schedule_actor(self, info: ActorInfo,
                              scheduling: dict) -> Optional[NodeInfo]:
        """Pick a node and ask its agent to start the actor (reference:
        gcs/actor/gcs_actor_scheduler.h — lease-based; here the agent owns
        its own worker pool so one RPC does lease+spawn)."""
        if info.pg is not None:
            # PG-constrained: the bundle's node is the only candidate.
            pg_info = self.pgs.get(info.pg[0])
            idx = info.pg[1]
            if pg_info is None or pg_info.state != "CREATED" or \
                    idx >= len(pg_info.bundle_nodes):
                return None
            node = self.nodes.get(pg_info.bundle_nodes[idx])
            if node is None or not node.alive:
                return None
        else:
            node = self._pick_node(info.resources, scheduling)
        if node is None:
            return None
        info.node_id = node.node_id
        asyncio.ensure_future(self._request_start(info, node))
        return node

    def _pick_node(self, resources: Dict[str, float],
                   scheduling: dict) -> Optional[NodeInfo]:
        cands = [n for n in self.nodes.values() if n.alive]
        nid = scheduling.get("node_id")
        if nid is not None:
            cands = [n for n in cands if n.node_id == nid]
        labels = scheduling.get("labels") or {}
        for k, v in labels.items():
            cands = [n for n in cands if n.labels.get(k) == v]
        feasible = [n for n in cands
                    if _fits(resources, n.resources_available)]
        if not feasible:
            # fall back to total-capacity feasibility (queue on the agent)
            feasible = [n for n in cands
                        if _fits(resources, n.resources_total)]
        if not feasible:
            return None
        # most-available-first spread for actors
        return max(feasible, key=lambda n: sum(
            n.resources_available.get(k, 0) - v
            for k, v in resources.items()) if resources else
            sum(n.resources_available.values()))

    async def _request_start(self, info: ActorInfo, node: NodeInfo):
        try:
            resources = dict(info.resources)
            if info.pg is not None:
                # agent-side pseudo-keys select the bundle's reservation
                resources["_pg"] = info.pg[0]
                resources["_pg_bundle"] = info.pg[1]
            r = await self.pool.call(
                node.addr, "start_actor",
                timeout=self.config.actor_init_timeout_s + 30.0,
                actor_id=info.actor_id, creation_spec=info.creation_spec,
                resources=resources, runtime_env=info.runtime_env)
            if not r.get("ok"):
                await self._on_actor_death(
                    info, r.get("error", "agent failed to start actor"))
        except Exception as e:  # noqa: BLE001
            await self._on_actor_death(info, f"start_actor rpc failed: {e}")

    async def actor_started(self, actor_id: ActorID, addr, node_id: NodeID):
        a = self.actors.get(actor_id)
        if a is None:
            return {"ok": False}
        if a.state == DEAD:
            # e.g. killed while the kill RPC to its agent was lost, then
            # the agent re-reports it after a control restart: the table
            # is authoritative — tell the agent to reap the worker.
            return {"ok": False, "dead": True}
        a.state = ALIVE
        a.addr = tuple(addr)
        a.node_id = node_id
        self._persist_actor(a)
        await self.pubsub.publish(
            f"actor:{actor_id.hex()}",
            {"event": "alive", "addr": a.addr})
        await self.pubsub.publish(
            "actors", {"event": "alive", "actor_id": actor_id})
        return {"ok": True}

    async def actor_failed(self, actor_id: ActorID, reason: str):
        a = self.actors.get(actor_id)
        if a is None:
            return {"ok": False}
        await self._on_actor_death(a, reason)
        return {"ok": True}

    async def _on_actor_death(self, a: ActorInfo, reason: str):
        if a.state == DEAD:
            return
        if a.num_restarts < a.max_restarts:
            a.num_restarts += 1
            a.state = RESTARTING
            a.addr = None
            self._persist_actor(a)
            await self.pubsub.publish(
                f"actor:{a.actor_id.hex()}",
                {"event": "restarting", "restarts": a.num_restarts})
            node = await self._schedule_actor(a, {})
            if node is not None:
                return
            reason = f"{reason}; restart found no feasible node"
        a.state = DEAD
        a.death_cause = reason
        a.addr = None
        self._persist_actor(a)
        await self.pubsub.publish(
            f"actor:{a.actor_id.hex()}", {"event": "dead", "reason": reason})
        await self.pubsub.publish(
            "actors", {"event": "dead", "actor_id": a.actor_id,
                       "reason": reason})

    async def kill_actor(self, actor_id: ActorID, no_restart: bool = True):
        a = self.actors.get(actor_id)
        if a is None:
            return {"ok": False}
        if no_restart:
            a.max_restarts = a.num_restarts  # exhaust budget
        node = self.nodes.get(a.node_id) if a.node_id else None
        if a.addr is not None and node is not None:
            try:
                await self.pool.call(node.addr, "kill_actor_worker",
                                     actor_id=actor_id)
            except Exception:
                pass
        await self._on_actor_death(a, "killed via kill_actor")
        return {"ok": True}

    async def get_actor(self, actor_id: ActorID):
        a = self.actors.get(actor_id)
        if a is None:
            return None
        return {"actor_id": a.actor_id, "state": a.state, "addr": a.addr,
                "name": a.name, "class_name": a.class_name,
                "node_id": a.node_id, "num_restarts": a.num_restarts,
                "death_cause": a.death_cause}

    async def wait_actor_alive(self, actor_id: ActorID,
                               wait_timeout: float = 60.0):
        """Park until the actor is ALIVE (or DEAD). Used by handles to
        resolve the actor's direct-call address."""
        deadline = time.monotonic() + wait_timeout
        cursor = 0
        chan = f"actor:{actor_id.hex()}"
        while True:
            a = self.actors.get(actor_id)
            if a is None:
                return {"state": "UNKNOWN"}
            if a.state == ALIVE:
                return {"state": ALIVE, "addr": a.addr,
                        "num_restarts": a.num_restarts,
                        "max_concurrency": a.max_concurrency}
            if a.state == DEAD:
                return {"state": DEAD, "reason": a.death_cause}
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return {"state": a.state, "timeout": True}
            cursor, _ = await self.pubsub.poll(
                chan, cursor, timeout=min(remaining, 5.0))

    async def get_named_actor(self, name: str, namespace: str = "default"):
        aid = self.named_actors.get((namespace, name))
        if aid is None:
            return None
        return await self.get_actor(aid)

    async def list_actors(self):
        return [await self.get_actor(aid) for aid in list(self.actors)]

    # --- jobs --------------------------------------------------------------

    async def register_job(self, job_id: JobID, metadata=None):
        self.jobs[job_id] = {"job_id": job_id, "state": "RUNNING",
                             "start_time": time.time(),
                             "metadata": metadata or {}}
        self._persist("jobs", job_id, self.jobs[job_id])
        return {"ok": True}

    async def finish_job(self, job_id: JobID, state: str = "SUCCEEDED"):
        j = self.jobs.get(job_id)
        if j:
            j["state"] = state
            j["end_time"] = time.time()
            self._persist("jobs", job_id, j)
        return {"ok": True}

    async def list_jobs(self):
        return list(self.jobs.values())

    # --- job submission (entrypoint jobs) -----------------------------------
    # The head runs submitted entrypoints as driver subprocesses, tracks
    # their lifecycle, and captures logs (reference:
    # dashboard/modules/job/job_manager.py:62 JobManager.submit_job —
    # REST replaced by the same RPC plane everything else uses).

    async def submit_job(self, entrypoint: str, submission_id=None,
                         runtime_env: Optional[dict] = None):
        import os
        import tempfile
        import uuid as _uuid

        from ray_tpu.runtime.runtime_env import apply_to_env
        sub_id = submission_id or f"rtjob-{_uuid.uuid4().hex[:10]}"
        if sub_id in self.submitted_jobs and \
                self.submitted_jobs[sub_id]["status"] in (
                    "PENDING", "RUNNING"):
            return {"ok": False, "error": f"job {sub_id!r} already active"}
        log_dir = self.config.log_dir or tempfile.gettempdir()
        os.makedirs(log_dir, exist_ok=True)
        log_path = os.path.join(log_dir, f"job-{sub_id}.log")
        env = apply_to_env(runtime_env, dict(os.environ))
        # Entrypoints can import what the head can (ray_tpu itself,
        # notably) — python puts the SCRIPT's dir on sys.path, not cwd.
        import sys
        entries = [p if p else os.getcwd() for p in sys.path]
        prev = env.get("PYTHONPATH", "")  # user py_modules stay first
        env["PYTHONPATH"] = os.pathsep.join(
            dict.fromkeys(([prev] if prev else []) + entries))
        env["RAY_TPU_ADDRESS"] = f"{self.addr[0]}:{self.addr[1]}"
        env["RAY_TPU_SUBMISSION_ID"] = sub_id
        cwd = (runtime_env or {}).get("working_dir")
        logf = open(log_path, "ab", buffering=0)
        try:
            proc = await asyncio.create_subprocess_shell(
                entrypoint, env=env, cwd=cwd or None,
                stdout=logf, stderr=logf,
                start_new_session=True)
        except OSError as e:
            logf.close()
            return {"ok": False, "error": f"spawn failed: {e}"}
        finally:
            logf.close()
        job = {"submission_id": sub_id, "entrypoint": entrypoint,
               "status": "RUNNING", "pid": proc.pid,
               "log_path": log_path, "start_time": time.time()}
        self.submitted_jobs[sub_id] = job
        self._persist("submitted_jobs", sub_id, job)
        asyncio.ensure_future(self._watch_job(job, proc))
        return {"ok": True, "submission_id": sub_id}

    async def _watch_job(self, job: dict, proc):
        rc = await proc.wait()
        # The watcher is the single writer of terminal states: a stop
        # request only marks intent, so a job that happened to exit 0
        # before the signal landed still reports SUCCEEDED.
        if rc == 0:
            job["status"] = "SUCCEEDED"
        elif job.get("stop_requested"):
            job["status"] = "STOPPED"
        else:
            job["status"] = "FAILED"
        job["returncode"] = rc
        job["end_time"] = time.time()
        self._persist("submitted_jobs", job["submission_id"], job)

    async def get_submitted_job(self, submission_id: str):
        return self.submitted_jobs.get(submission_id)

    async def list_submitted_jobs(self):
        return list(self.submitted_jobs.values())

    async def stop_submitted_job(self, submission_id: str):
        import signal
        job = self.submitted_jobs.get(submission_id)
        if job is None:
            return {"ok": False, "error": "no such job"}
        if job["status"] in ("SUCCEEDED", "FAILED", "STOPPED"):
            return {"ok": True, "status": job["status"]}
        job["stop_requested"] = True
        try:
            import os
            os.killpg(job["pid"], signal.SIGTERM)
        except (OSError, ProcessLookupError):
            pass
        return {"ok": True, "status": "STOPPING"}

    async def submitted_job_logs(self, submission_id: str,
                                 tail_bytes: int = 1 << 20):
        job = self.submitted_jobs.get(submission_id)
        if job is None:
            return None
        try:
            with open(job["log_path"], "rb") as f:
                f.seek(0, 2)
                size = f.tell()
                f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except OSError:
            return ""

    # --- placement groups ---------------------------------------------------

    async def create_pg(self, pg_id: PlacementGroupID, bundles, strategy,
                        name=None):
        """Two-phase gang reserve (reference:
        gcs/gcs_placement_group_scheduler.h Prepare/Commit protocol;
        bundle policies raylet/scheduling/policy/bundle_scheduling_policy.h).
        """
        info = PlacementGroupInfo(
            pg_id=pg_id, bundles=[dict(b) for b in bundles],
            strategy=strategy, name=name,
            bundle_nodes=[None] * len(bundles))
        self.pgs[pg_id] = info
        # Stay PENDING while the cluster is busy: resource views refresh on
        # heartbeats, so placement that is infeasible *now* may fit in a
        # moment (reference: PGs queue in GcsPlacementGroupManager). Even
        # exceeding TOTAL cluster capacity is only terminal after the
        # infeasibility window: a PENDING gang's bundles are autoscaler
        # demand (autoscaler.py _collect_demand), so capacity may be on
        # its way — this is SURVEY section 7's "slice reservation races
        # autoscaling" hard part, resolved by making the reservation
        # patient instead of fail-fast. A prepare-phase race (two PGs
        # placed on the same stale view) also retries within the
        # deadline. Concurrent remove_pg aborts the wait.
        deadline = time.monotonic() + max(
            30.0, self.config.infeasible_wait_window_s)
        while True:
            if info.state == "REMOVED":
                return {"ok": False, "error": "placement group removed"}
            placement = self._place_bundles(info)
            if placement is None:
                if time.monotonic() >= deadline:
                    info.state = "INFEASIBLE"
                    reason = "exceeds total cluster capacity" \
                        if not self._feasible_by_total(info) \
                        else "timed out pending"
                    return {"ok": False,
                            "error": f"placement group {reason}"}
                await asyncio.sleep(0.25)
                continue
            # Phase 1: prepare on every node (all-or-nothing).
            prepared = []
            ok = True
            for idx, node in enumerate(placement):
                try:
                    r = await self.pool.call(
                        node.addr, "prepare_bundle", pg_id=pg_id,
                        bundle_index=idx, resources=info.bundles[idx])
                    if r.get("ok"):
                        prepared.append((idx, node))
                    else:
                        ok = False
                        break
                except Exception:
                    ok = False
                    break
            if ok and info.state == "REMOVED":
                ok = False  # removed while preparing: roll back
            if not ok:
                for idx, node in prepared:
                    try:
                        await self.pool.call(node.addr, "return_bundle",
                                             pg_id=pg_id, bundle_index=idx)
                    except Exception:
                        pass
                if info.state == "REMOVED":
                    return {"ok": False, "error": "placement group removed"}
                if time.monotonic() >= deadline:
                    info.state = "INFEASIBLE"
                    return {"ok": False,
                            "error": "bundle reservation failed"}
                await asyncio.sleep(0.25)
                continue
            # Phase 2: commit.
            for idx, node in prepared:
                await self.pool.call(node.addr, "commit_bundle", pg_id=pg_id,
                                     bundle_index=idx)
                info.bundle_nodes[idx] = node.node_id
            info.state = "CREATED"
            self._persist("pgs", pg_id, info)
            await self.pubsub.publish("pgs",
                                      {"event": "created", "pg_id": pg_id})
            return {"ok": True, "bundle_nodes": info.bundle_nodes}

    def _feasible_by_total(self, info: PlacementGroupInfo) -> bool:
        """Could the bundles EVER fit, given total capacities?"""
        saved = [dict(n.resources_available) for n in self.nodes.values()]
        nodes = list(self.nodes.values())
        try:
            for n in nodes:
                n.resources_available = dict(n.resources_total)
            return self._place_bundles(info) is not None
        finally:
            for n, s in zip(nodes, saved):
                n.resources_available = s

    def _place_bundles(self, info: PlacementGroupInfo
                       ) -> Optional[List[NodeInfo]]:
        alive = [n for n in self.nodes.values() if n.alive]
        if not alive:
            return None
        avail = {n.node_id: dict(n.resources_available) for n in alive}
        strategy = info.strategy.upper()
        out: List[NodeInfo] = []

        def take(node: NodeInfo, bundle) -> bool:
            a = avail[node.node_id]
            if not _fits(bundle, a):
                return False
            for k, v in bundle.items():
                a[k] = a.get(k, 0) - v
            return True

        if strategy in ("PACK", "STRICT_PACK"):
            order = sorted(alive, key=lambda n: -sum(
                n.resources_available.values()))
            for b in info.bundles:
                placed = False
                pool = out[:1] if (strategy == "STRICT_PACK" and out) else order
                for n in pool:
                    if take(n, b):
                        out.append(n)
                        placed = True
                        break
                if not placed:
                    return None
            if strategy == "STRICT_PACK" and len({n.node_id for n in out}) > 1:
                return None
            return out
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            used: set = set()
            for b in info.bundles:
                cands = sorted(alive, key=lambda n: (
                    n.node_id in used, -sum(avail[n.node_id].values())))
                placed = False
                for n in cands:
                    if strategy == "STRICT_SPREAD" and n.node_id in used:
                        continue
                    if take(n, b):
                        out.append(n)
                        used.add(n.node_id)
                        placed = True
                        break
                if not placed:
                    return None
            return out
        raise ValueError(f"unknown strategy {info.strategy}")

    async def remove_pg(self, pg_id: PlacementGroupID):
        info = self.pgs.get(pg_id)
        if info is None:
            return {"ok": False}
        for idx, nid in enumerate(info.bundle_nodes):
            if nid is None:
                continue
            node = self.nodes.get(nid)
            if node is None:
                continue
            try:
                await self.pool.call(node.addr, "return_bundle",
                                     pg_id=pg_id, bundle_index=idx)
            except Exception:
                pass
        info.state = "REMOVED"
        self._persist_del("pgs", pg_id)
        return {"ok": True}

    async def get_pg(self, pg_id: PlacementGroupID):
        info = self.pgs.get(pg_id)
        if info is None:
            return None
        return {"pg_id": info.pg_id, "state": info.state,
                "bundles": info.bundles, "strategy": info.strategy,
                "bundle_nodes": info.bundle_nodes, "name": info.name}

    async def list_pgs(self):
        return [await self.get_pg(p) for p in list(self.pgs)]

    # --- object directory ----------------------------------------------------

    async def add_object_location(self, oid: ObjectID, node_id: NodeID,
                                  size: int):
        self.object_locations.setdefault(oid, {})[node_id] = size
        return {"ok": True}

    async def report_metrics(self, source: str, text: str) -> dict:
        """Workers push labelled metric snapshots here (util/metrics.py
        push_loop); merged into this process's /metrics endpoint so the
        head serves cluster-wide series — and, when the health plane is
        on, ingested into the head time-series store so the same push
        builds queryable history (util/timeseries.py)."""
        from ray_tpu.util import metrics as _m
        _m.merge_remote(str(source), str(text))
        from ray_tpu.util import health as _health
        try:
            _health.ingest_push(str(source), str(text))
        except Exception:  # noqa: BLE001 — history must not fail pushes
            pass
        return {"ok": True}

    async def health_state(self) -> dict:
        """The health plane's machine-readable snapshot (objectives,
        burn rates, active alerts, sentinels) — the /health endpoint,
        `ray-tpu health`, and the dashboard all serve this; its
        ``burn_advice`` map is the input contract for SLO-driven
        replica autoscaling (ROADMAP item 3)."""
        from ray_tpu.util import health as _health
        return _health.local_state()

    async def query_series(self, name: str, since_s: float = 900.0,
                           labels: Optional[dict] = None) -> dict:
        """Windowed points for one stored metric series (`ray-tpu
        metrics <name> --since 15m` and the dashboard sparklines)."""
        from ray_tpu.util import health as _health
        return _health.local_query(str(name), float(since_s),
                                   labels if isinstance(labels, dict)
                                   else None)

    # --- cluster-wide profiling -------------------------------------------

    def _resolve_profile_actor(self, target: str):
        """An actor by name (any namespace) or id-hex prefix. Returns
        (actor_or_None, error_or_None) — ambiguity is an error, never a
        silent first-match (profiling the wrong actor misattributes a
        perf problem)."""
        named = [self.actors.get(aid)
                 for (_ns, name), aid in self.named_actors.items()
                 if name == target]
        named = [a for a in named if a is not None]
        if len(named) > 1:
            return None, (f"actor name {target!r} exists in multiple "
                          "namespaces — profile by actor id instead")
        if named:
            return named[0], None
        t = target.lower()
        hits = [a for aid, a in self.actors.items()
                if t and aid.hex().startswith(t)]
        if len(hits) > 1:
            ids = ", ".join(a.actor_id.hex()[:12] for a in hits[:4])
            return None, (f"actor id prefix {target!r} is ambiguous "
                          f"({ids}) — use a longer prefix")
        return (hits[0] if hits else None), None

    async def profile_target(self, target, op: str = "profile",
                             duration_s: float = 2.0, hz: int = 100):
        """Profile any live worker/actor from the driver (reference
        capability: the dashboard's py-spy stack/flamegraph buttons,
        dashboard/modules/reporter/reporter_agent.py). ``target`` is an
        actor name, an actor-id hex prefix, or a worker/agent pid;
        ``op`` is "profile" (sampled folded stacks, util/profiling.py)
        or "dump_stacks" (one-shot thread dump). The request routes
        head -> hosting worker directly for actors, head -> every agent
        for pids."""
        import math
        target = str(target)
        if op not in ("profile", "dump_stacks"):
            # op becomes the worker RPC method name — never let the
            # profiling entry point invoke arbitrary handlers
            return {"error": f"unknown profile op {op!r}"}
        duration_s = float(duration_s)
        if not math.isfinite(duration_s):
            return {"error": f"bad duration {duration_s!r}"}
        duration_s = min(max(duration_s, 0.0), 120.0)
        a, amb_err = self._resolve_profile_actor(target)
        if amb_err is not None:
            return {"error": amb_err}
        if a is not None:
            if a.state != ALIVE or not a.addr:
                return {"error": f"actor {target!r} is {a.state}, "
                                 "not profilable"}
            kw = {} if op == "dump_stacks" else \
                {"duration_s": duration_s, "hz": hz}
            try:
                r = await self.pool.call(tuple(a.addr), op,
                                         timeout=duration_s + 30.0, **kw)
            except Exception as e:  # noqa: BLE001 — surface, don't crash
                return {"error": f"profile RPC to actor failed: {e}"}
            r["target"] = {
                "actor_id": a.actor_id.hex(), "name": a.name,
                "class_name": a.class_name,
                "node_id": a.node_id.hex() if a.node_id else None}
            return r
        try:
            pid = int(target)
        except ValueError:
            return {"error": f"no live actor named {target!r} (and not "
                             "a pid)"}

        # Concurrent fan-out to every agent: pids are per-host, so the
        # same number can exist on several nodes (containers restart
        # pids low) — an ambiguous match must error, not silently
        # profile whichever node answered first.
        async def probe(n):
            try:
                return n, await self.pool.call(
                    n.addr, "profile_worker", pid=pid, op=op,
                    duration_s=duration_s, hz=hz,
                    timeout=duration_s + 30.0)
            except Exception:
                return n, {"found": False}

        alive = [n for n in self.nodes.values() if n.alive]
        results = await asyncio.gather(*[probe(n) for n in alive])
        hits = [(n, r) for n, r in results if r.get("found")]
        if not hits:
            return {"error": f"no live worker or agent with pid {pid}"}
        if len(hits) > 1:
            nodes = ", ".join(n.node_id.hex()[:12] for n, _ in hits)
            return {"error": f"pid {pid} exists on multiple nodes "
                             f"({nodes}) — profile by actor id instead"}
        n, r = hits[0]
        r.pop("found", None)
        r.setdefault("target", {"pid": pid, "node_id": n.node_id.hex()})
        return r

    async def autopsy(self, stall_timeout_s: float = 0.0) -> dict:
        """One-command postmortem: fan ``node_forensics`` out to every
        alive agent (each agent pulls stacks + collective ledgers +
        engine state from its own workers), run the cross-rank ledger
        audit over whatever came back, and write one atomic
        ``postmortem-*.json`` bundle on the head. Nodes that fail to
        answer are recorded as error rows — on a hung cluster the
        silence IS the finding. Returns the bundle path plus the
        audit's findings so the CLI can print a diagnosis without
        re-opening the file."""
        from ray_tpu.util import events as _ev
        from ray_tpu.util import forensics

        async def pull(n):
            try:
                return n.node_id.hex(), await self.pool.call(
                    n.addr, "node_forensics", timeout=30.0)
            except Exception as e:  # noqa: BLE001 — evidence, not fatal
                return n.node_id.hex(), \
                    {"error": f"{type(e).__name__}: {e}"}

        alive = [n for n in list(self.nodes.values()) if n.alive]
        results = await asyncio.gather(*[pull(n) for n in alive])
        nodes = {nid: dump for nid, dump in results}

        # Cross-rank audit over every worker dump that carries a rank
        # (train workers stamp one; bare task workers stay rank -1 and
        # only contribute stacks).
        ledgers: Dict[int, dict] = {}
        for dump in nodes.values():
            if not isinstance(dump, dict):
                continue
            for w in (dump.get("workers") or {}).values():
                r = w.get("rank", -1) if isinstance(w, dict) else -1
                snap = w.get("ledger") if isinstance(w, dict) else None
                if isinstance(r, int) and r >= 0 \
                        and isinstance(snap, dict) and "entries" in snap:
                    ledgers[r] = snap
        tmo = float(stall_timeout_s) if stall_timeout_s else \
            float(self.config.forensics_stall_timeout_s)
        findings = forensics.audit(ledgers, stall_timeout_s=tmo) \
            if ledgers else []
        payload = {
            "trigger": "autopsy",
            "findings": [dict(f) for f in findings],
            "nodes": nodes,
            "head_events": _ev.dump()[-512:],
        }
        try:
            path = forensics.write_bundle(payload)
        except Exception as e:  # noqa: BLE001 — diagnosis beats bundle
            path = None
            payload["bundle_error"] = f"{type(e).__name__}: {e}"
        _ev.record("forensics", "bundle", trigger="autopsy", path=path,
                   findings=len(findings))
        return {"path": path, "findings": payload["findings"],
                "nodes": sorted(nodes), "ranks": sorted(ledgers)}

    async def report_node_events(self, events: list) -> dict:
        """A stopping node archives its span buffer here so the cluster
        timeline outlives it (reference: task events live in the GCS,
        gcs/gcs_task_manager.h)."""
        self._archived_events.extend(events)
        return {"ok": True, "count": len(events)}

    async def _clock_offset(self, addr) -> Optional[Tuple[float, float]]:
        """Estimate a node's wall-clock offset vs this head: bracket a
        clock_probe RPC with local clock reads, offset = remote -
        midpoint; of 3 probes the one with the smallest RTT wins (its
        midpoint assumption — symmetric network halves — is tightest).
        Returns (offset_s, rtt_s), or None when the agent predates the
        probe RPC / is unreachable."""
        best = None
        try:
            for _ in range(3):
                t0 = time.time()
                r = await self.pool.call(addr, "clock_probe", timeout=5.0)
                t1 = time.time()
                rtt = t1 - t0
                off = float(r["t"]) - (t0 + t1) / 2.0
                if best is None or rtt < best[1]:
                    best = (off, rtt)
        except Exception:
            return best
        return best

    async def collect_timeline(self) -> dict:
        """Cluster-wide event/span collection: archived buffers from
        departed nodes + a fan-out to every alive agent (reference
        surface: ray.timeline via gcs_task_manager). Alongside the
        events, each alive node's wall-clock offset vs this head is
        estimated (ping-style midpoint over the same control-plane
        RPCs) and returned as ``clock_offsets`` — to_chrome subtracts
        them so merged cross-node lanes line up and collective flow
        arrows cannot point backwards."""
        async def pull(n):
            evs: list = []
            try:
                r = await self.pool.call(n.addr, "node_timeline",
                                         timeout=10.0)
                evs = r.get("events", [])
            except Exception:
                pass
            off = await self._clock_offset(n.addr)
            return n.node_id.hex(), evs, off

        results = await asyncio.gather(*[
            pull(n) for n in list(self.nodes.values()) if n.alive])
        out = self._archived_events.dump()
        offsets: Dict[str, float] = {}
        rtts: Dict[str, float] = {}
        for nid, evs, off in results:
            out.extend(evs)
            if off is not None:
                offsets[nid], rtts[nid] = off
        return {"events": out, "clock_offsets": offsets,
                "clock_rtts": rtts}

    async def report_objects(self, node_id: NodeID, objects) -> dict:
        """Bulk object-directory refresh: an agent re-registering after a
        control-service restart re-publishes every sealed object it holds
        as [(oid, size), ...] in one RPC."""
        for oid, size in objects:
            self.object_locations.setdefault(oid, {})[node_id] = int(size)
        return {"ok": True, "count": len(objects)}

    async def remove_object_location(self, oid: ObjectID, node_id: NodeID):
        locs = self.object_locations.get(oid)
        if locs:
            locs.pop(node_id, None)
            if not locs:
                del self.object_locations[oid]
        return {"ok": True}

    async def get_object_locations(self, oid: ObjectID):
        locs = self.object_locations.get(oid, {})
        return [{"node_id": nid, "addr": self.nodes[nid].addr, "size": sz}
                for nid, sz in locs.items()
                if nid in self.nodes and self.nodes[nid].alive]

    # --- pubsub ---------------------------------------------------------------

    async def poll_events(self, channel: str, cursor: int = 0,
                          poll_timeout: float = 30.0):
        nxt, events = await self.pubsub.poll(channel, cursor, poll_timeout)
        return {"cursor": nxt, "events": events}


def _fits(demand: Dict[str, float], avail: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items())
